"""block_zone — blocking operations reachable from no-block entry points.

The serving plane has a handful of loops whose stall is a whole-plane
stall: the backplane frame reader (every frontend's requests serialize
through it), the HTTP connection handler, the micro-batch seal loop,
and the /metrics scrape probes. The invariant — re-fixed by hand in
PRs 3, 13, and 14 — is that no unbounded blocking operation (sleep,
subprocess, kube I/O, inline XLA compile, device sync, foreign waits)
may be reachable from them.

Each entry point declares its *intrinsic* operation categories (a
frame reader's own socket recv is its job, not a violation). An allow
comment on a call site prunes traversal through that edge — used where
a guard the analyzer cannot see (e.g. ``fast=True`` raising
``NeedsEvaluation``) makes a path unreachable; the mandatory reason
documents the guard.
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph, FuncInfo
from .core import Finding, Project, dotted

# (qualname, intrinsic categories, description)
ENTRY_POINTS = [
    ("gatekeeper_tpu/control/backplane.py::BackplaneEngine._read_loop",
     {"socket", "lock"},
     "backplane frame-reader inline path"),
    ("gatekeeper_tpu/control/webhook.py::FastHTTPServer"
     "._serve_connection",
     {"socket", "lock"},
     "HTTP accept/connection loop"),
    ("gatekeeper_tpu/control/webhook.py::MicroBatcher._loop",
     {"lock"},
     "micro-batch seal path"),
    ("gatekeeper_tpu/control/metrics.py::run_saturation_probes",
     {"lock"},
     "/metrics scrape-time saturation probes"),
    ("gatekeeper_tpu/control/adaptive.py::AdaptiveController._loop",
     {"lock", "wait"},
     "adaptive controller tick loop"),
]

REGISTER_PROBE = "register_saturation_probe"

_SOCKET_ATTRS = {"accept", "recv", "recv_into", "recvfrom", "sendall",
                 "sendmsg", "connect", "connect_ex"}
_SOCKETISH_RECV = {"send", "read", "readline", "makefile"}
_SOCKET_HINTS = ("sock", "conn", "rfile", "wfile", "listener")
_LOCK_HINTS = ("lock", "mutex", "sem", "cv", "cond")
_THREAD_HINTS = ("thread", "proc", "_t", "worker")
MAX_DEPTH = 12


def _classify_call(call: ast.Call) -> tuple:
    """(category, op label) for a blocking call, or (None, '')."""
    name = dotted(call.func)
    if not name:
        return None, ""
    low = name.lower()
    leaf = name.split(".")[-1]
    recv = ".".join(name.split(".")[:-1]).lower()
    if name == "sleep" or name.endswith("time.sleep"):
        return "sleep", name
    if low.startswith("subprocess.") or ".subprocess." in low:
        return "subprocess", name
    if leaf == "block_until_ready":
        return "device-sync", name
    if leaf == "compile" and not call.args and not call.keywords:
        return "xla-compile", name
    if ".kube." in f".{low}" or low.startswith("kube."):
        return "kube", name
    if leaf in _SOCKET_ATTRS:
        return "socket", name
    if leaf in _SOCKETISH_RECV and any(h in recv for h in _SOCKET_HINTS):
        return "socket", name
    if leaf == "acquire" and any(h in recv for h in _LOCK_HINTS):
        for kw in call.keywords:
            if kw.arg == "blocking" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is False:
                return None, ""
        if call.args and isinstance(call.args[0], ast.Constant) \
                and call.args[0].value is False:
            return None, ""
        return "lock", name
    if leaf == "wait":
        if any(h in recv for h in _LOCK_HINTS):
            return "lock", name
        return "wait", name
    if leaf == "join" and any(h in recv for h in _THREAD_HINTS):
        return "wait", name
    return None, ""


def _scan_function(project: Project, graph: CallGraph, entry_label: str,
                   intrinsic: set, fn: FuncInfo, chain: list,
                   visited: set, findings: list) -> None:
    if fn.qual in visited or len(chain) > MAX_DEPTH:
        return
    visited.add(fn.qual)
    sf = project.files[fn.path]
    nested: set = set()
    for sub in ast.walk(fn.node):
        if sub is not fn.node and isinstance(
                sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            for inner in ast.walk(sub):
                nested.add(inner)
    # `with <lock>:` blocks
    for sub in ast.walk(fn.node):
        if sub in nested or not isinstance(sub, (ast.With, ast.AsyncWith)):
            continue
        for item in sub.items:
            name = dotted(item.context_expr)
            if any(h in name.lower() for h in _LOCK_HINTS):
                if "lock" in intrinsic or sf.allowed(sub.lineno,
                                                     "block_zone"):
                    continue
                findings.append(Finding(
                    "block_zone", fn.path, sub.lineno,
                    f"{entry_label}->{_short(fn)}",
                    f"lock:{name}",
                    f"`with {name}` reachable from no-block entry "
                    f"{entry_label} (via {' -> '.join(chain)})"))
    for call in graph.calls_in(fn):
        cat, op = _classify_call(call)
        if cat is not None and cat not in intrinsic \
                and not sf.allowed(call.lineno, "block_zone"):
            findings.append(Finding(
                "block_zone", fn.path, call.lineno,
                f"{entry_label}->{_short(fn)}",
                f"{cat}:{op}",
                f"blocking op `{op}` ({cat}) reachable from no-block "
                f"entry {entry_label} (via {' -> '.join(chain)})"))
        callee = graph.resolve_call(fn, call)
        if callee is not None and not sf.allowed(call.lineno,
                                                 "block_zone"):
            target = graph.funcs[callee]
            _scan_function(project, graph, entry_label, intrinsic,
                           target, chain + [_short(target)], visited,
                           findings)


def _short(fn: FuncInfo) -> str:
    return f"{fn.cls}.{fn.name}" if fn.cls else fn.name


def _probe_entries(project: Project, graph: CallGraph):
    """Callables registered as saturation probes become entry points
    themselves: they run inline on every /metrics scrape."""
    for path, sf in project.files.items():
        for fn in graph.funcs.values():
            if fn.path != path:
                continue
            for call in graph.calls_in(fn):
                name = dotted(call.func)
                if not name.endswith(REGISTER_PROBE) or len(call.args) < 2:
                    continue
                arg = call.args[1]
                if isinstance(arg, ast.Lambda):
                    pseudo = FuncInfo(
                        f"{fn.qual}.<probe-lambda@{arg.lineno}>",
                        path, _LambdaShim(arg), fn.cls)
                    yield pseudo, f"probe@{_short(fn)}"
                elif isinstance(arg, ast.Name):
                    # nested def registered by name
                    for sub in ast.walk(fn.node):
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)) \
                                and sub.name == arg.id:
                            pseudo = FuncInfo(
                                f"{fn.qual}.{sub.name}", path, sub,
                                fn.cls)
                            yield pseudo, f"probe@{_short(fn)}"
                elif isinstance(arg, ast.Attribute):
                    q = graph.resolve_call(
                        fn, ast.Call(func=arg, args=[], keywords=[]))
                    if q is not None:
                        yield graph.funcs[q], f"probe@{_short(fn)}"


class _LambdaShim:
    """Duck-typed FunctionDef stand-in so calls_in/ast.walk work on a
    lambda body."""

    def __init__(self, lam: ast.Lambda):
        self.name = f"<lambda@{lam.lineno}>"
        self.body = [ast.Expr(value=lam.body)]
        self._lam = lam

    def __getattr__(self, item):
        return getattr(self._lam, item)


# ast.walk needs iter_child_nodes to work on the shim: walk the lambda
def _walk_shim(node):
    return ast.walk(node._lam if isinstance(node, _LambdaShim) else node)


def check(project: Project) -> list[Finding]:
    graph = CallGraph(project)
    findings: list[Finding] = []
    entries = []
    for qual, intrinsic, label in ENTRY_POINTS:
        fn = graph.funcs.get(qual)
        if fn is None:
            findings.append(Finding(
                "block_zone", qual.split("::")[0], 1, qual,
                "missing-entry",
                f"declared no-block entry point {qual} not found — "
                "update tools/gklint/block_zone.py ENTRY_POINTS"))
            continue
        entries.append((fn, label, set(intrinsic)))
    for fn, label in _probe_entries(project, graph):
        entries.append((fn, label, {"lock"}))
    for fn, label, intrinsic in entries:
        node = fn.node
        if isinstance(node, _LambdaShim):
            # direct ops only for lambdas (their receivers are bound
            # defaults the graph can't type)
            sf = project.files[fn.path]
            for sub in _walk_shim(node):
                if isinstance(sub, ast.Call):
                    cat, op = _classify_call(sub)
                    if cat is not None and cat not in intrinsic \
                            and not sf.allowed(sub.lineno, "block_zone"):
                        findings.append(Finding(
                            "block_zone", fn.path, sub.lineno,
                            label, f"{cat}:{op}",
                            f"blocking op `{op}` ({cat}) in scrape "
                            f"probe lambda"))
            continue
        _scan_function(project, graph, label, intrinsic, fn,
                       [_short(fn)], set(), findings)
    return findings
