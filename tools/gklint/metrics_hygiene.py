"""metrics_hygiene — family naming and bounded label values.

* counters end in ``_total``; histograms end in ``_seconds`` (the
  Prometheus/OpenMetrics conventions strict scrapers enforce). The
  reference-parity legacy names the repo inherited are allowlisted
  explicitly — the list may only shrink.
* label values must never be interpolated strings (f-strings, ``%``,
  ``+``, ``.format``): one interpolated kind/user/path value mints an
  unbounded series set and the registry never forgets a label set.
* ``reason``/``outcome``/``path``/``status`` labels fed from a
  variable must show a bounded-set discipline in the enclosing
  function: a membership test (or fold) against an ALL_CAPS constant,
  the REASON_CODES pattern from ir/compile.py.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, dotted, str_const

# reference metric-name parity (SURVEY.md §2.1) predates the _total
# convention; these families are frozen — never add to this list
LEGACY_COUNTERS = {
    "request_count",
    "mutation_request_count",
    "mutator_ingestion_count",
    "admission_batch_timeouts",
}

# histograms whose unit genuinely is not seconds
NON_SECONDS_HISTOGRAMS = {
    "gatekeeper_tpu_batch_fill_ratio",  # dimensionless fill fraction
}

_RECORDERS = {"counter_add": "counter", "observe": "histogram",
              "observe_bucketed": "histogram", "gauge_set": "gauge"}

_BOUNDED_LABELS = {"reason", "outcome", "path", "status",
                   "knob", "direction", "rung", "tier"}


def _interpolated(node: ast.AST) -> bool:
    if isinstance(node, ast.JoinedStr):
        return True
    if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                  (ast.Add, ast.Mod)):
        return (_interpolated(node.left) or _interpolated(node.right)
                or str_const(node.left) is not None
                or str_const(node.right) is not None)
    if isinstance(node, ast.Call) and \
            dotted(node.func).endswith(".format"):
        return True
    return False


def _has_bound_discipline(fn_node: ast.AST, name: str) -> bool:
    """True when the enclosing function tests/folds `name` against an
    ALL_CAPS constant (`if reason not in REASON_CODES: ...`,
    `REASONS.get(reason, ...)`), or reassigns it from a literal."""
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Compare) and \
                isinstance(sub.left, ast.Name) and sub.left.id == name:
            for op, comp in zip(sub.ops, sub.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    target = dotted(comp).split(".")[-1]
                    if target and target.upper() == target:
                        return True
        if isinstance(sub, ast.Call):
            d = dotted(sub.func)
            base = d.rsplit(".", 1)[0] if "." in d else ""
            if d.endswith(".get") and base.upper() == base and base:
                for a in sub.args:
                    if isinstance(a, ast.Name) and a.id == name:
                        return True
    return False


def _enclosing_function(sf, node):
    cur = sf.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = sf.parents.get(cur)
    return None


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for path, sf in project.files.items():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted(node.func).split(".")[-1]
            kind = _RECORDERS.get(leaf)
            if kind is None:
                continue
            name = str_const(node.args[0]) if node.args else None
            scope = sf.scope_of(node)
            if name is not None and not sf.allowed(node.lineno,
                                                   "metrics_hygiene"):
                if kind == "counter" and not name.endswith("_total") \
                        and name not in LEGACY_COUNTERS:
                    findings.append(Finding(
                        "metrics_hygiene", path, node.lineno, scope,
                        f"counter-name:{name}",
                        f"counter `{name}` must end in _total "
                        "(OpenMetrics strict scrapers reject bare "
                        "counter families)"))
                elif kind == "histogram" \
                        and not name.endswith("_seconds") \
                        and name not in NON_SECONDS_HISTOGRAMS:
                    findings.append(Finding(
                        "metrics_hygiene", path, node.lineno, scope,
                        f"histogram-name:{name}",
                        f"histogram `{name}` must end in _seconds "
                        "(or be allowlisted with its real unit)"))
            # label kwargs: interpolation + boundedness
            for kw in node.keywords:
                if kw.arg is None or kw.arg in ("help_", "value",
                                                "buckets", "exemplar"):
                    continue
                if sf.allowed(node.lineno, "metrics_hygiene"):
                    continue
                if _interpolated(kw.value):
                    findings.append(Finding(
                        "metrics_hygiene", path, node.lineno, scope,
                        f"interpolated-label:{kw.arg}",
                        f"label `{kw.arg}` built from string "
                        "interpolation — label values must come from "
                        "bounded sets, never formatted input"))
                elif kw.arg in _BOUNDED_LABELS and \
                        isinstance(kw.value, ast.Name):
                    fn = _enclosing_function(sf, node)
                    if fn is not None and \
                            not _has_bound_discipline(fn, kw.value.id):
                        findings.append(Finding(
                            "metrics_hygiene", path, node.lineno,
                            scope, f"unbounded-label:{kw.arg}",
                            f"label `{kw.arg}` fed from variable "
                            f"`{kw.value.id}` with no membership "
                            "test/fold against an ALL_CAPS bounded "
                            "set in this function (REASON_CODES "
                            "pattern)"))
    return findings
