"""clock_discipline — wall clocks forbidden in duration arithmetic.

``time.time()`` (and naive ``datetime.now()``) jumps under NTP steps
and leap adjustments; every duration, deadline, or duty-cycle
computation built on it mis-attributes exactly when the system is
under stress. PR 13's review pass converted the duty accounting to
``time.monotonic()`` by hand — this checker makes the conversion
stick.

Flagged: a wall-clock call participating in +/- arithmetic or an
ordered comparison, directly or through a variable assigned from one
inside the same function. Pure timestamp *storage* (log fields,
epoch stamps persisted for other processes) is not arithmetic and
passes; genuinely cross-process epoch math (snapshot age) carries an
allow comment explaining why wall clock is correct there.
"""

from __future__ import annotations

import ast

from .core import Finding, Project, dotted

_ORDERED = (ast.Lt, ast.LtE, ast.Gt, ast.GtE)


def _is_wall_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = dotted(node.func)
    if name.endswith("time.time") or name == "time":
        # `time.time()` / `_time.time()` / bare `time()` via
        # `from time import time`
        return name != "time" or isinstance(node.func, ast.Name)
    if name.endswith("datetime.now") or name == "now":
        # naive now(); tz-aware now(tz) is a labeled wall timestamp
        return not node.args and not node.keywords
    return False


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            yield node


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for path, sf in project.files.items():
        funcs = list(_functions(sf.tree))
        in_any_func: set = set()
        for f in funcs:
            in_any_func |= set(ast.walk(f)) - {f}
        scopes = funcs + [sf.tree]
        seen_lines: set = set()
        for scope in scopes:
            own = set(ast.walk(scope))
            if isinstance(scope, ast.Module):
                own -= in_any_func  # module scope: top-level only
            else:
                for sub in ast.walk(scope):
                    if sub is not scope and isinstance(
                            sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                        own -= set(ast.walk(sub)) - {sub}
            # names assigned (directly) from a wall-clock call
            wall_names: set = set()
            for sub in own:
                if isinstance(sub, ast.Assign) and \
                        _is_wall_call(sub.value):
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            wall_names.add(tgt.id)
                        elif isinstance(tgt, ast.Attribute):
                            wall_names.add(f"@{tgt.attr}")

            def _tainted(node) -> bool:
                if _is_wall_call(node):
                    return True
                if isinstance(node, ast.Name):
                    return node.id in wall_names
                if isinstance(node, ast.Attribute):
                    return f"@{node.attr}" in wall_names
                return False

            for sub in own:
                operands = []
                if isinstance(sub, ast.BinOp) and \
                        isinstance(sub.op, (ast.Add, ast.Sub)):
                    operands = [sub.left, sub.right]
                elif isinstance(sub, ast.Compare) and any(
                        isinstance(op, _ORDERED) for op in sub.ops):
                    operands = [sub.left] + list(sub.comparators)
                elif isinstance(sub, ast.AugAssign) and \
                        isinstance(sub.op, (ast.Add, ast.Sub)):
                    operands = [sub.value]
                if not operands:
                    continue
                if not any(_tainted(o) for o in operands):
                    continue
                line = sub.lineno
                if line in seen_lines:
                    continue
                if sf.allowed(line, "clock_discipline"):
                    seen_lines.add(line)
                    continue
                seen_lines.add(line)
                findings.append(Finding(
                    "clock_discipline", path, line, sf.scope_of(sub),
                    f"wall-arith@{sf.scope_of(sub)}",
                    "wall-clock value in duration/deadline arithmetic "
                    "— use time.monotonic() (NTP steps corrupt "
                    "durations built on time.time())"))
    return findings
