"""jit_discipline — AotJit coverage in ir/ and the trace-stage registry.

* Every ``jax.jit`` in ``gatekeeper_tpu/ir/`` must flow through
  ``AotJit`` (ir/aot.py) so the program rides the serialized-
  executable store and a warm boot deserializes instead of
  recompiling (the PR 8 contract). A bare ``jax.jit`` outside aot.py
  is a cold-start regression waiting for a restart to find it.
* Every stage/phase name literal passed to the span recorders must be
  declared in ``gatekeeper_tpu/control/stages.py`` — the bounded
  ``stage`` label set, which also renders the README stage table.
  Dynamic stage names need an allow comment naming where the values
  are bounded.
"""

from __future__ import annotations

import ast
import os

from .core import Finding, Project, dotted, str_const

STAGES_MODULE = "gatekeeper_tpu/control/stages.py"

# call-leaf -> index of the stage-name argument
_STAGE_SINKS = {
    "span": 0,          # tr.span("encode")
    "add_span": 0,      # tr.add_span("frontend_parse", t0, t1)
    "add_phase": 0,     # tr.add_phase(name, secs)
    "observe_stage": 0,  # frontend stats accumulator
    "stage_hook": 0,    # frontend stage relay
    "report_stage": 1,  # metrics.report_stage(plane, stage, ...)
    "report_stage_bucketed": 1,
    "report_audit_shard": 0,
    "phase": 0,         # profiling.timers().phase("compile")
    "add": 0,           # profiling.timers().add("device_sweep", s)
}

# receivers that make a bare .phase()/.add() a PhaseTimers call and a
# bare .span()/.add_span() a trace call — everything else (set.add,
# argparse groups, ...) is ignored
_TIMERS_HINTS = ("timers", "phase_timers")
_TRACE_HINTS = ("tr", "trace", "self", "p.trace")


def load_stage_names(root: str) -> frozenset:
    """Parse STAGES keys out of stages.py without importing the
    package (the linter must run without jax on the path)."""
    path = os.path.join(root, STAGES_MODULE)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) and tgt.id == "STAGES" \
                        and isinstance(node.value, ast.Dict):
                    return frozenset(
                        k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str))
    raise SystemExit(f"gklint: no STAGES dict literal in {path}")


def _stage_receiver_ok(leaf: str, recv: str) -> bool:
    recv_low = recv.lower()
    if leaf in ("phase", "add"):
        return any(h in recv_low for h in _TIMERS_HINTS)
    if leaf in ("span", "add_span", "add_phase"):
        return any(recv_low == h or recv_low.startswith(h)
                   for h in _TRACE_HINTS) or "trace" in recv_low \
            or recv_low in ("tr", "t")
    return True  # uniquely-named sinks


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    stage_names = load_stage_names(project.root)

    for path, sf in project.files.items():
        in_ir = path.startswith("gatekeeper_tpu/ir/") and \
            not path.endswith("ir/aot.py")
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            leaf = name.split(".")[-1]
            # --- bare jax.jit in ir/ -------------------------------
            if in_ir and name.endswith("jax.jit") \
                    and not sf.allowed(node.lineno, "jit_discipline"):
                findings.append(Finding(
                    "jit_discipline", path, node.lineno,
                    sf.scope_of(node), "bare-jax-jit",
                    "bare jax.jit in ir/ — wrap in AotJit (ir/aot.py) "
                    "so the executable rides the AOT store and warm "
                    "boots deserialize instead of recompiling"))
                continue
            # --- stage-name registry -------------------------------
            idx = _STAGE_SINKS.get(leaf)
            if idx is None or len(node.args) <= idx:
                continue
            recv = ".".join(name.split(".")[:-1])
            if not _stage_receiver_ok(leaf, recv):
                continue
            # x.span(...) used as a context manager or via TRACER etc.
            lit = str_const(node.args[idx])
            if sf.allowed(node.lineno, "stage_registry"):
                continue
            if lit is None:
                findings.append(Finding(
                    "stage_registry", path, node.lineno,
                    sf.scope_of(node), f"dynamic-stage:{leaf}",
                    f"dynamic stage name passed to {leaf}() — stage "
                    "labels are a bounded set; pass a literal from "
                    "control/stages.py or allow(stage) with the "
                    "bounding argument"))
            elif lit not in stage_names:
                findings.append(Finding(
                    "stage_registry", path, node.lineno,
                    sf.scope_of(node), f"unregistered-stage:{lit}",
                    f"stage name `{lit}` not declared in "
                    "gatekeeper_tpu/control/stages.py — register it "
                    "(the README stage table renders from there)"))
    return findings
