"""gklint core: source model, allow-comment parsing, findings, baseline.

Finding identity (the baseline key) is ``checker:path:scope:code`` —
deliberately line-free, so unrelated edits above a pinned finding don't
churn the ratchet file. Multiple findings may share a key (the baseline
stores counts); the ratchet compares per-key counts both ways.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Iterable, Optional

# checker name -> allow-comment code (what goes inside allow(...))
ALLOW_CODES = {
    "block_zone": "block-zone",
    "gauge_teardown": "gauge-teardown",
    "clock_discipline": "clock",
    "metrics_hygiene": "metrics",
    "jit_discipline": "jit",
    "stage_registry": "stage",
}

_ALLOW_RE = re.compile(
    r"#\s*gklint:\s*allow\(([a-z\-,\s]+)\)(?:\s+reason=(.*))?")


class Finding:
    __slots__ = ("checker", "path", "line", "scope", "code", "message")

    def __init__(self, checker: str, path: str, line: int, scope: str,
                 code: str, message: str):
        self.checker = checker
        self.path = path
        self.line = line
        self.scope = scope
        self.code = code
        self.message = message

    def key(self) -> str:
        return f"{self.checker}:{self.path}:{self.scope}:{self.code}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.checker}] "
                f"{self.scope}: {self.message}")


class SourceFile:
    """One parsed module: AST + allow-comment map + parent links."""

    def __init__(self, root: str, path: str):
        self.path = path  # repo-relative, forward slashes
        with open(os.path.join(root, path), encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.parents: dict = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # line -> set of allowed codes; malformed allows become findings
        self.allows: dict[int, set] = {}
        self.allow_errors: list[int] = []
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(line)
            if not m:
                continue
            reason = (m.group(2) or "").strip()
            if not reason:
                self.allow_errors.append(i)
                continue
            codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
            self.allows.setdefault(i, set()).update(codes)

    def allowed(self, line: int, checker: str) -> bool:
        """An allow comment suppresses on its own line or the line it
        precedes (comment-above style)."""
        code = ALLOW_CODES.get(checker, checker)
        for ln in (line, line - 1):
            if code in self.allows.get(ln, ()):  # exact code only
                return True
        return False

    def scope_of(self, node: ast.AST) -> str:
        """Dotted qualname of the enclosing class/function chain."""
        parts = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"


class Project:
    """All analyzed sources, loaded once and shared by every checker."""

    def __init__(self, root: str, package: str = "gatekeeper_tpu",
                 paths: Optional[Iterable[str]] = None):
        self.root = root
        self.package = package
        self.files: dict[str, SourceFile] = {}
        if paths is None:
            paths = sorted(self._discover(root, package))
        for rel in paths:
            try:
                self.files[rel] = SourceFile(root, rel)
            except (SyntaxError, UnicodeDecodeError) as e:
                raise SystemExit(f"gklint: cannot parse {rel}: {e}")

    @staticmethod
    def _discover(root: str, package: str) -> Iterable[str]:
        pkg_root = os.path.join(root, package)
        for dirpath, _dirs, names in os.walk(pkg_root):
            for name in names:
                if name.endswith(".py"):
                    full = os.path.join(dirpath, name)
                    yield os.path.relpath(full, root).replace(os.sep, "/")


def run_checkers(project: Project, checkers=None) -> list[Finding]:
    """Run every checker (or the named subset) and fold in malformed
    allow comments as findings."""
    from . import (block_zone, clock_discipline, gauge_teardown,
                   jit_discipline, metrics_hygiene)

    registry = {
        "block_zone": block_zone.check,
        "gauge_teardown": gauge_teardown.check,
        "clock_discipline": clock_discipline.check,
        "metrics_hygiene": metrics_hygiene.check,
        "jit_discipline": jit_discipline.check,
    }
    findings: list[Finding] = []
    for name, fn in registry.items():
        if checkers and name not in checkers:
            continue
        findings.extend(fn(project))
    for sf in project.files.values():
        for ln in sf.allow_errors:
            findings.append(Finding(
                "allow", sf.path, ln, "<comment>", f"line{ln}",
                "gklint allow comment without a reason= (the escape "
                "hatch requires one)"))
    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.code))
    return findings


# ------------------------------------------------------------ baseline

def load_baseline(path: str) -> dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {str(k): int(v) for k, v in (data.get("findings") or {}).items()}


def write_baseline(path: str, findings: list[Finding]) -> None:
    counts: dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    with open(path, "w", encoding="utf-8") as f:
        json.dump({
            "comment": "gklint suppression ratchet: new findings fail "
                       "CI; fixed findings must shrink this file "
                       "(python -m tools.gklint --write-baseline). "
                       "Values are finding counts per stable key.",
            "findings": dict(sorted(counts.items())),
        }, f, indent=2, sort_keys=False)
        f.write("\n")


def ratchet(findings: list[Finding], baseline: dict[str, int]
            ) -> tuple[list[str], list[str]]:
    """(new_findings, stale_suppressions): both must be empty to pass.

    New = a key's current count exceeds its baselined count (each
    excess occurrence is listed). Stale = a baselined key whose count
    shrank — the fix landed, so the suppression must shrink too."""
    counts: dict[str, int] = {}
    by_key: dict[str, list[Finding]] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
        by_key.setdefault(f.key(), []).append(f)
    new: list[str] = []
    for key, n in sorted(counts.items()):
        allowed = baseline.get(key, 0)
        if n > allowed:
            for f in by_key[key][allowed:]:
                new.append(f.render())
    stale = [f"{key} (baseline {n}, now {counts.get(key, 0)})"
             for key, n in sorted(baseline.items())
             if counts.get(key, 0) < n]
    return new, stale


# ------------------------------------------------------- AST utilities

def dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('self.kube.get');
    empty string for anything unresolvable."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        base = dotted(node.func)
        return f"{base}()" if base else ""
    return ""


def call_name(call: ast.Call) -> str:
    return dotted(call.func)


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
