#!/usr/bin/env python
"""Perf-trend watchdog over the committed BENCH_r*.json history.

Until now, nothing watched the benchmark trajectory: a perf regression
only surfaced when a human re-read old JSON. This tool parses every
round's headline fields into one trajectory table, flags any metric
whose LATEST round regressed more than --threshold (default 25%)
against the best prior round, and renders a markdown report.

    python tools/bench_trend.py                  # print the report
    python tools/bench_trend.py --check          # exit 1 on regression
    python tools/bench_trend.py --report trend.md
    python bench.py --trend                      # same, via bench.py

Wired as the non-blocking `bench-trend` CI job (report uploaded as an
artifact). A config that recorded {"error": ...} instead of numbers is
reported as DID NOT RUN — distinguishable from "regressed" (bench.py
and bench_configs.py record per-config errors exactly for this).

Robustness: BENCH files carry {"parsed": {...}} when the harness
parsed the headline line, but older rounds hold only a truncated
"tail" (r05's headline JSON is cut mid-line at the FRONT). The loader
recovers those by re-wrapping the fragment at successive top-level
key boundaries until it parses — recovered fields are real, missing
ones stay missing rather than guessed.
"""

from __future__ import annotations

import argparse
import glob as globmod
import json
import os
import re
import sys
from typing import Optional

DEFAULT_THRESHOLD = 0.25

# explicit metric directions; anything the heuristic can't classify is
# shown in the table but never gated
_LOWER_BETTER = {
    "full_audit_wall_clock_s", "audit_wall_clock_s", "sweep_wall_s",
    "match_s", "materialize_s", "materialize_vs_sweep", "delta_audit_s",
    "mutate_audit_s", "mutate_s", "warm_boot_s", "cold_boot_s",
    "warm_first_audit_s", "cold_first_audit_s", "mesh_audit_s",
    "whatif_preview_s", "first_audit_s", "first_call_s",
    "violation_detection_p99_ms", "violation_detection_p50_ms",
    # chaos MTTR matrix (ISSUE 19): worst recovery wall across the
    # six-fault matrix, and the verifier's violation count (always 0
    # in a passing round — the bench asserts it — so the trend gate
    # only ever sees zeros; kept here to pin the direction)
    "chaos_mttr_p99_s", "chaos_invariant_violations",
}
_HIGHER_BETTER = {
    "audit_cross_product_evals_per_sec_per_chip", "evals_per_sec_per_chip",
    "admission_rps", "admission_requests_per_sec", "vs_baseline",
    "detection_speedup_p99", "mesh_audit_vs_single_device",
    "compile_widening_speedup", "general_library_compiled_fraction",
    "engine_batched_reviews_per_sec",
    # serving-plane wire tiers (ISSUE 14): the gRPC batched tier fell
    # 5,067 (r04, per VERDICT) -> 3,517 (r05) with nothing watching —
    # these are now first-class gated series so the NEXT wire-path
    # regression fails --check instead of surfacing in a verdict
    "grpc_batched_reviews_per_sec",
    "grpc_stream_reviews_per_sec",
    "backplane_bulk_reviews_per_sec",
    # the evaluation-honest bulk tier (ISSUE 20): same B frames, a
    # --no-decision-cache engine, so a cache-hit speedup can't mask an
    # evaluation regression in the gated series
    "backplane_bulk_reviews_per_sec_nocache",
    "edge_vs_engine_ratio",
    # offline fleet scan (ISSUE 20): manifests/s through the
    # loader/dedupe/bulk-feed pipeline, best warm tier
    "fleet_scan_manifests_per_sec",
    "scan_warm_manifests_per_sec",
    "scan_backplane_manifests_per_sec",
    # sharded inventory plane (ISSUE 16): one composed audit round's
    # throughput over the process-sharded plane
    "sharded_audit_objects_per_sec", "sharded_objects_per_sec",
    # adaptive controller (ISSUE 18): converged fraction of the
    # hand-tuned reference throughput, gated >= 0.9 in-bench too
    "adaptive_converged_frac",
}

# measured but NOT gated by --check: cold-start and first-call numbers
# move with workload size and host weather; baseline_* measures the
# Python reference, not us; setup is harness cost. They stay in the
# table so a human can still read their trajectory.
_NOISY = {
    "first_audit_s", "first_call_s", "cold_first_audit_s",
    "cold_boot_s", "setup_s", "vs_baseline", "mutate_audit_s",
}

# per-config fields (beyond the headline `value`) lifted into the
# trajectory as c<N>.<field>: the serving-plane wire tiers live INSIDE
# config 5's record, not as its headline value, and were invisible to
# the watchdog (exactly how the gRPC batched-tier regression shipped
# unflagged in r05). Non-numeric entries ("unavailable: ...") are
# skipped by the numeric filter, so a tier that failed to run never
# poisons its series.
_CONFIG_EXTRA_FIELDS = (
    "grpc_batched_reviews_per_sec",
    "grpc_stream_reviews_per_sec",
    "backplane_bulk_reviews_per_sec",
    "backplane_bulk_reviews_per_sec_nocache",
    "engine_batched_reviews_per_sec",
    "edge_vs_engine_ratio",
    "scan_warm_manifests_per_sec",
    "scan_backplane_manifests_per_sec",
)

# top-level headline fields bench.py COPIES out of the side configs —
# the copy carries no unit string, so a config scale change would
# false-flag it; the gated series is the unit-carrying c<N>.* twin
_CONFIG_MIRRORS = {
    "admission_rps", "mutate_s", "warm_boot_s",
    "violation_detection_ms", "detection_speedup_p99",
    "whatif_preview_s", "mesh_audit_s", "mesh_audit_vs_single_device",
    "compile_widening_speedup", "general_library_compiled_fraction",
    "warm_first_audit_s", "sharded_objects_per_sec",
    "sharded_sweep_wall_s", "chaos_mttr_p99_s",
    "chaos_invariant_violations", "fleet_scan_manifests_per_sec",
}

def _ungated(name: str) -> bool:
    """True when `name` is shown in the table but never gated by
    --check: noisy fields anywhere, config mirrors only at TOP level
    (a c<N>.* twin with the same base name still gates)."""
    base = name.split(".", 1)[-1]
    return base in _NOISY or ("." not in name
                              and base in _CONFIG_MIRRORS)
_SKIP = {
    "objects", "constraints", "violating_pairs",
    "violations_materialized", "baseline_evals_per_sec",
    "baseline_full_audit_s", "n_devices", "config", "violations",
    "host_cores", "workers", "device_compiled_kinds", "total_kinds",
    "slo_met", "setup_s", "best_shards", "sharded_best_shards",
}


def direction(name: str) -> Optional[str]:
    """'lower' / 'higher' / None (untracked) for one metric name."""
    base = name.split(".", 1)[-1]
    if base in _SKIP:
        return None
    if base in _LOWER_BETTER:
        return "lower"
    if base in _HIGHER_BETTER:
        return "higher"
    if re.search(r"(_per_sec|_rps|speedup|fraction)s?$", base):
        return "higher"
    if re.search(r"(_s|_ms|_seconds)$", base):
        return "lower"
    return None


# ----------------------------------------------------------- loading


def _recover_fragment(line: str) -> Optional[dict]:
    """Parse a (possibly front-truncated) JSON object line: drop
    leading garbage up to successive top-level `, "` boundaries and
    re-wrap in braces until json.loads succeeds. Recovers the TRAILING
    fields of a headline line whose front was cut by tail capture."""
    line = line.strip()
    if not line:
        return None
    if line.startswith("{"):
        try:
            return json.loads(line)
        except ValueError:
            pass
    pos = 0
    for _ in range(64):
        idx = line.find(', "', pos)
        if idx < 0:
            return None
        candidate = "{" + line[idx + 2:]
        try:
            doc = json.loads(candidate)
            if isinstance(doc, dict):
                return doc
        except ValueError:
            pass
        pos = idx + 1
    return None


def _headline_doc(raw: dict) -> Optional[dict]:
    """The benchmark headline object of one BENCH_r*.json: the
    harness-parsed copy when present, else recovered from the captured
    output tail."""
    parsed = raw.get("parsed")
    if isinstance(parsed, dict):
        return parsed
    tail = raw.get("tail") or ""
    best = None
    for line in tail.splitlines():
        if '"metric"' in line or '"configs"' in line or \
                line.strip().endswith("}"):
            doc = _recover_fragment(line)
            # prefer the recovery with the most fields (the headline
            # line dwarfs warning lines)
            if doc and (best is None or len(doc) > len(best)):
                best = doc
    return best


def flatten_round(doc: dict) -> tuple[dict, dict, dict]:
    """(metrics, errors, units) of one round's headline doc. Metric
    keys: top-level numeric fields by name, the headline `value` keyed
    by its `metric` name, and each side config's `value` keyed
    `c<N>.<metric>`. Errors: {key: message} for configs that recorded
    {"error": ...} instead of numbers (DID NOT RUN, not regressed).
    Units: the value's `unit` string — the bench encodes the workload
    SCALE there, and two rounds are only comparable when it matches
    (a scale or methodology change restarts the series baseline)."""
    metrics: dict = {}
    errors: dict = {}
    units: dict = {}

    def put(name, v, unit=None):
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return
        if direction(name) is None and name.split(".", 1)[-1] in _SKIP:
            return
        metrics[name] = float(v)
        if isinstance(unit, str):
            units[name] = unit

    for k, v in doc.items():
        if k == "value":
            mname = doc.get("metric")
            if isinstance(mname, str):
                put(mname, v, doc.get("unit"))
        elif k == "configs" and isinstance(v, dict):
            for cnum, cdoc in v.items():
                if not isinstance(cdoc, dict):
                    continue
                if cdoc.get("error"):
                    errors[f"c{cnum}"] = str(cdoc["error"])[:200]
                    continue
                cm = cdoc.get("metric")
                if isinstance(cm, str):
                    put(f"c{cnum}.{cm}", cdoc.get("value"),
                        cdoc.get("unit"))
                for f in _CONFIG_EXTRA_FIELDS:
                    if f in cdoc:
                        put(f"c{cnum}.{f}", cdoc.get(f))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            put(k, v)
    if doc.get("error"):
        errors["headline"] = str(doc["error"])[:200]
    return metrics, errors, units


def load_rounds(paths: list[str]) -> list[dict]:
    """[{round, path, metrics, errors}] in round order."""
    rounds = []
    for path in sorted(paths):
        name = os.path.basename(path)
        m = re.search(r"r(\d+)", name)
        label = f"r{int(m.group(1)):02d}" if m else name
        try:
            raw = json.load(open(path))
        except (OSError, ValueError) as e:
            rounds.append({"round": label, "path": path, "metrics": {},
                           "errors": {"file": str(e)[:200]}})
            continue
        doc = _headline_doc(raw) or {}
        metrics, errors, units = flatten_round(doc)
        rounds.append({"round": label, "path": path,
                       "metrics": metrics, "errors": errors,
                       "units": units,
                       # execution platform (bench.py `jax_backend`):
                       # part of the comparability key — None for
                       # rounds that predate the field
                       "platform": doc.get("jax_backend")})
    return rounds


# ------------------------------------------------------------ analysis


def find_regressions(rounds: list[dict],
                     threshold: float = DEFAULT_THRESHOLD,
                     latest_only: bool = True) -> list[dict]:
    """Metrics regressing > threshold vs the best PRIOR round.
    `latest_only` gates only each metric's newest data point (the
    --check contract: history that already shipped can't fail CI
    forever); False flags every historical regression for the report."""
    series: dict[str, list[tuple[int, float, Optional[str],
                                 Optional[str]]]] = {}
    for i, rnd in enumerate(rounds):
        for name, v in rnd["metrics"].items():
            series.setdefault(name, []).append(
                (i, v, (rnd.get("units") or {}).get(name),
                 rnd.get("platform")))
    out = []
    for name, points in sorted(series.items()):
        d = direction(name)
        # only gate the UNIT-CARRYING series: top-level fields copied
        # out of configs have no unit to restart on, so a config scale
        # change would false-flag the copy (the c<N>.* twin gates)
        if d is None or _ungated(name):
            continue
        if len(points) < 2:
            continue
        if latest_only:
            # gate ONLY metrics present in the newest ROUND: a metric
            # whose series ended earlier (config dropped/renamed) is
            # immutable history — its old final point must not fail
            # every future PR's --check forever
            if points[-1][0] != len(rounds) - 1:
                continue
            checks = [len(points) - 1]
        else:
            checks = range(1, len(points))
        for j in checks:
            i, v, unit, plat = points[j]
            # a round is only comparable against priors measured at
            # the SAME unit string — the bench encodes workload scale
            # and methodology there (r04 configs ran reduced scale,
            # r05 full: not a regression, a series restart) — AND on
            # the same execution platform (`jax_backend`): r03/r04 ran
            # on accelerator hosts, r06 on a 1-core CPU container;
            # device-bound walls differ ~20x by host class alone.
            # Rounds predating the field (platform None) only compare
            # among themselves: comparability can't be assumed, and a
            # host-class move must restart the baseline, not fail
            # every future --check forever.
            prior = [pv for _pi, pv, pu, pp in points[:j]
                     if pu == unit and pp == plat]
            if not prior:
                continue
            best = min(prior) if d == "lower" else max(prior)
            if best <= 0:
                continue
            ratio = (v / best) if d == "lower" else (best / v if v > 0
                                                    else float("inf"))
            if ratio > 1.0 + threshold:
                out.append({
                    "metric": name, "direction": d,
                    "round": rounds[i]["round"], "value": v,
                    "best_prior": best,
                    "regression_pct": round((ratio - 1.0) * 100, 1),
                })
    return out


# ------------------------------------------------------------- report


def _fmt_v(v: Optional[float]) -> str:
    if v is None:
        return "—"
    if abs(v) >= 1000:
        return f"{v:,.0f}"
    if abs(v) >= 10:
        return f"{v:.1f}"
    return f"{v:.4g}"


def render_markdown(rounds: list[dict], regressions: list[dict],
                    threshold: float) -> str:
    names = sorted({n for r in rounds for n in r["metrics"]},
                   key=lambda n: (direction(n) is None, n))
    lines = ["# Benchmark trend", ""]
    lines.append("Rounds: " + ", ".join(
        r["round"] + (f" [{r['platform']}]" if r.get("platform")
                      else "") for r in rounds) + "  ")
    lines.append(f"Regression threshold: >{threshold:.0%} vs the best "
                 "prior round (latest round gated; `↓` lower is "
                 "better, `↑` higher is better, unmarked metrics are "
                 "informational). Rounds compare only within the same "
                 "`jax_backend` platform — a host-class change "
                 "restarts every series baseline.")
    lines.append("")
    header = "| metric | " + " | ".join(r["round"] for r in rounds) + " |"
    lines.append(header)
    lines.append("|" + "---|" * (len(rounds) + 1))
    flagged = {(r["metric"], r["round"]) for r in regressions}
    for name in names:
        d = direction(name)
        arrow = {"lower": " ↓", "higher": " ↑", None: ""}[d]
        noisy = " (info)" if _ungated(name) else ""
        cells = []
        for rnd in rounds:
            v = rnd["metrics"].get(name)
            cell = _fmt_v(v)
            if (name, rnd["round"]) in flagged:
                cell = f"**{cell}** ⚠"
            cells.append(cell)
        lines.append(f"| {name}{arrow}{noisy} | " + " | ".join(cells)
                     + " |")
    lines.append("")
    ran_errors = [(r["round"], k, msg) for r in rounds
                  for k, msg in sorted(r["errors"].items())]
    if ran_errors:
        lines.append("## Did not run")
        lines.append("")
        lines.append("Configs that recorded an error instead of "
                     "numbers (NOT regressions):")
        lines.append("")
        for rnd, key, msg in ran_errors:
            lines.append(f"- {rnd} `{key}`: {msg}")
        lines.append("")
    if regressions:
        lines.append("## Regressions")
        lines.append("")
        for r in regressions:
            lines.append(
                f"- **{r['metric']}** ({r['round']}): "
                f"{_fmt_v(r['value'])} vs best prior "
                f"{_fmt_v(r['best_prior'])} — "
                f"{r['regression_pct']}% worse "
                f"({'lower' if r['direction'] == 'lower' else 'higher'}"
                " is better)")
    else:
        lines.append("## Regressions")
        lines.append("")
        lines.append("None: no gated headline metric regressed "
                     f">{threshold:.0%} vs its best prior round.")
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------- CLI


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_trend",
        description="perf-trend watchdog over BENCH_r*.json history")
    p.add_argument("--dir", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))),
        help="directory holding the BENCH files (default: repo root)")
    p.add_argument("--glob", default="BENCH_r*.json")
    p.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                   help="fractional regression beyond which a metric "
                        "flags (default 0.25 = 25%%)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when any gated metric's LATEST round "
                        "regressed vs its best prior round")
    p.add_argument("--report", default="",
                   help="also write the markdown report to this path")
    p.add_argument("--all-history", action="store_true",
                   help="flag historical (non-latest) regressions too "
                        "(report only; --check always gates the "
                        "latest round)")
    args = p.parse_args(argv)
    paths = globmod.glob(os.path.join(args.dir, args.glob))
    if not paths:
        print(f"no files match {args.glob} under {args.dir}",
              file=sys.stderr)
        return 2
    rounds = load_rounds(paths)
    gate = find_regressions(rounds, args.threshold, latest_only=True)
    shown = find_regressions(rounds, args.threshold, latest_only=False) \
        if args.all_history else gate
    report = render_markdown(rounds, shown, args.threshold)
    print(report)
    if args.report:
        with open(args.report, "w") as f:
            f.write(report)
    if args.check and gate:
        print(f"FAIL: {len(gate)} gated metric(s) regressed "
              f">{args.threshold:.0%} vs best prior round",
              file=sys.stderr)
        return 1
    if args.check:
        print("OK: no gated regressions", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
