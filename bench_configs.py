#!/usr/bin/env python
"""BASELINE.md configs #1, #2, #3, #5 (config #4 is bench.py's headline).

One JSON line per config:
  #1 requiredlabels x 1k Namespaces     — full audit wall-clock + the
     measured local interpreter (local-OPA stand-in) audit baseline
  #2 full shipped general library x 10k mixed objects — full audit
  #3 full shipped pod-security-policy library x 50k Pods (regex-heavy)
     — full audit
  #5 streaming admission through the MicroBatcher vs the FULL general
     library — sustained requests/s and p50/p99 latency under 64
     closed-loop concurrent clients

All audits run steady-state through client.audit() (warm caches), same
contract as bench.py. Run: python bench_configs.py [1 2 3 5]
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

TARGET = "admission.k8s.gatekeeper.sh"
SCALE = float(os.environ.get("BENCH_SCALE", 1.0))  # shrink for smoke runs


def new_client(driver=None):
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    driver = driver or TpuDriver()
    return driver, Backend(driver).new_client([K8sValidationTarget()])


def steady_audit(client, iters=3):
    t0 = time.time()
    resp = client.audit()
    first = time.time() - t0
    best = float("inf")
    for _ in range(iters):
        t0 = time.time()
        resp = client.audit()
        best = min(best, time.time() - t0)  # min-of-N: noise-robust
    return best, first, len(resp.results())


# --------------------------------------------------------------- config 1


def config1():
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import RegoDriver
    from gatekeeper_tpu.parallel.workload import synth_objects

    n = int(1000 * SCALE)
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "must-own"},
        "spec": {"parameters": {"labels": [
            {"key": "owner", "allowedRegex": "^[a-z]+.corp.example$"}]}},
    }
    objs = synth_objects(n, violate_frac=0.02, seed=7)

    _, client = new_client()
    client.add_template(policies.load("general/requiredlabels"))
    client.add_constraint(constraint)
    for o in objs:
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)

    # the local-OPA stand-in baseline, measured on the SAME workload
    # (pure interpreter: codegen disabled)
    base_driver = RegoDriver()
    base_driver._codegen_for = lambda *a, **k: None
    _, base_client = new_client(base_driver)
    base_client.add_template(policies.load("general/requiredlabels"))
    base_client.add_constraint(constraint)
    for o in objs:
        base_client.add_data(o)
    t0 = time.time()
    base_n = len(base_client.audit().results())
    base_s = time.time() - t0
    assert base_n == nres
    print(json.dumps({
        "config": 1, "metric": "audit_wall_clock_s", "value": round(audit_s, 4),
        "unit": f"s (requiredlabels x {n} namespaces, steady state)",
        "baseline_interpreter_s": round(base_s, 3),
        "vs_baseline": round(base_s / audit_s, 1),
        "first_audit_s": round(first, 2), "violations": nres,
    }))


# --------------------------------------------------------------- config 2


def synth_mixed_objects(n: int, seed: int = 0) -> list[dict]:
    """Pods/Deployments/Ingresses/Services with fields the general
    library examines (images, limits/requests, labels, tls/hosts,
    selectors). ~2% violate something."""
    rng = random.Random(seed)
    repos = ["registry.corp.example/", "gcr.io/corp/"]
    out = []
    for i in range(n):
        kind = ("Pod", "Pod", "Pod", "Deployment", "Ingress",
                "Service")[i % 6]
        name = f"{kind.lower()}-{i}"
        labels = {"owner": "team.corp.example", "app": f"app{i % 50}"}
        bad = rng.random() < 0.02
        if kind == "Pod":
            image = (rng.choice(repos) + f"svc{i % 20}:v1"
                     if not bad else f"docker.io/evil{i}:latest")
            cpu = "900m" if not bad else "4"
            out.append({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"containers": [{
                    "name": "main", "image": image,
                    "resources": {
                        "limits": {"cpu": cpu, "memory": "512Mi"},
                        "requests": {"cpu": "250m", "memory": "256Mi"}},
                }]},
            })
        elif kind == "Deployment":
            out.append({
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"replicas": 2,
                         "selector": {"matchLabels": {"app": f"app{i}"}}},
            })
        elif kind == "Ingress":
            spec = {"rules": [{"host": f"h{i}.corp.example"}]}
            meta = {"name": name, "namespace": f"ns{i % 20}",
                    "labels": labels}
            if not bad:
                spec["tls"] = [{"hosts": [f"h{i}.corp.example"]}]
                meta["annotations"] = {
                    "kubernetes.io/ingress.allow-http": "false"}
            out.append({"apiVersion": "networking.k8s.io/v1beta1",
                        "kind": "Ingress", "metadata": meta, "spec": spec})
        else:
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"selector": {"app": f"app{i}"},
                         "ports": [{"port": 80}]},
            })
    return out


GENERAL_CONSTRAINTS = [
    ("K8sAllowedRepos", "repos-allowed",
     {"repos": ["registry.corp.example/", "gcr.io/corp/"]}),
    ("K8sContainerLimits", "limits-capped", {"cpu": "2", "memory": "1Gi"}),
    ("K8sContainerRatios", "ratio-capped", {"ratio": "4"}),
    ("K8sHttpsOnly", "https-only", None),
    ("K8sRequiredLabels", "must-own",
     {"labels": [{"key": "owner",
                  "allowedRegex": "^[a-z]+.corp.example$"}]}),
    ("K8sUniqueIngressHost", "unique-hosts", None),
    ("K8sUniqueServiceSelector", "unique-selectors", None),
]


def config2():
    from gatekeeper_tpu import policies

    n = int(10_000 * SCALE)
    _, client = new_client()
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    for kind, cname, params in GENERAL_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in synth_mixed_objects(n):
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)
    print(json.dumps({
        "config": 2, "metric": "audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": f"s (full general library, {len(GENERAL_CONSTRAINTS)} "
                f"constraints x {n} mixed objects, steady state)",
        "first_audit_s": round(first, 2), "violations": nres,
    }))


# --------------------------------------------------------------- config 3


def synth_pods_psp(n: int, seed: int = 0) -> list[dict]:
    """Pod specs exercising the PSP library's fields; ~3% violate."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        bad = rng.random() < 0.03
        ctx = {"allowPrivilegeEscalation": False,
               "readOnlyRootFilesystem": True,
               "runAsUser": 1000 + (i % 1000),
               "capabilities": {"drop": ["ALL"]}}
        if bad:
            kind_of_bad = rng.randrange(5)
            if kind_of_bad == 0:
                ctx["privileged"] = True
            elif kind_of_bad == 1:
                ctx["runAsUser"] = 0
            elif kind_of_bad == 2:
                ctx["capabilities"] = {"add": ["SYS_ADMIN"], "drop": []}
            elif kind_of_bad == 3:
                ctx.pop("readOnlyRootFilesystem")
            else:
                ctx["allowPrivilegeEscalation"] = True
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": f"pod-{i}", "namespace": f"ns{i % 40}",
                "annotations": {
                    "seccomp.security.alpha.kubernetes.io/pod":
                        "runtime/default",
                    "container.apparmor.security.beta.kubernetes.io/main":
                        "runtime/default",
                },
            },
            "spec": {
                "securityContext": {"fsGroup": 2000,
                                    "sysctls": ([{"name": "net.ipv4.ip_local_port_range", "value": "1024 65535"}]
                                                if i % 7 else [{"name": "kernel.msgmax", "value": "1"}])},
                "containers": [{
                    "name": "main",
                    "image": f"registry.corp.example/app{i % 100}:v1",
                    "securityContext": ctx,
                    "ports": ([{"hostPort": 8080 + (i % 100)}]
                              if i % 11 == 0 else []),
                }],
                "volumes": [{"name": "cfg", "configMap": {"name": "c"}}] +
                           ([{"name": "h", "hostPath":
                              {"path": f"/var/log/app{i}"}}]
                            if i % 13 == 0 else []),
            },
        }
        out.append(pod)
    return out


PSP_CONSTRAINTS = [
    ("K8sPSPAllowPrivilegeEscalationContainer", "no-escalation", None),
    ("K8sPSPAppArmor", "apparmor-default",
     {"allowedProfiles": ["runtime/default"]}),
    ("K8sPSPCapabilities", "caps",
     {"allowedCapabilities": ["NET_BIND_SERVICE"],
      "requiredDropCapabilities": ["ALL"]}),
    ("K8sPSPFlexVolumes", "flex", {"allowedFlexVolumes": []}),
    ("K8sPSPForbiddenSysctls", "sysctls",
     {"forbiddenSysctls": ["kernel.*", "vm.swappiness"]}),
    ("K8sPSPFSGroup", "fsgroup",
     {"rule": "MustRunAs", "ranges": [{"min": 1000, "max": 65535}]}),
    ("K8sPSPHostFilesystem", "hostfs",
     {"allowedHostPaths": [{"pathPrefix": "/var/log", "readOnly": True}]}),
    ("K8sPSPHostNamespace", "no-host-ns", None),
    ("K8sPSPHostNetworkingPorts", "host-ports",
     {"hostNetwork": False, "min": 8000, "max": 9000}),
    ("K8sPSPPrivilegedContainer", "no-privileged", None),
    ("K8sPSPProcMount", "procmount", {"procMount": "Default"}),
    ("K8sPSPReadOnlyRootFilesystem", "ro-root", None),
    ("K8sPSPSeccomp", "seccomp",
     {"allowedProfiles": ["runtime/default", "docker/default"]}),
    ("K8sPSPSELinux", "selinux",
     {"allowedSELinuxOptions": {"level": "s0:c123,c456"}}),
    ("K8sPSPAllowedUsers", "users",
     {"runAsUser": {"rule": "MustRunAsNonRoot"}}),
    ("K8sPSPVolumeTypes", "volumes",
     {"volumes": ["configMap", "secret", "emptyDir", "hostPath"]}),
]


def config3():
    from gatekeeper_tpu import policies

    n = int(50_000 * SCALE)
    drv, client = new_client()
    for name in policies.names():
        if name.startswith("pod-security-policy/"):
            client.add_template(policies.load(name))
    for kind, cname, params in PSP_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in synth_pods_psp(n):
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)
    compiled = drv.compiled_kinds() if hasattr(drv, "compiled_kinds") else []
    device = [k for k in compiled if drv.compiled_for(k) is not None]
    print(json.dumps({
        "config": 3, "metric": "audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": f"s (full pod-security-policy library, "
                f"{len(PSP_CONSTRAINTS)} constraints x {n} pods, "
                f"steady state)",
        "first_audit_s": round(first, 2), "violations": nres,
        "device_compiled_kinds": len(device),
    }))


# --------------------------------------------------------------- config 5


def config5():
    from gatekeeper_tpu.control.webhook import MicroBatcher
    from gatekeeper_tpu import policies
    import threading

    _, client = new_client()
    # the BASELINE workload: streaming admission vs the FULL general
    # library (join templates included), mixed object kinds
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    for kind, cname, params in GENERAL_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    objs = synth_mixed_objects(512, seed=3)
    reviews = []
    for o in objs:
        meta = o.get("metadata", {})
        r = {"kind": {"group": o["apiVersion"].rpartition("/")[0],
                      "version": o["apiVersion"].rpartition("/")[2],
                      "kind": o["kind"]},
             "name": meta.get("name", ""), "object": o,
             "operation": "CREATE"}
        if "namespace" in meta:
            r["namespace"] = meta["namespace"]
        reviews.append(r)
    batcher = MicroBatcher(client, max_wait=0.003, max_batch=256)
    # steady state: warm codegen, device probe EMAs, and memo caches
    # before the measured window (a resident webhook is warm)
    driver = client.driver
    for bs in (32, 128, 256):
        batch = [r for r in reviews[:bs]]
        for _ in range(3):
            driver.review_batch(TARGET, batch)
    batcher.submit(reviews[0])
    # standard long-lived-server tuning: the warmed caches (features,
    # memos, codegen closures) are permanent; freezing them out of the
    # GC's scan set removes multi-ms gen-2 pauses from the tail
    import gc
    gc.collect()
    gc.freeze()

    n_requests = int(10_000 * SCALE)
    n_threads = 64
    latencies: list[float] = []
    lock = threading.Lock()

    def worker(k: int):
        lats = []
        for j in range(n_requests // n_threads):
            r = reviews[(k * 131 + j) % len(reviews)]
            t0 = time.time()
            batcher.submit(r)
            lats.append(time.time() - t0)
        with lock:
            latencies.extend(lats)

    t0 = time.time()
    threads = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.time() - t0
    batcher.stop()
    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p99 = latencies[int(len(latencies) * 0.99)]
    print(json.dumps({
        "config": 5, "metric": "admission_requests_per_sec",
        "value": round(len(latencies) / wall),
        "unit": f"req/s ({len(latencies)} reviews, {n_threads} concurrent "
                f"clients, micro-batched)",
        "p50_ms": round(p50 * 1000, 2), "p99_ms": round(p99 * 1000, 2),
        "batches": batcher.batches,
        "avg_batch": round(batcher.batched_requests /
                           max(1, batcher.batches), 1),
    }))


def main() -> None:
    which = [int(a) for a in sys.argv[1:]] or [1, 2, 3, 5]
    for c in which:
        {1: config1, 2: config2, 3: config3, 5: config5}[c]()


if __name__ == "__main__":
    main()
