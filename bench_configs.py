#!/usr/bin/env python
"""BASELINE.md configs #1, #2, #3, #5, #6, #7, #8 (config #4 is
bench.py's headline).

One JSON line per config:
  #1 requiredlabels x 1k Namespaces     — full audit wall-clock + the
     measured local interpreter (local-OPA stand-in) audit baseline
  #2 full shipped general library x 10k mixed objects — full audit
  #3 full shipped pod-security-policy library x 50k Pods (regex-heavy)
     — full audit
  #5 streaming admission vs the FULL general library, in tiers:
     pre-batched engine throughput (driver.review_batch), the same
     batches over the real gRPC wire (ReviewBatch RPC), the 64-client
     closed-loop micro-batcher harness, an OPEN-LOOP multi-process
     HTTP sweep against the real webhook server, and the serving
     plane (pre-forked frontends over the shared batching backplane)
     at 1/2/4 workers — the `admission_rps` headline
  #6 steady-state audit @ 1% churn — PSP library x 50k pods with ~1% of
     objects mutated between sweeps: incremental (journal-patched)
     sweep vs the full re-encode sweep
  #7 mutating admission: micro-batched /v1/mutate throughput + p50 at
     three mutator-library sizes (batched applicability matching +
     host apply-to-convergence + RFC-6902 patch generation)
  #8 resilience under overload: a 64-thread closed loop against a
     deliberately slowed flusher with a bounded queue and 2s propagated
     deadlines — shed/deadline fractions plus the worst decision
     latency as a fraction of the deadline (must stay < 1.0)
  #9 warm restart vs cold boot: time-to-ready at the config-6 inventory
     scale — restore the durable state snapshots (vocab + library +
     encoded inventory + tracker) and re-validate vs a live list,
     against the cold library-ingest + full list/encode resync path
  #10 multichip audit promotion at 1M+ objects: the default (no-flag)
     mesh-sharded audit path vs the forced single-device path, each in
     a fresh subprocess (on a 1-device host the mesh run forces 8
     host-platform devices so the slab pipeline is exercised)
  #12 compiler-widening speedup: per-kind steady audit latency on the
     extended-form corpus (upstream-canonical Rego shapes that were
     interpreter-bound before the PR 10 widening), interpreter driver
     vs the newly device-compiled path, plus the shipped general
     library's device coverage (general_library_compiled_fraction
     must read 1.0)
  #13 sharded inventory plane at 10M * BENCH_SCALE objects
     (BENCH_C13_OBJECTS overrides): the inventory consistent-hashed
     across 1/2/4 audit shard processes — spawn + slice-sync wall,
     the full composed-round wall (objects_per_sec headline), and the
     steady incremental round under ~0.1% routed churn, vs the
     unsharded single-client sweep
  #14 adaptive serving controller: an edge-bound closed loop from cold
     mis-tuned defaults (max_wait 50ms) with the controller armed must
     converge to within ~10% of the config-5 hand-tuned optimum's rps
     with the actuation flip count gated, survive a mid-burst engine
     kill with zero unanswered admissions, and restore the baseline
     knobs bit-exactly on the kill switch

All audits run steady-state through client.audit() (warm caches), same
contract as bench.py. Run: python bench_configs.py [1 2 3 5 6 7 8 9]
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

TARGET = "admission.k8s.gatekeeper.sh"
SCALE = float(os.environ.get("BENCH_SCALE", 1.0))  # shrink for smoke runs


def new_client(driver=None):
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    driver = driver or TpuDriver()
    return driver, Backend(driver).new_client([K8sValidationTarget()])


def compiled_coverage(drv, client) -> dict:
    """Per-library device coverage (ROADMAP item 4's tracked number):
    the fraction of ingested template kinds served by a device program
    (dense CompiledTemplate or inventory-join) rather than interpreter
    fallback — a compiler regression that silently demotes a kind shows
    up here as a fraction drop, not just a latency creep."""
    kinds = client.template_kinds()
    device = [k for k in kinds
              if (hasattr(drv, "compiled_for")
                  and drv.compiled_for(k) is not None)
              or (hasattr(drv, "join_for")
                  and drv.join_for(k) is not None)]
    return {
        "device_compiled_kinds": len(device),
        "total_kinds": len(kinds),
        "device_compiled_fraction":
            round(len(device) / max(1, len(kinds)), 3),
        "interpreter_kinds": sorted(set(kinds) - set(device)),
    }


def steady_audit(client, iters=3):
    t0 = time.time()
    resp = client.audit()
    first = time.time() - t0
    best = float("inf")
    for _ in range(iters):
        t0 = time.time()
        resp = client.audit()
        best = min(best, time.time() - t0)  # min-of-N: noise-robust
    return best, first, len(resp.results())


def audit_phase_breakdown(drv, client, iters=2) -> dict:
    """Per-phase attribution of one full (non-delta) steady sweep:
    sweep_wall_s (time blocked on the device), materialize_s (violation
    message assembly), status_write_s (streamed constraint-status
    publishing — 0 without an audit manager), and the headline
    materialize_vs_sweep ratio (ROADMAP item 3's gate: <= 1.0 means
    the steady audit is sweep-bound, not host-bound). The results
    delta cache is dropped per iteration so the full pipeline runs."""
    from gatekeeper_tpu.utils import profiling

    best: dict = {}
    best_wall = float("inf")
    for _ in range(iters):
        drop = getattr(drv, "_audit_results_cache", None)
        if drop is not None:
            drop.clear()
        snap0 = profiling.timers().snapshot()
        t0 = time.time()
        client.audit()
        wall = time.time() - t0
        phases = profiling.PhaseTimers.diff(snap0,
                                            profiling.timers().snapshot())
        if wall < best_wall:
            best_wall = wall
            best = phases
    sweep = best.get("device_sweep", 0.0)
    mat = best.get("materialize", 0.0)
    return {
        "full_sweep_wall_s": round(best_wall, 4),
        "sweep_wall_s": round(sweep, 4),
        "materialize_s": round(mat, 4),
        "status_write_s": round(best.get("status_write", 0.0), 4),
        "materialize_vs_sweep":
            round(mat / sweep, 3) if sweep > 0 else None,
        "interp_eval_s": round(best.get("interp_eval", 0.0), 4),
    }


# --------------------------------------------------------------- config 1


def config1():
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import RegoDriver
    from gatekeeper_tpu.parallel.workload import synth_objects

    n = int(1000 * SCALE)
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "must-own"},
        "spec": {"parameters": {"labels": [
            {"key": "owner", "allowedRegex": "^[a-z]+.corp.example$"}]}},
    }
    objs = synth_objects(n, violate_frac=0.02, seed=7)

    _, client = new_client()
    client.add_template(policies.load("general/requiredlabels"))
    client.add_constraint(constraint)
    for o in objs:
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)

    # the local-OPA stand-in baseline, measured on the SAME workload
    # (pure interpreter: codegen disabled)
    base_driver = RegoDriver()
    base_driver._codegen_for = lambda *a, **k: None
    _, base_client = new_client(base_driver)
    base_client.add_template(policies.load("general/requiredlabels"))
    base_client.add_constraint(constraint)
    for o in objs:
        base_client.add_data(o)
    t0 = time.time()
    base_n = len(base_client.audit().results())
    base_s = time.time() - t0
    assert base_n == nres
    print(json.dumps({
        "config": 1, "metric": "audit_wall_clock_s", "value": round(audit_s, 4),
        "unit": f"s (requiredlabels x {n} namespaces, steady state)",
        "baseline_interpreter_s": round(base_s, 3),
        "vs_baseline": round(base_s / audit_s, 1),
        "first_audit_s": round(first, 2), "violations": nres,
    }))


# --------------------------------------------------------------- config 2


def synth_mixed_objects(n: int, seed: int = 0) -> list[dict]:
    """Pods/Deployments/Ingresses/Services with fields the general
    library examines (images, limits/requests, labels, tls/hosts,
    selectors). ~2% violate something."""
    rng = random.Random(seed)
    repos = ["registry.corp.example/", "gcr.io/corp/"]
    out = []
    for i in range(n):
        kind = ("Pod", "Pod", "Pod", "Deployment", "Ingress",
                "Service")[i % 6]
        name = f"{kind.lower()}-{i}"
        labels = {"owner": "team.corp.example", "app": f"app{i % 50}"}
        bad = rng.random() < 0.02
        if kind == "Pod":
            image = (rng.choice(repos) + f"svc{i % 20}:v1"
                     if not bad else f"docker.io/evil{i}:latest")
            cpu = "900m" if not bad else "4"
            out.append({
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"containers": [{
                    "name": "main", "image": image,
                    "resources": {
                        "limits": {"cpu": cpu, "memory": "512Mi"},
                        "requests": {"cpu": "250m", "memory": "256Mi"}},
                }]},
            })
        elif kind == "Deployment":
            out.append({
                "apiVersion": "apps/v1", "kind": "Deployment",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"replicas": 2,
                         "selector": {"matchLabels": {"app": f"app{i}"}}},
            })
        elif kind == "Ingress":
            spec = {"rules": [{"host": f"h{i}.corp.example"}]}
            meta = {"name": name, "namespace": f"ns{i % 20}",
                    "labels": labels}
            if not bad:
                spec["tls"] = [{"hosts": [f"h{i}.corp.example"]}]
                meta["annotations"] = {
                    "kubernetes.io/ingress.allow-http": "false"}
            out.append({"apiVersion": "networking.k8s.io/v1beta1",
                        "kind": "Ingress", "metadata": meta, "spec": spec})
        else:
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": name, "namespace": f"ns{i % 20}",
                             "labels": labels},
                "spec": {"selector": {"app": f"app{i}"},
                         "ports": [{"port": 80}]},
            })
    return out


GENERAL_CONSTRAINTS = [
    ("K8sAllowedRepos", "repos-allowed",
     {"repos": ["registry.corp.example/", "gcr.io/corp/"]}),
    ("K8sContainerLimits", "limits-capped", {"cpu": "2", "memory": "1Gi"}),
    ("K8sContainerRatios", "ratio-capped", {"ratio": "4"}),
    ("K8sHttpsOnly", "https-only", None),
    ("K8sRequiredLabels", "must-own",
     {"labels": [{"key": "owner",
                  "allowedRegex": "^[a-z]+.corp.example$"}]}),
    ("K8sUniqueIngressHost", "unique-hosts", None),
    ("K8sUniqueServiceSelector", "unique-selectors", None),
]


def config2():
    from gatekeeper_tpu import policies

    n = int(10_000 * SCALE)
    drv, client = new_client()
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    for kind, cname, params in GENERAL_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in synth_mixed_objects(n):
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)
    phases = audit_phase_breakdown(drv, client)
    print(json.dumps({
        "config": 2, "metric": "audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": f"s (full general library, {len(GENERAL_CONSTRAINTS)} "
                f"constraints x {n} mixed objects, steady state)",
        "first_audit_s": round(first, 2), "violations": nres,
        **phases,
        **compiled_coverage(drv, client),
    }))


# --------------------------------------------------------------- config 3


def synth_pods_psp(n: int, seed: int = 0) -> list[dict]:
    """Pod specs exercising the PSP library's fields; ~3% violate."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        bad = rng.random() < 0.03
        ctx = {"allowPrivilegeEscalation": False,
               "readOnlyRootFilesystem": True,
               "runAsUser": 1000 + (i % 1000),
               "capabilities": {"drop": ["ALL"]}}
        if bad:
            kind_of_bad = rng.randrange(5)
            if kind_of_bad == 0:
                ctx["privileged"] = True
            elif kind_of_bad == 1:
                ctx["runAsUser"] = 0
            elif kind_of_bad == 2:
                ctx["capabilities"] = {"add": ["SYS_ADMIN"], "drop": []}
            elif kind_of_bad == 3:
                ctx.pop("readOnlyRootFilesystem")
            else:
                ctx["allowPrivilegeEscalation"] = True
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {
                "name": f"pod-{i}", "namespace": f"ns{i % 40}",
                "annotations": {
                    "seccomp.security.alpha.kubernetes.io/pod":
                        "runtime/default",
                    "container.apparmor.security.beta.kubernetes.io/main":
                        "runtime/default",
                },
            },
            "spec": {
                "securityContext": {"fsGroup": 2000,
                                    "sysctls": ([{"name": "net.ipv4.ip_local_port_range", "value": "1024 65535"}]
                                                if i % 7 else [{"name": "kernel.msgmax", "value": "1"}])},
                "containers": [{
                    "name": "main",
                    "image": f"registry.corp.example/app{i % 100}:v1",
                    "securityContext": ctx,
                    "ports": ([{"hostPort": 8080 + (i % 100)}]
                              if i % 11 == 0 else []),
                }],
                "volumes": [{"name": "cfg", "configMap": {"name": "c"}}] +
                           ([{"name": "h", "hostPath":
                              {"path": f"/var/log/app{i}"}}]
                            if i % 13 == 0 else []),
            },
        }
        out.append(pod)
    return out


PSP_CONSTRAINTS = [
    ("K8sPSPAllowPrivilegeEscalationContainer", "no-escalation", None),
    ("K8sPSPAppArmor", "apparmor-default",
     {"allowedProfiles": ["runtime/default"]}),
    ("K8sPSPCapabilities", "caps",
     {"allowedCapabilities": ["NET_BIND_SERVICE"],
      "requiredDropCapabilities": ["ALL"]}),
    ("K8sPSPFlexVolumes", "flex", {"allowedFlexVolumes": []}),
    ("K8sPSPForbiddenSysctls", "sysctls",
     {"forbiddenSysctls": ["kernel.*", "vm.swappiness"]}),
    ("K8sPSPFSGroup", "fsgroup",
     {"rule": "MustRunAs", "ranges": [{"min": 1000, "max": 65535}]}),
    ("K8sPSPHostFilesystem", "hostfs",
     {"allowedHostPaths": [{"pathPrefix": "/var/log", "readOnly": True}]}),
    ("K8sPSPHostNamespace", "no-host-ns", None),
    ("K8sPSPHostNetworkingPorts", "host-ports",
     {"hostNetwork": False, "min": 8000, "max": 9000}),
    ("K8sPSPPrivilegedContainer", "no-privileged", None),
    ("K8sPSPProcMount", "procmount", {"procMount": "Default"}),
    ("K8sPSPReadOnlyRootFilesystem", "ro-root", None),
    ("K8sPSPSeccomp", "seccomp",
     {"allowedProfiles": ["runtime/default", "docker/default"]}),
    ("K8sPSPSELinux", "selinux",
     {"allowedSELinuxOptions": {"level": "s0:c123,c456"}}),
    ("K8sPSPAllowedUsers", "users",
     {"runAsUser": {"rule": "MustRunAsNonRoot"}}),
    ("K8sPSPVolumeTypes", "volumes",
     {"volumes": ["configMap", "secret", "emptyDir", "hostPath"]}),
]


def config3():
    from gatekeeper_tpu import policies

    n = int(50_000 * SCALE)
    drv, client = new_client()
    for name in policies.names():
        if name.startswith("pod-security-policy/"):
            client.add_template(policies.load(name))
    for kind, cname, params in PSP_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in synth_pods_psp(n):
        client.add_data(o)
    audit_s, first, nres = steady_audit(client)
    phases = audit_phase_breakdown(drv, client)
    # the tentpole's tracked number: cold restart (no cache volume) vs
    # warm restart (populated XLA cache + AOT program store) first
    # audit, each in a fresh subprocess
    coldwarm = coldwarm_probe("3")
    print(json.dumps({
        "config": 3, "metric": "audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": f"s (full pod-security-policy library, "
                f"{len(PSP_CONSTRAINTS)} constraints x {n} pods, "
                f"steady state)",
        "first_audit_s": round(first, 2), "violations": nres,
        **phases,
        **compiled_coverage(drv, client),
        **coldwarm,
    }))


# ------------------------------------------------ cold vs warm first audit


def _coldwarm_child(workload: str) -> None:
    """Child process for the cold-vs-warm first-audit probe: build the
    named workload, run ONE audit, print first-audit wall clock + the
    compile source counts (aot=deserialized executable, cache=
    persistent-XLA-cache compile, fresh=cold compile). The parent
    controls cold vs warm purely through the env cache dirs
    (JAX_COMPILATION_CACHE_DIR + GATEKEEPER_TPU_AOT_DIR): an empty dir
    is a cold boot, a populated one is exactly how a restarted pod with
    a cache volume boots."""
    from gatekeeper_tpu.ir import aot

    drv, client = new_client()
    if workload == "3":
        from gatekeeper_tpu import policies

        n = int(50_000 * SCALE)
        for name in policies.names():
            if name.startswith("pod-security-policy/"):
                client.add_template(policies.load(name))
        for kind, cname, params in PSP_CONSTRAINTS:
            client.add_constraint({
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind, "metadata": {"name": cname},
                "spec": ({"parameters": params} if params else {}),
            })
        for o in synth_pods_psp(n):
            client.add_data(o)
    else:  # "4": the bench.py headline workload
        from gatekeeper_tpu.parallel.workload import (
            REQUIRED_LABELS_TEMPLATE,
            synth_constraints,
            synth_objects,
        )

        n = int(int(os.environ.get("BENCH_OBJECTS", 100_000)) * SCALE)
        ncons = int(os.environ.get("BENCH_CONSTRAINTS", 500))
        client.add_template(REQUIRED_LABELS_TEMPLATE)
        for c in synth_constraints(ncons, seed=1):
            client.add_constraint(c)
        for o in synth_objects(n, violate_frac=0.01, seed=0):
            client.add_data(o)
    if drv.aot.programs_count():
        # warm boot: give the ingest-time background prewarm a beat to
        # deserialize + adopt the stored sweep signatures (a cold boot
        # has nothing to load and proceeds immediately)
        time.sleep(1.0)
    t0 = time.time()
    resp = client.audit()
    first = time.time() - t0
    # drain background compiles so this run's store is fully populated
    # before the parent launches the warm run against it
    t0w = time.time()
    while drv.warm_status()["compiling"] and time.time() - t0w < 600:
        time.sleep(0.2)
    print(json.dumps({"first_audit_s": round(first, 3),
                      "violations": len(resp.results()),
                      "compile_sources": dict(aot.COMPILE_COUNTS)}))


def coldwarm_probe(workload: str) -> dict:
    """Cold-vs-warm first-audit measurement (the tentpole's tracked
    number): run the workload child twice in fresh subprocesses against
    the same initially-empty compile-cache + AOT dirs. Run 1 pays every
    XLA compile (cold restart with no cache volume); run 2 boots the
    way a restarted pod with the populated volume does — deserialize
    and go."""
    import shutil
    import subprocess
    import tempfile

    tmp = tempfile.mkdtemp(prefix="gk-coldwarm-")
    out: dict = {}
    try:
        env = dict(os.environ)
        env["JAX_COMPILATION_CACHE_DIR"] = os.path.join(tmp, "xla")
        env["GATEKEEPER_TPU_AOT_DIR"] = os.path.join(tmp, "aot")
        for run in ("cold", "warm"):
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--coldwarm-child", workload],
                    capture_output=True, text=True, env=env,
                    timeout=int(os.environ.get("BENCH_COLDWARM_TIMEOUT",
                                               1800)))
            except subprocess.TimeoutExpired:
                out[f"{run}_error"] = "timeout"
                break
            lines = [ln for ln in r.stdout.splitlines()
                     if ln.startswith("{")]
            if not lines:
                out[f"{run}_error"] = (r.stderr or "")[-300:]
                break
            d = json.loads(lines[-1])
            out[f"{run}_first_audit_s"] = d["first_audit_s"]
            out[f"{run}_compile_sources"] = d["compile_sources"]
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


# --------------------------------------------------------------- config 6


def config6():
    """Steady-state audit under churn (the recurring-sweep reality: most
    of the cluster does NOT change between 60s sweeps). PSP library x
    50k pods; ~1% of objects mutate between sweeps. Incremental sweep
    (the driver's journal patches dirty feature/mask rows in place)
    vs the full re-encode sweep (drop_inventory_caches: re-flatten,
    re-extract, re-upload everything) on the same client."""
    import copy

    n = int(50_000 * SCALE)
    churn = max(1, int(n * 0.01))
    drv, client = new_client()
    from gatekeeper_tpu import policies

    for name in policies.names():
        if name.startswith("pod-security-policy/"):
            client.add_template(policies.load(name))
    for kind, cname, params in PSP_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    pods = synth_pods_psp(n)
    for o in pods:
        client.add_data(o)
    _warm, first, nres = steady_audit(client, iters=1)
    # wait out the async device warm-up: both timed paths below must
    # measure the steady-state device pipeline, not the host fallback
    # that serves while XLA compiles in the background
    t0 = time.time()
    while hasattr(drv, "warm_status") and \
            drv.warm_status()["compiling"] and time.time() - t0 < 600:
        time.sleep(0.2)
    client.audit()

    rng = random.Random(6)

    def mutate(round_):
        """In-place replacement of ~1% of pods: churned label values
        (vocabulary-growing strings) but unchanged structure, the shape
        the patch journal must absorb without a rebuild."""
        for i in rng.sample(range(n), churn):
            pod = copy.deepcopy(pods[i])
            pod["metadata"].setdefault("labels", {})["churn"] = \
                f"r{round_}-{i}"
            client.add_data(pod)

    inc_s = float("inf")
    for k in range(3):
        mutate(k)
        t0 = time.time()
        r = client.audit()
        inc_s = min(inc_s, time.time() - t0)
    n_inc = len(r.results())

    full_s = float("inf")
    for k in range(3):
        mutate(100 + k)
        drv.drop_inventory_caches()
        t0 = time.time()
        r = client.audit()
        full_s = min(full_s, time.time() - t0)
    n_full = len(r.results())

    print(json.dumps({
        "config": 6, "metric": "churn_audit_wall_clock_s",
        "value": round(inc_s, 3),
        "unit": f"s (pod-security-policy library, {len(PSP_CONSTRAINTS)} "
                f"constraints x {n} pods, ~1% churn between sweeps, "
                "incremental steady state)",
        "full_reencode_s": round(full_s, 3),
        "speedup_vs_full": round(full_s / inc_s, 1),
        "churned_objects": churn,
        "first_audit_s": round(first, 2),
        "violations": n_inc,
        "violations_full_path": n_full,
    }))


# --------------------------------------------------------------- config 9


def config9():
    """Warm restart vs cold boot (statestore tentpole): time-to-ready at
    the config-6 inventory scale. Cold boot = ingest the PSP library and
    full-resync the tracker (list every object, add_data each through
    the target handler: the O(cluster) path every restart used to pay).
    Warm boot = restore the vocab/library/inventory snapshots + tracker
    state, then re-validate against a live (uid, resourceVersion) diff
    list — no per-object re-encode. Also reports both first-audit times
    (the warm one can adopt the snapshotted encoded feature rows)."""
    import shutil
    import tempfile

    from gatekeeper_tpu import policies
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube
    from gatekeeper_tpu.control.statestore import (
        StateStore,
        restore_section,
    )

    n = int(50_000 * SCALE)
    kube = FakeKube()
    kube.register_kind(("", "v1", "Pod"))
    kube.register_kind(("", "v1", "Namespace"))
    for i in range(40):
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"ns{i}"}})
    for pod in synth_pods_psp(n):
        kube.create(pod)

    def ingest_library(client):
        for name in policies.names():
            if name.startswith("pod-security-policy/"):
                client.add_template(policies.load(name))
        for kind, cname, params in PSP_CONSTRAINTS:
            client.add_constraint({
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind, "metadata": {"name": cname},
                "spec": ({"parameters": params} if params else {}),
            })

    # ---- cold boot: the path every restart used to pay -------------
    t0 = time.time()
    drv, client = new_client()
    ingest_library(client)
    am = AuditManager(kube, client, incremental=True,
                      gc_stale_statuses=False)
    from gatekeeper_tpu.control.audit import (
        InventoryTracker,
        _auditable_gvks,
    )

    am.tracker = InventoryTracker(kube, client)
    am.tracker.full_resync(_auditable_gvks(kube))
    cold_s = time.time() - t0
    t0 = time.time()
    client.audit()
    cold_audit_s = time.time() - t0

    # ---- snapshot (what the periodic/drain snapshot persists) ------
    state_dir = tempfile.mkdtemp(prefix="gk-state-")
    try:
        store = StateStore(state_dir)
        inv = {"tree": drv.inventory_snapshot() or {},
               "tracker": am.tracker.snapshot()}
        store.save_blob("inventory", inv, codec="marshal")
        store.save("library", client.snapshot_library())
        rows = drv.encoded_rows_snapshot()
        if rows:
            store.save_blob("rows", rows)
        store.save("vocab", drv.vocab_snapshot())
        am.tracker.stop()

        # ---- warm boot: restore + live-list re-validation ----------
        # min of 3 restore cycles, like every other warm measurement
        # here: a single sample of a sub-second restore on a shared
        # 1-core host is GC/scheduler-bimodal (0.4s vs 3.5s observed
        # back to back at identical code)
        import gc

        warm_samples = []
        drv2 = client2 = am2 = None
        retired_drivers = []
        for _ in range(3):
            if am2 is not None:
                am2.tracker.stop()
                retired_drivers.append(drv2)
            gc.collect()  # the cold path's garbage must not bill here
            t0 = time.time()
            drv2, client2 = new_client()
            vocab_ok = restore_section(store, "vocab",
                                       drv2.vocab_restore)
            restore_section(store, "library", client2.restore_library)
            am2 = AuditManager(kube, client2, incremental=True,
                              gc_stale_statuses=False)

            def apply_inventory(snap):
                drv2.inventory_restore(snap.get("tree") or {})
                am2.restore_state(snap.get("tracker") or {})

            restored = restore_section(store, "inventory",
                                       apply_inventory, blob=True)
            if am2.tracker is None:
                # restore fell back (corrupt/torn snapshot): the bench
                # must degrade to the cold path like the product, not
                # crash
                am2.tracker = InventoryTracker(kube, client2)
                am2.tracker.full_resync(_auditable_gvks(kube))
            stats = am2.tracker.apply_pending()  # (uid, rv) re-valid.
            warm_samples.append(time.time() - t0)
        warm_s = min(warm_samples)
        # encoded rows load rides a background thread in the runtime
        # (first-audit optimization, not a readiness dependency) —
        # restored synchronously here so the adopted-rows first audit
        # below is deterministic
        if restored and vocab_ok and rows:
            restore_section(store, "rows", drv2.encoded_rows_restore,
                            blob=True)
        t0 = time.time()
        client2.audit()
        warm_audit_s = time.time() - t0
        adopted = getattr(drv2, "restored_rows_adopted", 0)
        am2.tracker.stop()
        # wait out any background device warm-up before teardown (an
        # XLA compile thread killed at interpreter exit aborts)
        t0 = time.time()
        for d in (drv, drv2, *retired_drivers):
            while hasattr(d, "warm_status") and \
                    d.warm_status()["compiling"] and time.time() - t0 < 600:
                time.sleep(0.2)
    finally:
        shutil.rmtree(state_dir, ignore_errors=True)

    print(json.dumps({
        "config": 9, "metric": "warm_boot_s",
        "value": round(warm_s, 3),
        "unit": f"s (restore snapshots + live-list re-validation to "
                f"ready, min of 3 restore cycles, PSP library x {n} "
                "pods; cold = library ingest + full list/encode "
                "resync)",
        "cold_boot_s": round(cold_s, 3),
        "speedup_vs_cold": round(cold_s / warm_s, 1) if warm_s else None,
        "warm_first_audit_s": round(warm_audit_s, 3),
        "cold_first_audit_s": round(cold_audit_s, 3),
        "encoded_row_kinds_adopted": adopted,
        "revalidated_dirty": stats["dirty"],
        "inventory": stats["total"],
    }))


# --------------------------------------------------------------- config 7


def _synth_mutators(n: int) -> list[dict]:
    """A mutator library shaped like real fleets: imagePullPolicy /
    metadata-label / toleration mutators with varied match selectors so
    applicability actually discriminates across the batch."""
    out = []
    for i in range(n):
        shape = i % 3
        if shape == 0:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "Assign",
                "metadata": {"name": f"pull-policy-{i}"},
                "spec": {
                    "applyTo": [{"groups": [""], "versions": ["v1"],
                                 "kinds": ["Pod"]}],
                    "match": {"kinds": [{"apiGroups": [""],
                                         "kinds": ["Pod"]}],
                              "namespaces": [f"ns{i % 20}"]},
                    "location": "spec.containers[name: *].imagePullPolicy",
                    "parameters": {"assign": {"value": "IfNotPresent"}},
                },
            })
        elif shape == 1:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "AssignMetadata",
                "metadata": {"name": f"owner-label-{i}"},
                "spec": {
                    "match": {"labelSelector":
                              {"matchLabels": {"app": f"app{i % 50}"}}},
                    "location": f"metadata.labels.injected-{i}",
                    "parameters": {"assign": {"value": f"v{i}"}},
                },
            })
        else:
            out.append({
                "apiVersion": "mutations.gatekeeper.sh/v1alpha1",
                "kind": "ModifySet",
                "metadata": {"name": f"tolerations-{i}"},
                "spec": {
                    "applyTo": [{"groups": [""], "versions": ["v1"],
                                 "kinds": ["Pod"]}],
                    "match": {"kinds": [{"apiGroups": [""],
                                         "kinds": ["Pod"]}]},
                    "location": "spec.tolerations",
                    "parameters": {
                        "operation": "merge",
                        "values": {"fromList": [
                            {"key": f"pool-{i % 4}",
                             "operator": "Exists"}]},
                    },
                },
            })
    return out


def config7():
    """Mutating admission (micro-batched /v1/mutate) at three
    mutator-library sizes: per-batch applicability rides the vectorized
    matcher once per micro-batch, the host applies matched mutators to
    convergence, and the handler emits the RFC-6902 patch. Headline
    `mutate_s` is the wall-clock of one 512-review batched mutation
    pass at the largest library; p50 comes from a 32-thread closed loop
    through the real MutationHandler (envelope + patch encode
    included)."""
    import threading

    from gatekeeper_tpu.control.webhook import MutationHandler
    from gatekeeper_tpu.mutation import MutationSystem

    sizes = [max(1, int(s * SCALE)) for s in (30, 150, 600)]
    n_reviews = max(16, int(512 * SCALE))
    reviews = _mixed_reviews(n_reviews, seed=11)
    per_size = []
    mutate_s = None
    p50_ms = None
    for n_mut in sizes:
        system = MutationSystem()
        for m in _synth_mutators(n_mut):
            system.upsert(m)
        assert not system.conflicts(), "synthetic library must be clean"
        # --- batched engine path: one vectorized applicability sweep +
        # host convergence for the whole batch
        system.mutate_batch(reviews)  # warm matcher signature caches
        best = float("inf")
        n_batched = 0
        t_all = time.time()
        while time.time() - t_all < 2.0:
            t0 = time.time()
            outs = system.mutate_batch(reviews)
            best = min(best, time.time() - t0)
            n_batched += len(outs)
        batched_rps = n_batched / (time.time() - t_all)
        # --- closed loop through the real handler (micro-batcher +
        # JSONPatch emission), 32 in-process clients
        handler = MutationHandler(system, batch_max_wait=0.003)
        payloads = [{"apiVersion": "admission.k8s.io/v1",
                     "kind": "AdmissionReview",
                     "request": dict(r, uid=f"u{k}",
                                     userInfo={"username": "bench"})}
                    for k, r in enumerate(reviews)]
        handler.handle(payloads[0])  # warm the flusher
        lats: list = []
        lock = threading.Lock()
        n_req = max(64, int(4000 * SCALE))
        n_threads = 32

        def worker(k: int):
            mine = []
            for j in range(n_req // n_threads):
                t0 = time.time()
                handler.handle(payloads[(k * 131 + j) % len(payloads)])
                mine.append(time.time() - t0)
            with lock:
                lats.extend(mine)

        t0 = time.time()
        ths = [threading.Thread(target=worker, args=(k,))
               for k in range(n_threads)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        wall = time.time() - t0
        handler.batcher.stop()
        lats.sort()
        entry = {
            "mutators": n_mut,
            "mutate_s": round(best, 4),
            "batched_reviews_per_sec": round(batched_rps),
            "handler_rps": round(len(lats) / wall),
            "p50_ms": round(lats[len(lats) // 2] * 1000, 2),
            "p99_ms": round(lats[int(len(lats) * 0.99)] * 1000, 2),
        }
        per_size.append(entry)
        mutate_s = entry["mutate_s"]  # largest library wins (last)
        p50_ms = entry["p50_ms"]
    print(json.dumps({
        "config": 7, "metric": "mutate_batch_wall_clock_s",
        "value": mutate_s,
        "unit": f"s (one {n_reviews}-review micro-batch mutated vs a "
                f"{sizes[-1]}-mutator library: vectorized applicability "
                "+ host convergence)",
        "mutate_s": mutate_s,
        "p50_ms": p50_ms,
        "reviews_per_batch": n_reviews,
        "sizes": per_size,
    }))


# --------------------------------------------------------------- config 5


def _general_library_client():
    from gatekeeper_tpu import policies

    driver, client = new_client()
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    for kind, cname, params in GENERAL_CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    return driver, client


def _mixed_reviews(n=512, seed=3):
    reviews = []
    for o in synth_mixed_objects(n, seed=seed):
        meta = o.get("metadata", {})
        r = {"kind": {"group": o["apiVersion"].rpartition("/")[0],
                      "version": o["apiVersion"].rpartition("/")[2],
                      "kind": o["kind"]},
             "name": meta.get("name", ""), "object": o,
             "operation": "CREATE"}
        if "namespace" in meta:
            r["namespace"] = meta["namespace"]
        reviews.append(r)
    return reviews


def _loadgen_child(port: int, rate: float, duration: float,
                   seed: int, out_path: str) -> None:
    """OPEN-LOOP load generator (run as its own process so client work
    never shares the server's GIL): arrivals on a fixed schedule at
    `rate` req/s regardless of response latency; each arrival is fired
    by a pool thread and its latency recorded. Unsustained rates show
    up as queue growth -> unbounded p99, not as a throttled client.

    The client is a RAW keep-alive HTTP/1.1 socket, not http.client:
    at webhook payload sizes the stdlib client costs more CPU than the
    request being measured, and on a small host that skews every rate
    downward (the loadgen starves the server it is probing)."""
    import gc
    import queue as _q
    import socket as _socket
    import threading

    reviews = _mixed_reviews(256, seed=seed)
    payloads = []
    for k, r in enumerate(reviews):
        body = json.dumps({
            "apiVersion": "admission.k8s.io/v1beta1",
            "kind": "AdmissionReview",
            "request": dict(r, uid=f"u{k}",
                            userInfo={"username": "bench"})}).encode()
        payloads.append(
            b"POST /v1/admit HTTP/1.1\r\nHost: bench\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: %d\r\n\r\n%s" % (len(body), body))
    n = max(1, int(rate * duration))
    lat: list = []
    errors = [0]
    lock = threading.Lock()
    work: "_q.Queue" = _q.Queue()
    # the loadgen allocates no cycles (append-only latency lists); a
    # gen-2 GC pause here would be RECORDED as server latency
    gc.disable()

    def runner():
        conn = rfile = None

        def connect():
            nonlocal conn, rfile
            conn = _socket.create_connection(("127.0.0.1", port),
                                             timeout=30)
            conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            rfile = conn.makefile("rb", 65536)

        while True:
            item = work.get()
            if item is None:
                return
            t_sched, payload = item
            try:
                if conn is None:
                    connect()
                conn.sendall(payload)
                line = rfile.readline(65537)
                if not line:
                    raise ConnectionError("server closed")
                clen = 0
                while True:
                    h = rfile.readline(65537)
                    if h in (b"\r\n", b"\n", b""):
                        break
                    if h[:15].lower() == b"content-length:":
                        clen = int(h[15:])
                if clen:
                    rfile.read(clen)
            except (OSError, ValueError):
                # count, reconnect, keep the thread alive — a dead pool
                # thread would silently skew the whole rate's numbers
                with lock:
                    errors[0] += 1
                try:
                    if conn is not None:
                        conn.close()
                except OSError:
                    pass
                conn = rfile = None
                continue
            now = time.time()
            with lock:
                lat.append((now - t_sched, now))

    # enough pool threads that the schedule never starves on slow
    # responses (open-loop: concurrency grows when the server lags)
    pool = [threading.Thread(target=runner, daemon=True)
            for _ in range(64)]
    for t in pool:
        t.start()
    t0 = time.time()
    # loadgen honesty: track how far the SCHEDULER itself fell behind
    # its arrival schedule. On a small host the generator shares cores
    # with the server it probes; when the enqueue loop lags, "achieved
    # < offered" is the GENERATOR's ceiling, not the serving plane's —
    # the sweep records that explicitly instead of letting a loadgen
    # limit masquerade as an edge limit (the BENCH_r05 1-core trap)
    sched_lag = 0.0
    for j in range(n):
        t_sched = t0 + j / rate
        now = time.time()
        if t_sched > now:
            time.sleep(t_sched - now)
        elif now - t_sched > sched_lag:
            sched_lag = now - t_sched
        work.put((t_sched, payloads[j % len(payloads)]))
    deadline = time.time() + 30
    while not work.empty() and time.time() < deadline:
        time.sleep(0.05)
    for _ in pool:
        work.put(None)
    for t in pool:
        t.join(timeout=5)
    # threads that outlived the join timeout may still append: snapshot
    # under the lock so done/latencies/last_done agree with each other
    with lock:
        snap = list(lat)
        n_err = errors[0]
    with open(out_path, "w") as f:
        json.dump({"sent": n, "done": len(snap), "t0": t0,
                   "errors": n_err,
                   "sched_lag_s": round(sched_lag, 4),
                   "latencies": [x[0] for x in snap],
                   "last_done": max((x[1] for x in snap), default=t0)}, f)


def _engine_child(socket_path: str, decision_cache: bool = True) -> None:
    """The serving plane's ENGINE process: full general-library client
    + the shared MicroBatcher behind a BackplaneEngine on a Unix
    socket. Pre-forked frontends (control/backplane.py __main__)
    forward parsed-but-undecoded reviews here, so requests from every
    frontend coalesce into the same device micro-batches.

    decision_cache=False spawns the evaluation-honest variant: the
    bulk tier's repeated payload shapes would otherwise serve from the
    generation-keyed cache, so the gated series would measure cache
    hits, not evaluation (the PR 14 tiers_note caveat)."""
    import threading

    from gatekeeper_tpu.control.backplane import BackplaneEngine
    from gatekeeper_tpu.control.webhook import (
        MicroBatcher, NamespaceLabelHandler, ValidationHandler)

    from gatekeeper_tpu.control import metrics as gmetrics

    _, client = _general_library_client()
    batcher = MicroBatcher(client, max_wait=0.003, max_batch=256)
    validation = ValidationHandler(
        client, kube=None, batcher=batcher,
        decision_cache_size=4096 if decision_cache else 0)
    # warm the evaluator, then signal readiness on stdout
    client.driver.review_batch(TARGET, _mixed_reviews(64, seed=9))
    import gc
    gc.collect()
    gc.freeze()
    engine = BackplaneEngine(socket_path, validation=validation,
                             ns_label=NamespaceLabelHandler(()))
    engine.start()
    # capacity attribution during the bench: this engine serves
    # /metrics (ephemeral port, announced on the READY line) with the
    # saturation probes armed, so one scrape mid-sweep reads batch
    # fill/seal reasons, queue depths, and the eval duty cycle
    gmetrics.register_saturation_probe(
        "admission-queue",
        lambda: gmetrics.report_queue_depth("admission",
                                            batcher.pending()))
    drv = client.driver
    if hasattr(drv, "duty_cycle"):
        gmetrics.register_saturation_probe(
            "engine-duty-cycle",
            lambda: gmetrics.report_duty_cycle(drv.duty_cycle()))
    mport = 0
    try:
        mserver = gmetrics.serve(0, addr="127.0.0.1")
        mport = mserver.server_address[1]
    except OSError:
        pass
    print(f"READY {mport}", flush=True)
    threading.Event().wait()


def _run_sweep(port, rates, n_procs, duration, here):
    import subprocess
    import tempfile

    sweep = []
    sustained = None
    for total_rate in rates:
        outs = []
        procs = []
        for k in range(n_procs):
            f = tempfile.NamedTemporaryFile("w", suffix=".json",
                                            delete=False)
            f.close()
            outs.append(f.name)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--loadgen", str(port),
                 str(total_rate / n_procs), str(duration), str(k),
                 f.name],
                cwd=here))
        for p in procs:
            try:
                p.wait(timeout=duration + 90)
            except subprocess.TimeoutExpired:
                # a wedged loadgen must not lose the whole config: kill
                # the stragglers and fold in whatever results exist
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                break
        lats: list = []
        sent = done = n_err = 0
        span = duration
        sched_lag = 0.0
        for path in outs:
            try:
                with open(path) as f:
                    d = json.load(f)
                sent += d["sent"]
                done += d["done"]
                n_err += d.get("errors", 0)
                sched_lag = max(sched_lag, d.get("sched_lag_s", 0.0))
                lats.extend(d["latencies"])
                span = max(span, d["last_done"] - d["t0"])
            except ValueError:
                pass  # killed child: empty/partial file
            finally:
                os.unlink(path)
        lats.sort()
        if not lats:
            break
        achieved = round(done / span)
        p99 = round(lats[int(len(lats) * 0.99)] * 1000, 1)
        entry = {"offered_rps": total_rate,
                 "achieved_rps": achieved,
                 "p50_ms": round(lats[len(lats) // 2] * 1000, 1),
                 "p99_ms": p99,
                 "completed": done, "sent": sent, "errors": n_err}
        if sched_lag > 0.25:
            entry["sched_lag_s"] = round(sched_lag, 3)
        # the GENERATOR topped out, not the plane: everything it sent
        # completed, fast, yet the achieved rate undershot the offer —
        # the arrival schedule itself fell behind. An edge improvement
        # must not be judged against (or masked by) this entry.
        if (achieved < 0.9 * total_rate and n_err == 0
                and done >= 0.95 * sent
                and (p99 < 100 or sched_lag > 0.25)):
            entry["loadgen_limited"] = True
        sweep.append(entry)
        # SLO: p99 under 100ms and the offered schedule kept up with
        if entry["p99_ms"] < 100 and done >= 0.95 * sent:
            sustained = entry
        elif sustained is not None:
            break  # past the knee: stop sweeping
    return sweep, sustained


def c5_skip_record(counts: list, cores: int, forced: bool,
                   env_key: str, what: str):
    """Why a config-5 subprocess sweep will not run on this host, as an
    explicit {"skipped": reason} record — or None to run it. Every
    skip path MUST produce a record: a silent [] in the headline JSON
    is indistinguishable from "measured and got nothing" (exactly what
    hid the single-core gap in BENCH_r05)."""
    if not counts:
        return {"skipped": f"{env_key} is empty"}
    if cores < 2 and not forced:
        return {"skipped": f"{cores} host core(s): {what} would "
                           f"time-share one core (set {env_key} to "
                           "force)"}
    return None


def sweep_or_skip(entries: list, what: str) -> list:
    """Backstop for the headline JSON: a sweep list that somehow ended
    up empty ships an explicit record instead of a bare []."""
    if not entries:
        entries.append({"skipped": f"{what} produced no entries "
                                   "(unexpected: no skip record was "
                                   "recorded either)"})
    return entries


def config5():
    """Streaming admission (BASELINE config #5) measured three ways:
    1. engine: pre-batched reviews through driver.review_batch — the
       evaluator's capability with batching amortized (the gRPC
       service's pre-batched ingest path);
    2. open-loop HTTP: multi-process load generators with scheduled
       arrivals against the real webhook server, swept upward until
       p99 degrades — one worker's sustainable rate, then an
       SO_REUSEPORT multi-worker group's (the one-node replica story);
    3. the serving plane: 1/2/4 pre-forked frontends over the shared
       batching backplane (the --admission-workers topology), swept
       open-loop — the headline `admission_rps`;
    4. the documented ceiling: highest swept rate meeting the SLO.
    """
    import subprocess

    driver, client = _general_library_client()
    reviews = _mixed_reviews(512, seed=3)

    # --- 1. engine capability: pre-batched throughput ------------------
    driver_batches = [reviews[i:i + 256]
                      for i in range(0, len(reviews), 256)]
    for b in driver_batches:  # warm codegen/memos/EMAs
        driver.review_batch(TARGET, b)
    import gc
    gc.collect()
    gc.freeze()
    n_eng = 0
    t0 = time.time()
    while time.time() - t0 < 3.0:
        for b in driver_batches:
            driver.review_batch(TARGET, b)
            n_eng += len(b)
    engine_rps = n_eng / (time.time() - t0)

    # the same pre-batched reviews over the REAL gRPC wire (the
    # production comm backend at the Driver seam): adds JSON + protobuf
    # framing and the localhost round-trip. The STREAM tier pipelines
    # the same batches over one bidirectional HTTP/2 stream
    # (ReviewStream) — no per-RPC round trip between batches; it is
    # the bulk-ingest successor path the trend watchdog gates.
    grpc_rps = None
    grpc_stream_rps = None
    server = rc = None
    try:
        from gatekeeper_tpu.service import RemoteClient, make_server

        server, port = make_server(client=client)
        server.start()
        rc = RemoteClient(f"127.0.0.1:{port}")
        # plain review dicts ride the "raw" wire path, so the server
        # evaluates byte-identical reviews to the engine tier — the
        # delta between the two numbers is wire framing + RPC, nothing
        # else
        for wb in driver_batches:  # warm the wire path
            rc.review_batch(wb)
        n_wire = 0
        t0 = time.time()
        while time.time() - t0 < 3.0:
            for wb in driver_batches:
                rc.review_batch(wb)
                n_wire += len(wb)
        grpc_rps = n_wire / (time.time() - t0)

        def stream_batches(stop_at):
            while time.time() < stop_at:
                for wb in driver_batches:
                    yield wb

        for _ in rc.review_stream(stream_batches(time.time() + 0.5)):
            pass  # warm the stream path
        n_stream = 0
        t0 = time.time()
        for resp in rc.review_stream(stream_batches(t0 + 3.0)):
            n_stream += len(resp)
        grpc_stream_rps = n_stream / (time.time() - t0)
    except Exception as e:
        err = f"unavailable: {e}"[:120]
        if grpc_rps is None:
            grpc_rps = err
        if grpc_stream_rps is None:
            grpc_stream_rps = err
    finally:
        # leaked server/channel threads would skew every later tier;
        # stop() returns an event — WAIT for teardown to finish
        if rc is not None:
            rc.close()
        if server is not None:
            server.stop(grace=None).wait(timeout=30)

    # --- 2. batcher closed-loop (BENCH_r04 continuity): 64 in-process
    # threads through batcher.submit — no HTTP, measures the engine +
    # micro-batching frontier sharing one GIL with its clients
    import threading

    from gatekeeper_tpu.control.webhook import (
        MicroBatcher, NamespaceLabelHandler, ValidationHandler,
        WebhookServer)

    batcher = MicroBatcher(client, max_wait=0.003, max_batch=256)
    batcher.submit(reviews[0])  # warm the flusher
    lat_cl: list = []
    cl_lock = threading.Lock()
    n_req = int(10_000 * SCALE)
    n_threads = 64

    def cl_worker(k: int):
        lats = []
        for j in range(n_req // n_threads):
            r = reviews[(k * 131 + j) % len(reviews)]
            t0 = time.time()
            batcher.submit(r)
            lats.append(time.time() - t0)
        with cl_lock:
            lat_cl.extend(lats)

    t0 = time.time()
    ths = [threading.Thread(target=cl_worker, args=(k,))
           for k in range(n_threads)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    cl_wall = time.time() - t0
    lat_cl.sort()
    closed_loop = {
        "rps": round(len(lat_cl) / cl_wall),
        "p50_ms": round(lat_cl[len(lat_cl) // 2] * 1000, 2),
        "p99_ms": round(lat_cl[int(len(lat_cl) * 0.99)] * 1000, 2),
    }

    # --- 3. open-loop HTTP sweep (separate loadgen processes) ----------
    cores = os.cpu_count() or 1
    validation = ValidationHandler(client, kube=None, batcher=batcher)
    server = WebhookServer(validation, NamespaceLabelHandler(()), port=0)
    server.start()
    # re-freeze: the closed-loop tier allocated past the first freeze,
    # and a gen-2 GC scan of the policy heap is a >1s serving stall
    gc.collect()
    gc.freeze()
    here = os.path.dirname(os.path.abspath(__file__))
    n_procs = max(1, min(4, cores))
    duration = float(os.environ.get("BENCH_C5_SECONDS", 4.0))
    sweep, sustained = _run_sweep(
        server.port, (500, 1000, 1500, 2000, 3000, 5000, 8000, 12000),
        n_procs, duration, here)
    server.server.shutdown()
    batcher.stop()

    # --- 4. serving plane: pre-forked frontends over the shared
    # batching backplane. ONE engine process owns the evaluator and the
    # micro-batcher; 1/2/4 accept/parse-only frontend processes bind
    # one SO_REUSEPORT port and forward reviews (bytes, undecoded) over
    # a Unix socket, so every worker's trickle coalesces into shared
    # micro-batches. The decision cache (generation-keyed) serves
    # repeated object shapes without re-evaluation — the open-loop
    # payload set models exactly the DaemonSet-storm case it targets.
    import tempfile

    from gatekeeper_tpu.control.backplane import FrontendSupervisor

    def _spawn_engines(n: int, tag: str, extra_args: tuple = ()) -> tuple:
        """Spawn n --serve-engine children, each on its own socket.
        Returns (procs, socket_paths, metrics_ports); raises with the
        child's stderr tail when one fails to come up (the caller
        records an explicit skip — a silent empty sweep hid exactly
        this in BENCH_r05). The READY line carries each engine's
        /metrics port (0 = unavailable) for the mid-sweep
        saturation scrape."""
        procs, socks, mports = [], [], []
        try:
            for k in range(n):
                sp = os.path.join(
                    tempfile.gettempdir(),
                    f"gk-bench-bp-{os.getpid()}-{tag}{k}.sock")
                socks.append(sp)
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__),
                     "--serve-engine", sp, *extra_args],
                    cwd=here, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True))
            for k, proc in enumerate(procs):
                line = proc.stdout.readline()
                if "READY" not in (line or ""):
                    err = (proc.stderr.read() or "")[-300:]
                    raise RuntimeError(
                        f"backplane engine {k} failed to start: "
                        f"{err or 'no stderr'}")
                parts = (line or "").split()
                try:
                    mports.append(int(parts[1]))
                except (IndexError, ValueError):
                    mports.append(0)
                # drain later output so a full pipe can never block
                import threading as _th
                _th.Thread(target=proc.stdout.read, daemon=True).start()
                _th.Thread(target=proc.stderr.read, daemon=True).start()
            return procs, socks, mports
        except Exception:
            for p in procs:
                p.kill()
            raise

    def _scrape_raw(mport: int) -> dict:
        """Raw attribution counters/gauges from one /metrics scrape of
        the serving engine (admission plane only)."""
        import re as _re
        import urllib.request

        text = urllib.request.urlopen(
            f"http://127.0.0.1:{mport}/metrics",
            timeout=5).read().decode()
        out: dict = {"seals": {}, "fill_sum": 0.0, "fill_count": 0,
                     "queue_depth": {}, "duty": None}
        for m in _re.finditer(
                r'gatekeeper_tpu_batch_seal_total\{plane="admission",'
                r'reason="([^"]+)"\} (\S+)', text):
            out["seals"][m.group(1)] = int(float(m.group(2)))
        fs = _re.search(r'gatekeeper_tpu_batch_fill_ratio_sum'
                        r'\{plane="admission"\} (\S+)', text)
        fc = _re.search(r'gatekeeper_tpu_batch_fill_ratio_count'
                        r'\{plane="admission"\} (\S+)', text)
        if fs and fc:
            out["fill_sum"] = float(fs.group(1))
            out["fill_count"] = int(float(fc.group(1)))
        for m in _re.finditer(
                r'gatekeeper_tpu_queue_depth\{[^}]*queue="([^"]+)"\}'
                r' (\S+)', text):
            out["queue_depth"][m.group(1)] = float(m.group(2))
        m = _re.search(
            r'gatekeeper_tpu_device_duty_cycle\{engine="[^"]*"\} (\S+)',
            text)
        if m:
            out["duty"] = float(m.group(1))
        return out

    def _attribution_delta(before: dict, after: dict) -> dict:
        """This topology's attribution read: seal/fill counter DELTAS
        between the scrape before and after its rate sweep (one
        long-lived engine serves every topology, so cumulative totals
        would smear earlier topologies' traffic in), plus the
        post-sweep duty cycle (its sample window spans the sweep) and
        queue depths."""
        seals = {r: after["seals"].get(r, 0) - before["seals"].get(r, 0)
                 for r in set(after["seals"]) | set(before["seals"])}
        out: dict = {"batch_seal_reasons":
                     {r: n for r, n in sorted(seals.items()) if n > 0}}
        dn = after["fill_count"] - before["fill_count"]
        if dn > 0:
            out["batch_fill_ratio_mean"] = round(
                (after["fill_sum"] - before["fill_sum"]) / dn, 4)
            out["batches_sealed"] = dn
        out["queue_depth"] = after["queue_depth"]
        if after["duty"] is not None:
            out["device_duty_cycle"] = after["duty"]
        return out

    worker_counts = [int(w) for w in os.environ.get(
        "BENCH_C5_WORKERS", "1,2,4").split(",") if w.strip()]
    mw_sweep: list = []
    mw_sustained = None
    base = sustained["offered_rps"] if sustained else 500
    mw_skip = c5_skip_record(worker_counts, cores,
                             "BENCH_C5_WORKERS" in os.environ,
                             "BENCH_C5_WORKERS",
                             "pre-forked frontend + engine + loadgen "
                             "processes")
    bulk_rps = None
    bulk_nocache_rps = None
    if mw_skip is not None:
        mw_sweep.append(mw_skip)
        bulk_rps = mw_skip.get("skipped")
        bulk_nocache_rps = mw_skip.get("skipped")
    else:
        engine_procs: list = []
        try:
            engine_procs, socks, mports = _spawn_engines(1, "w")
            # BULK binary ingest tier: pre-framed reviews over the
            # backplane B frame straight into the engine child's
            # MicroBatcher — the edge path with no HTTP at all (what a
            # CI scanner speaks). Cross-process, unlike the in-process
            # engine tier above.
            try:
                from gatekeeper_tpu.control.backplane import (
                    BackplaneClient as _BC)

                bulk_payloads = [json.dumps({
                    "apiVersion": "admission.k8s.io/v1beta1",
                    "kind": "AdmissionReview",
                    "request": dict(r, uid=f"bk{k}",
                                    userInfo={"username": "bench"})},
                ).encode() for k, r in enumerate(reviews[:256])]
                bulk_chunks = [bulk_payloads[i:i + 64]
                               for i in range(0, len(bulk_payloads), 64)]
                bc = _BC(socks[0], worker_id="bulk")
                for ch in bulk_chunks:  # warm
                    bc.review_bulk(ch, timeout_s=30.0)
                n_bulk = 0
                t0 = time.time()
                while time.time() - t0 < 3.0:
                    for ch in bulk_chunks:
                        bc.review_bulk(ch, timeout_s=30.0)
                        n_bulk += len(ch)
                bulk_rps = round(n_bulk / (time.time() - t0))
                bc.close()
            except Exception as e:
                bulk_rps = f"unavailable: {e}"[:120]
            # same tier against a --no-decision-cache engine: the
            # repeated payload shapes above serve mostly from the
            # generation-keyed decision cache, so the cached number
            # measures cache hits; THIS series measures evaluation
            # (the PR 14 tiers_note caveat, fixed as its own gated
            # metric)
            nc_procs: list = []
            try:
                nc_procs, nc_socks, _nc_mp = _spawn_engines(
                    1, "wnc", extra_args=("--no-decision-cache",))
                bc = _BC(nc_socks[0], worker_id="bulknc")
                for ch in bulk_chunks:  # warm
                    bc.review_bulk(ch, timeout_s=30.0)
                n_bulk = 0
                t0 = time.time()
                while time.time() - t0 < 3.0:
                    for ch in bulk_chunks:
                        bc.review_bulk(ch, timeout_s=30.0)
                        n_bulk += len(ch)
                bulk_nocache_rps = round(n_bulk / (time.time() - t0))
                bc.close()
            except Exception as e:
                bulk_nocache_rps = f"unavailable: {e}"[:120]
            finally:
                for p in nc_procs:
                    p.kill()
            for n_workers in worker_counts:
                fronts = FrontendSupervisor(n_workers, socks[0],
                                            port=0, addr="127.0.0.1")
                fronts.start()
                scrape: dict = {}
                try:
                    mults = (1, 2, 3, 4, 6, 8) if n_workers > 1 \
                        else (1, 2)
                    rates = sorted({int(base * m) for m in mults})
                    # counter DELTAS across this topology's own sweep:
                    # one long-lived engine serves every worker count,
                    # so cumulative totals would smear topologies
                    pre = None
                    if mports and mports[0]:
                        try:
                            pre = _scrape_raw(mports[0])
                        except Exception:
                            pre = None
                    sweep_n, sus_n = _run_sweep(fronts.port, rates,
                                                n_procs, duration,
                                                here)
                    if pre is not None:
                        try:
                            scrape = _attribution_delta(
                                pre, _scrape_raw(mports[0]))
                        except Exception as e:
                            scrape = {"error": str(e)[:200]}
                finally:
                    fronts.stop()
                best_n = sus_n or (max(sweep_n,
                                       key=lambda e: e["achieved_rps"])
                                   if sweep_n else {})
                mw_sweep.append({
                    "workers": n_workers,
                    "admission_rps": best_n.get("achieved_rps", 0),
                    "slo_met": sus_n is not None,
                    "p50_ms": best_n.get("p50_ms"),
                    "p99_ms": best_n.get("p99_ms"),
                    "saturation": scrape or None,
                    "sweep": sweep_n,
                })
                if sus_n is not None and (
                        mw_sustained is None
                        or sus_n["achieved_rps"]
                        > mw_sustained["achieved_rps"]):
                    mw_sustained = sus_n
        except Exception as e:  # an EXPLICIT record, never a silent []
            mw_sweep.append({"skipped": str(e)[:300]})
        finally:
            for p in engine_procs:
                p.kill()

    # --- 5. N-engine plane (--admission-engines): K engine processes,
    # one per chip, frontends routing least-load across them — the
    # scale-with-chips topology. Each engine child self-ingests the
    # general library; 2 frontends route over all K sockets.
    engine_counts = [int(c) for c in os.environ.get(
        "BENCH_C5_ENGINES", "1,2").split(",") if c.strip()]
    me_sweep: list = []
    me_sustained = None
    me_skip = c5_skip_record(engine_counts, cores,
                             "BENCH_C5_ENGINES" in os.environ,
                             "BENCH_C5_ENGINES",
                             "N JAX engine processes")
    if me_skip is not None:
        me_sweep.append(me_skip)
    else:
        for n_engines in engine_counts:
            engine_procs = []
            try:
                engine_procs, socks, _mp = _spawn_engines(
                    n_engines, f"e{n_engines}-")
                fronts = FrontendSupervisor(2, socks, port=0,
                                            addr="127.0.0.1")
                fronts.start()
                try:
                    rates = sorted({int(base * m)
                                    for m in (1, 2, 4, 6, 8)})
                    sweep_n, sus_n = _run_sweep(fronts.port, rates,
                                                n_procs, duration,
                                                here)
                finally:
                    fronts.stop()
                best_n = sus_n or (max(sweep_n,
                                       key=lambda e: e["achieved_rps"])
                                   if sweep_n else {})
                me_sweep.append({
                    "engines": n_engines,
                    "admission_rps": best_n.get("achieved_rps", 0),
                    "slo_met": sus_n is not None,
                    "p50_ms": best_n.get("p50_ms"),
                    "p99_ms": best_n.get("p99_ms"),
                    "sweep": sweep_n,
                })
                if sus_n is not None and (
                        me_sustained is None
                        or sus_n["achieved_rps"]
                        > me_sustained["achieved_rps"]):
                    me_sustained = sus_n
            except Exception as e:
                me_sweep.append({"engines": n_engines,
                                 "skipped": str(e)[:300]})
            finally:
                for p in engine_procs:
                    p.kill()

    all_entries = sweep + [e for m in mw_sweep + me_sweep
                           for e in m.get("sweep", [])]
    best_sus = max((s for s in (mw_sustained, me_sustained, sustained)
                    if s is not None),
                   key=lambda s: s["achieved_rps"], default=None)
    best = (best_sus
            or (max(all_entries, key=lambda e: e["achieved_rps"])
                if all_entries else {}))
    print(json.dumps({
        "config": 5, "metric": "admission_rps",
        "value": best.get("achieved_rps", 0),
        "admission_rps": best.get("achieved_rps", 0),
        "unit": "req/s (open-loop multi-process HTTP vs full general "
                "library; highest offered rate with p99<100ms, else "
                "the measured host ceiling; best across the serving-"
                "plane worker counts)",
        "slo_met": best_sus is not None,
        "p50_ms": best.get("p50_ms"), "p99_ms": best.get("p99_ms"),
        "host_cores": cores,
        "worker_counts": worker_counts,
        "engine_batched_reviews_per_sec": round(engine_rps),
        # the ISSUE-14 headline gap: best open-loop edge rate as a
        # fraction of the engine's pre-batched ceiling (acceptance:
        # >= 0.5 on the bench host). Tracked by bench_trend.
        "edge_vs_engine_ratio": (
            round(best.get("achieved_rps", 0) / engine_rps, 3)
            if engine_rps else None),
        # the generator topped out on the headline entry: the edge
        # number is a loadgen floor, not a serving-plane ceiling
        "loadgen_limited": bool(best.get("loadgen_limited", False)),
        "grpc_batched_reviews_per_sec": (round(grpc_rps)
                                         if isinstance(grpc_rps, float)
                                         else grpc_rps),
        # pipelined ReviewStream over one HTTP/2 stream — the bulk-
        # ingest successor of the unary batched tier (gated >= r04's
        # 5,067/s by bench_trend once two rounds carry it)
        "grpc_stream_reviews_per_sec": (
            round(grpc_stream_rps)
            if isinstance(grpc_stream_rps, float) else grpc_stream_rps),
        # length-prefixed B frames over the backplane socket into a
        # separate engine process — the no-HTTP binary ingest path
        "backplane_bulk_reviews_per_sec": bulk_rps,
        # the same tier against a --no-decision-cache engine: every
        # review evaluates (gated alongside the cached series, so a
        # cache-hit speedup can't mask an evaluation regression)
        "backplane_bulk_reviews_per_sec_nocache": bulk_nocache_rps,
        "batcher_closed_loop": closed_loop,
        "tiers_note": "engine = pre-batched driver.review_batch (the "
                      "gRPC pre-batched ingest path); closed_loop = "
                      "64 in-process clients on batcher.submit (r4's "
                      "harness); HTTP sweeps are OPEN-LOOP with "
                      "separate loadgen processes — on a small host "
                      "they measure the serving plane sharing cores "
                      "with the load generators; multi_worker_sweep = "
                      "pre-forked frontends over the shared batching "
                      "backplane (--admission-workers). The bulk and "
                      "HTTP tiers ride the engine's generation-keyed "
                      "decision cache on repeated shapes (the "
                      "DaemonSet-storm case they model); the engine, "
                      "gRPC, and bulk-nocache tiers evaluate every "
                      "review",
        # the attribution read (ISSUE 13 acceptance): seal-reason /
        # fill / queue-depth / duty-cycle deltas across one topology's
        # open-loop sweep — the topology whose sweep actually drove
        # the batcher (later topologies can serve entirely from the
        # decision cache and seal nothing new)
        "saturation_scrape": max(
            (e["saturation"] for e in mw_sweep if e.get("saturation")),
            key=lambda s: s.get("batches_sealed", 0), default=None),
        "sweep": sweep,
        "multi_worker_sweep": sweep_or_skip(mw_sweep,
                                            "multi_worker_sweep"),
        # K engine processes (the --admission-engines topology), 2
        # frontends routing least-load across all K sockets; entries
        # are per engine count, or one explicit skip record
        "multi_engine_sweep": sweep_or_skip(me_sweep,
                                            "multi_engine_sweep"),
    }))


# -------------------------------------------------------------- config 10


def _mesh_audit_child(n_objects: int, n_constraints: int) -> None:
    """--mesh-audit child: one audit-scaling measurement in a fresh
    process (the parent sets GATEKEEPER_TPU_MESH / XLA_FLAGS before
    JAX initializes here). Prints one JSON line."""
    import jax

    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.parallel.workload import (
        REQUIRED_LABELS_TEMPLATE, synth_constraints, synth_objects)
    from gatekeeper_tpu.target import K8sValidationTarget

    driver = TpuDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    for c in synth_constraints(n_constraints, seed=1):
        client.add_constraint(c)
    for o in synth_objects(n_objects, violate_frac=0.002, seed=0):
        client.add_data(o)
    t0 = time.time()
    resp = client.audit()
    first_s = time.time() - t0
    t_warm = time.time()
    while driver.warm_status()["compiling"] and \
            time.time() - t_warm < 600:
        time.sleep(0.2)
    audit_s = float("inf")
    for _ in range(3):
        cache = getattr(driver, "_audit_results_cache", None)
        if cache is not None:
            cache.clear()  # measure the full sweep, not the delta hit
        t0 = time.time()
        resp = client.audit()
        audit_s = min(audit_s, time.time() - t0)
    print(json.dumps({
        "audit_s": round(audit_s, 3),
        "first_audit_s": round(first_s, 2),
        "path": driver.last_audit_path,
        "violations": len(resp.results()),
        "n_devices": len(jax.devices())}))


def config10():
    """Multichip audit promotion at 1M+ objects: the DEFAULT no-flag
    audit path must report mesh(data=N) whenever more than one device
    is visible, and wall-clock must improve against the forced
    single-device path. Each measurement runs in a fresh subprocess so
    the device topology (GATEKEEPER_TPU_MESH, XLA_FLAGS) binds before
    JAX initializes; on a 1-device host the mesh run forces 8
    host-platform devices so the sharded slab pipeline is exercised
    (those time-share the same cores — the record says which it was,
    so a CPU ratio is read as path validation, not chip scaling)."""
    import subprocess

    n_objects = int(os.environ.get("BENCH_C10_OBJECTS",
                                   int(1_000_000 * SCALE)))
    n_cons = int(os.environ.get("BENCH_C10_CONSTRAINTS", 100))
    here = os.path.dirname(os.path.abspath(__file__))
    import jax
    n_dev = len(jax.devices())
    forced = n_dev < 2

    def run_child(mesh_cfg: str) -> dict:
        env = dict(os.environ)
        env["GATEKEEPER_TPU_MESH"] = mesh_cfg
        if forced:
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-audit",
             str(n_objects), str(n_cons)],
            cwd=here, capture_output=True, text=True, env=env,
            timeout=int(os.environ.get("BENCH_C10_TIMEOUT", 1800)))
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(f"mesh-audit child ({mesh_cfg}) failed: "
                           f"{(proc.stderr or '')[-300:]}")

    out = {"config": 10, "metric": "mesh_audit_wall_clock_s",
           "objects": n_objects, "constraints": n_cons,
           "host_devices": n_dev,
           "mesh_platform": ("8 forced host-platform (cpu) devices"
                             if forced else f"{n_dev} devices")}
    try:
        mesh = run_child("auto")
        single = run_child("off")
        out.update({
            "value": mesh["audit_s"],
            "unit": f"s (one client.audit(), min of 3 warm sweeps; "
                    f"{n_cons} constraints x {n_objects} objects, "
                    "default no-flag mesh path)",
            "audit_path": mesh["path"],
            "first_audit_s": mesh["first_audit_s"],
            "violations": mesh["violations"],
            "single_device_s": single["audit_s"],
            "single_first_audit_s": single["first_audit_s"],
            "vs_single_device": (round(single["audit_s"]
                                       / mesh["audit_s"], 2)
                                 if mesh["audit_s"] else None),
        })
        if forced:
            out["note"] = ("host-platform devices time-share the same "
                           "CPU cores: vs_single_device here validates "
                           "the sharded path, not chip scaling")
    except Exception as e:  # an explicit record, never a lost config
        out.update({"value": None, "skipped": str(e)[:300]})
    print(json.dumps(out))


# --------------------------------------------------------------- config 8


def config8():
    """Resilience under overload: a 64-thread closed loop drives the
    micro-batched ValidationHandler through a flusher slowed to well
    below the offered load, with a bounded queue and 2s propagated
    deadlines. Measures the shed fraction, the deadline-answer fraction,
    and — the resilience headline — the WORST decision latency as a
    fraction of the deadline: every request must be answered before the
    API server would have given up, no matter the overload."""
    import threading

    from gatekeeper_tpu.control.webhook import (
        MicroBatcher,
        ValidationHandler,
    )

    _, client = _general_library_client()
    reviews = _mixed_reviews(max(64, int(256 * SCALE)), seed=5)
    inner = None

    def slowed(batch):
        time.sleep(0.05)  # force overload: capacity ~20 batches/s
        return inner(batch)

    batcher = MicroBatcher(client, max_wait=0.002, max_batch=16,
                           evaluate=slowed, max_queue=64)
    inner = batcher._evaluate_violations
    handler = ValidationHandler(client, batcher=batcher)
    timeout_s = 2
    payloads = [{"apiVersion": "admission.k8s.io/v1",
                 "kind": "AdmissionReview",
                 "request": dict(r, uid=f"u{k}", timeoutSeconds=timeout_s,
                                 userInfo={"username": "bench"})}
                for k, r in enumerate(reviews)]
    handler.handle(payloads[0])
    counts: dict[str, int] = {}
    lats: list = []
    lock = threading.Lock()
    n_threads = 64
    duration = 4.0 * max(SCALE, 0.25)
    stop = time.time() + duration

    def worker(k: int):
        mine: list = []
        mcounts: dict[str, int] = {}
        j = 0
        while time.time() < stop:
            t0 = time.time()
            out = handler.handle(payloads[(k * 131 + j) % len(payloads)])
            dt = time.time() - t0
            mine.append(dt)
            resp = out["response"]
            code = (resp.get("status") or {}).get("code")
            key = {429: "shed", 504: "deadline"}.get(code, "decided")
            mcounts[key] = mcounts.get(key, 0) + 1
            j += 1
        with lock:
            lats.extend(mine)
            for key, n in mcounts.items():
                counts[key] = counts.get(key, 0) + n

    ths = [threading.Thread(target=worker, args=(k,))
           for k in range(n_threads)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    batcher.stop()
    lats.sort()
    total = len(lats)
    worst_frac = (lats[-1] / timeout_s) if lats else 0.0
    print(json.dumps({
        "config": 8, "metric": "overload_worst_latency_deadline_frac",
        "value": round(worst_frac, 3),
        "unit": f"worst decision latency / {timeout_s}s deadline under "
                f"{n_threads}-thread overload (must stay < 1.0: every "
                "request answered before the API server gives up)",
        "requests": total,
        "decided_frac": round(counts.get("decided", 0) / max(total, 1), 3),
        "shed_frac": round(counts.get("shed", 0) / max(total, 1), 3),
        "deadline_frac": round(counts.get("deadline", 0) / max(total, 1),
                               3),
        "p50_ms": round(lats[total // 2] * 1000, 2) if lats else None,
        "p99_ms": round(lats[int(total * 0.99)] * 1000, 2) if lats
        else None,
    }))


# -------------------------------------------------------------- config 11


def config11():
    """Streaming audit + what-if preview (the PR-9 tentpole numbers).

    Part 1 — violation DETECTION latency (watch event -> the constraint-
    status write reflecting it) at config-6 churn scale (PSP library x
    50k pods), measured two ways on the same warm pipeline:
      interval: the reference line's polling sweep — events land at
        uniform offsets across one --audit-interval window and are
        detected by the sweep at the tick (latency ~ U(0, I) + sweep);
      streaming: --stream-audit — the tracker's watch events debounce-
        flush through the delta pipeline; p50/p99 from the
        event-receipt -> status-write clock inside the flush.
    The headline gate: streaming p99 beats the interval line's by >=10x.

    Part 2 — `whatif_preview_s`: a candidate constraint swept against a
    100k+-object encoded inventory via /v1/preview's engine. Cold call
    serves host while XLA warms off-path; the headline is the WARM
    sweep (< 1s gate)."""
    import threading

    from gatekeeper_tpu import policies
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    n = int(50_000 * SCALE)
    interval_s = float(os.environ.get("BENCH_C11_INTERVAL", 10.0))
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    pods = synth_pods_psp(n)
    for i, pod in enumerate(pods):
        pod["metadata"]["uid"] = f"c11-{i}"
        kube.create(pod)
    drv, client = new_client()
    for name in policies.names():
        if name.startswith("pod-security-policy/"):
            client.add_template(policies.load(name))
    for kind, cname, params in PSP_CONSTRAINTS:
        con = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
               "kind": kind, "metadata": {"name": cname},
               "spec": ({"parameters": params} if params else {})}
        client.add_constraint(con)
        kube.apply(dict(con))

    mgr = AuditManager(kube, client, incremental=True,
                       interval=3600, stream_audit=True,
                       stream_window_s=0.025)
    t0 = time.time()
    mgr.audit_once()  # builds the tracker + encodes the inventory
    first = time.time() - t0
    t0 = time.time()
    while hasattr(drv, "warm_status") and \
            drv.warm_status()["compiling"] and time.time() - t0 < 600:
        time.sleep(0.2)
    t0 = time.time()
    mgr.audit_once()
    sweep_s = time.time() - t0  # warm steady-state sweep

    rng = random.Random(11)

    def churn(round_, k):
        """k pod replacements; ~half flip violation state (privileged
        toggle), half are healthy label churn — the stream flush must
        both rewrite statuses and confirm no-ops."""
        import copy
        for j, i in enumerate(rng.sample(range(n), k)):
            pod = copy.deepcopy(pods[i])
            if j % 2:
                ctx = pod["spec"]["containers"][0]["securityContext"]
                ctx["privileged"] = not ctx.get("privileged", False)
            else:
                pod["metadata"].setdefault("labels", {})["churn"] = \
                    f"r{round_}-{i}"
            kube.apply(pod)

    # --- interval line: events at uniform offsets across one window,
    # detected by the sweep at the tick (driven inline — this IS what
    # the polling loop does, without burning a thread to wait on)
    k_events = 60
    offsets = sorted(rng.uniform(0.0, interval_s * 0.95)
                     for _ in range(k_events))
    t_window = time.time()
    event_times = []
    for j, off in enumerate(offsets):
        time.sleep(max(0.0, t_window + off - time.time()))
        churn(1000 + j, 1)
        event_times.append(time.time())
    time.sleep(max(0.0, t_window + interval_s - time.time()))
    mgr.audit_once()  # the tick
    t_done = time.time()
    int_lat = sorted(t_done - te for te in event_times)
    interval_ms = {
        "p50": round(int_lat[len(int_lat) // 2] * 1e3, 1),
        "p99": round(int_lat[int(len(int_lat) * 0.99)] * 1e3, 1),
        "interval_s": interval_s,
    }

    # --- streaming line: the stream loop flushes dirty rows as the
    # watch delivers them; latencies come from the flush's own
    # event-receipt -> status-write clock
    stream_lat: list = []
    lat_lock = threading.Lock()

    def on_flush(lat, writes):
        with lat_lock:
            stream_lat.extend(lat)

    mgr.on_flush = on_flush
    mgr.start()
    t0 = time.time()
    while mgr.tracker is not None and not mgr.tracker.track_event_times \
            and time.time() - t0 < 10:
        time.sleep(0.02)  # stream loop arming the tracker hooks
    time.sleep(0.3)
    rounds = 40
    burst = max(1, int(n * 0.01) // rounds)  # ~1% churn total
    for r in range(rounds):
        churn(r, burst)
        time.sleep(0.15)  # past the debounce window: distinct flushes
    t0 = time.time()
    while time.time() - t0 < 10:
        with lat_lock:
            if len(stream_lat) >= rounds * burst:
                break
        time.sleep(0.05)
    mgr.stop()
    with lat_lock:
        s_lat = sorted(stream_lat)
    if not s_lat:
        s_lat = [float("nan")]
    stream_ms = {
        "p50": round(s_lat[len(s_lat) // 2] * 1e3, 1),
        "p99": round(s_lat[int(len(s_lat) * 0.99)] * 1e3, 1),
    }

    # --- what-if preview over a 100k+-object encoded inventory -------
    from gatekeeper_tpu.control.preview import PreviewEngine
    from gatekeeper_tpu.parallel.workload import (
        REQUIRED_LABELS_TEMPLATE, synth_objects)

    n_pv = int(100_000 * SCALE)
    drv2, client2 = new_client()
    client2.add_template(REQUIRED_LABELS_TEMPLATE)
    for o in synth_objects(n_pv, violate_frac=0.01, seed=0):
        client2.add_data(o)
    pv = PreviewEngine(client2)
    candidate = {
        "kind": "K8sRequiredLabels", "metadata": {"name": "whatif"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": [{"key": "cost-center"}]}},
    }
    out = pv.preview({"constraint": candidate, "limit": 5})
    cold_s = out["duration_s"]
    t0 = time.time()
    while out["path"] != "device" and time.time() - t0 < 300:
        time.sleep(1.0)  # background XLA warm for the alias kind
        out = pv.preview({"constraint": candidate, "limit": 5})
    warm_s = float("inf")
    for _ in range(3):
        out = pv.preview({"constraint": candidate, "limit": 5})
        warm_s = min(warm_s, out["duration_s"])

    print(json.dumps({
        "config": 11, "metric": "violation_detection_ms_p99",
        "value": stream_ms["p99"],
        "unit": f"ms (watch event -> constraint-status write, "
                f"--stream-audit, PSP library x {n} pods, ~1% churn "
                f"in {rounds} bursts)",
        "violation_detection_ms": stream_ms,
        "detection_events": len(s_lat),
        "stream_stats": mgr.stream_stats,
        "interval_detection_ms": interval_ms,
        "detection_speedup_p99": (
            round(int_lat[int(len(int_lat) * 0.99)] * 1e3
                  / max(stream_ms["p99"], 1e-9), 1)),
        "steady_sweep_s": round(sweep_s, 3),
        "first_audit_s": round(first, 2),
        "whatif_preview_s": round(warm_s, 4),
        "whatif_preview_cold_s": round(cold_s, 4),
        "preview_reviewed": out["reviewed"],
        "preview_violations": out["violations"],
        "preview_path": out["path"],
    }))


# ----------------------------------------- config 12: compiler widening


def _xtemplate(kind: str, rego: str) -> dict:
    return {"apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": kind.lower()},
            "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                     "targets": [{"target": TARGET, "rego": rego}]}}


# Upstream-canonical Rego forms the PR 10 compiler widening brought onto
# the device path — before it, every one of these kinds audited on the
# interpreter (the `Uncompilable` wall each one used to hit is noted).
# Shared with tests/test_compile_coverage.py, which differential-tests
# each against the interpreter driver.
EXTENDED_FORM_TEMPLATES = [
    # param key-set comprehension (was: "param key-set comprehension")
    ("XRequiredLabelKeys", _xtemplate("XRequiredLabelKeys", """
package xrequiredlabelkeys

violation[{"msg": msg}] {
  provided := {k | input.review.object.metadata.labels[k]}
  required := {k | input.parameters.labels[k]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("missing required label keys: %v", [missing])
}
"""), {"labels": {"owner": "", "app": "", "team": ""}}),
    # non-var comprehension head + computed set membership
    # (was: "unbound var c" / "unsupported set bracket")
    ("XBannedImages", _xtemplate("XBannedImages", """
package xbannedimages

violation[{"msg": msg}] {
  images := {c.image | c := input.review.object.spec.containers[_]}
  images[input.parameters.banned]
  msg := sprintf("banned image <%v> in use", [input.parameters.banned])
}
"""), {"banned": "docker.io/evil7:latest"}),
    # multi-literal filter body over the generator element + lower()
    # derived column (was: "unbound base var c" / "unsupported call lower")
    ("XRootfulPrefixed", _xtemplate("XRootfulPrefixed", """
package xrootfulprefixed

violation[{"msg": msg}] {
  bad := {c.name | c := input.review.object.spec.containers[_]; startswith(lower(c.image), input.parameters.prefix); not c.securityContext.runAsNonRoot}
  count(bad) > 0
  msg := sprintf("containers from <%v> must set runAsNonRoot: %v", [input.parameters.prefix, bad])
}
"""), {"prefix": "docker.io/"}),
    # `some`-decls + 2-arg identical(obj, review) canonical join body
    # (was: "join: some-decl")
    ("XUniqueIngressHostCanon", _xtemplate("XUniqueIngressHostCanon", """
package xuniqueingresshostcanon

identical(obj, review) {
  obj.metadata.namespace == review.object.metadata.namespace
  obj.metadata.name == review.object.metadata.name
}

violation[{"msg": msg}] {
  input.review.kind.kind == "Ingress"
  re_match("^(extensions|networking.k8s.io)$", input.review.kind.group)
  some ns, apiv, name
  host := input.review.object.spec.rules[_].host
  other := data.inventory.namespace[ns][apiv]["Ingress"][name]
  re_match("^(extensions|networking.k8s.io)/.+$", apiv)
  other.spec.rules[_].host == host
  not identical(other, input.review)
  msg := sprintf("ingress host conflicts with an existing ingress <%v>", [host])
}
"""), None),
    # inline inventory generator + inline self-exclusion disequality
    # (was: "join: generator must bind a var" / "unsupported mixed
    # literal")
    ("XUniqueSelectorInline", _xtemplate("XUniqueSelectorInline", """
package xuniqueselectorinline

violation[{"msg": msg}] {
  input.review.kind.kind == "Service"
  sel := input.review.object.spec.selector
  data.inventory.namespace[ns][_]["Service"][name].spec.selector == sel
  name != input.review.object.metadata.name
  msg := sprintf("same selector as service <%v>", [name])
}
"""), None),
]


def config12():
    """Per-kind audit latency, interpreter vs the newly device-compiled
    path, for the extended-form corpus (kinds that PR 10's compiler
    widening moved off the interpreter). Dense kinds run at config-6
    inventory scale; the cross-object join kinds run both sides at a
    reduced N the interpreter's O(N*M) rescan can finish at all —
    speedups are apples-to-apples at each kind's own N. Also reports
    the shipped general library's device coverage (the
    `general_library_compiled_fraction` headline: must read 1.0)."""
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    n_dense = int(50_000 * SCALE)  # config-6 inventory scale
    n_join = int(4_000 * SCALE)
    objs_dense = synth_mixed_objects(n_dense, seed=12)
    for i, o in enumerate(objs_dense):
        if i % 50:  # ~2% violating tail: keep materialization sparse
            o["metadata"]["labels"]["team"] = "core"
    # join-kind inventory: mostly-unique hosts/selectors with a ~2%
    # colliding tail, so the cross-object filter does real work but the
    # exact-message materialization (same cost on both sides) stays off
    # the critical path
    objs_join = []
    for i in range(n_join):
        if i % 2:
            host = (f"dup{i % 10}.corp.example" if i % 50 == 1
                    else f"h{i}.corp.example")
            objs_join.append({
                "apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": {"name": f"ing-{i}", "namespace": f"ns{i % 9}"},
                "spec": {"rules": [{"host": host}]}})
        else:
            sel = ({"app": f"dupapp{i % 10}"} if i % 50 == 0
                   else {"app": f"app{i}"})
            objs_join.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": f"svc-{i}", "namespace": f"ns{i % 9}"},
                "spec": {"selector": sel}})

    # shipped-library coverage: the ratcheted headline numbers
    drv, client = new_client()
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    cov = compiled_coverage(drv, client)

    per_kind = {}
    best = 0.0
    for kind, tmpl, params in EXTENDED_FORM_TEMPLATES:
        is_join = kind.startswith("XUnique")
        objs = objs_join if is_join else objs_dense
        con = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
               "kind": kind, "metadata": {"name": kind.lower()},
               "spec": ({"parameters": params} if params else {})}
        row = {"objects": len(objs), "path": None}
        for side in ("interpreter", "device"):
            drv2 = RegoDriver() if side == "interpreter" else None
            if drv2 is None:
                drv2, client2 = new_client()
            else:
                client2 = Backend(drv2).new_client([K8sValidationTarget()])
            client2.add_template(tmpl)
            client2.add_constraint(con)
            for o in objs:
                client2.add_data(o)
            client2.audit()  # warm-up (device: background XLA compile)
            if side == "device":
                t0 = time.time()
                while hasattr(drv2, "warm_status") and \
                        drv2.warm_status()["compiling"] and \
                        time.time() - t0 < 600:
                    time.sleep(0.2)
                assert drv2.compiled_for(kind) is not None or \
                    drv2.join_for(kind) is not None, \
                    f"{kind} fell back: {drv2.fallback_reasons()}"
                row["path"] = "join" if drv2.join_for(kind) else "device"
            best_s = float("inf")
            for _ in range(2):
                # measure the full per-kind sweep, not PR 1's unchanged-
                # rows delta shortcut (which answers from cache in ~0s)
                if hasattr(drv2, "_audit_results_cache"):
                    drv2._audit_results_cache.clear()
                t0 = time.time()
                nres = len(client2.audit().results())
                best_s = min(best_s, time.time() - t0)
            row[f"{side}_audit_s"] = round(best_s, 4)
            row[f"{side}_violations"] = nres
        assert row["interpreter_violations"] == row["device_violations"], \
            f"{kind}: verdict count diverged"
        row["speedup"] = round(
            row["interpreter_audit_s"] / max(row["device_audit_s"], 1e-9), 1)
        best = max(best, row["speedup"])
        per_kind[kind] = row

    print(json.dumps({
        "config": 12, "metric": "compile_widening_speedup",
        "value": best,
        "unit": ("x (best per-kind steady audit speedup, interpreter vs "
                 "newly device-compiled path, extended-form corpus; "
                 f"dense kinds at {n_dense} objects, join kinds at "
                 f"{n_join})"),
        "general_library_compiled_fraction":
            cov["device_compiled_fraction"],
        "general_library_interpreter_kinds": cov["interpreter_kinds"],
        "per_kind": per_kind,
    }))


# -------------------------------------------------------------- config 13


def config13():
    """Sharded inventory plane (the PR-16 tentpole): the audit
    inventory consistent-hashed by (GVK, namespace) across N audit
    shard PROCESSES, each sweeping only its slice, the leader
    composing per-shard results into one audit round. At each shard
    count over the SAME leader inventory it measures: the spawn +
    slice-sync wall (what a respawned shard pays end to end), the
    full-slice re-sweep wall right after a resync (the orphaned-
    partition path — the `objects_per_sec` headline), and the steady
    incremental round under ~0.1% routed churn (the recurring state).
    Defaults to 10M * BENCH_SCALE objects (BENCH_C13_OBJECTS
    overrides). On a small host the shard children time-share the
    cores, so shards>1 validates the sharded path, not core scaling —
    the record says which it was."""
    import shutil
    import tempfile

    from gatekeeper_tpu import policies
    from gatekeeper_tpu.control.audit import ShardedAuditPlane
    from gatekeeper_tpu.control.backplane import AuditShardSupervisor
    from gatekeeper_tpu.control.kube import FakeKube
    from gatekeeper_tpu.parallel.workload import REQUIRED_LABELS_TEMPLATE

    n = int(os.environ.get("BENCH_C13_OBJECTS",
                           int(10_000_000 * SCALE)))
    shard_counts = [int(s) for s in os.environ.get(
        "BENCH_C13_SHARDS", "1 2 4").split()]
    n_ns = max(16, min(8192, n // 100))
    n_ing = max(4, n // 1000)
    churn = max(1, min(1000, n // 1000))
    cores = os.cpu_count() or 1

    def pod(i, tag=None):
        # ~0.1% violating tail keeps materialization off the critical
        # path; churn tags mutate labels without changing verdicts
        labels = {"team": "core"} if i % 1000 else {"app": "x"}
        if tag:
            labels["churn"] = tag
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": f"p-{i}",
                             "namespace": f"ns{i % n_ns}",
                             "labels": labels}}

    drv, leader = new_client()
    leader.add_template(REQUIRED_LABELS_TEMPLATE)
    # the FIXED-kind join template: Ingresses broadcast their join
    # columns to every shard, Pods stay owner-only (the broadcast
    # pruning this plane exists for)
    leader.add_template(policies.load("general/uniqueingresshost"))
    leader.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "pods-need-team"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Pod"]}]},
                 "parameters": {"labels": [{"key": "team"}]}}})
    leader.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sUniqueIngressHost",
        "metadata": {"name": "unique-hosts"}, "spec": {}})

    t0 = time.time()
    for i in range(n_ns):
        leader.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": f"ns{i}"}})
    for i in range(n):
        leader.add_data(pod(i))
    for i in range(n_ing):
        host = (f"dup{i % 8}.corp.example" if i % 100 == 1
                else f"h{i}.corp.example")
        leader.add_data({"apiVersion": "networking.k8s.io/v1",
                         "kind": "Ingress",
                         "metadata": {"name": f"ing-{i}",
                                      "namespace": f"ns{i % n_ns}"},
                         "spec": {"rules": [{"host": host}]}})
    ingest_s = time.time() - t0
    total = n + n_ns + n_ing

    # unsharded reference on the leader itself (same client, same
    # inventory): full re-evaluation wall, delta cache dropped so the
    # steady-state shortcut can't answer from cache
    leader.audit()  # warm-up (background XLA compile)
    t0 = time.time()
    while hasattr(drv, "warm_status") and \
            drv.warm_status()["compiling"] and time.time() - t0 < 600:
        time.sleep(0.2)
    uns_s = float("inf")
    for _ in range(2):
        drop = getattr(drv, "_audit_results_cache", None)
        if drop is not None:
            drop.clear()
        t0 = time.time()
        uns_n = len(leader.audit().results())
        uns_s = min(uns_s, time.time() - t0)

    per_shards = []
    tmp = tempfile.mkdtemp(prefix="gk-c13-")
    try:
        for shards in shard_counts:
            sock = os.path.join(tmp, f"s{shards}.sock")
            plane_box: list = []
            sup = AuditShardSupervisor(
                shards,
                socket_for=lambda k, s=sock: f"{s}.{k}",
                spawn_args=["--log-level", "WARNING"],
                snapshot_provider=lambda k: plane_box[0].sync_snapshot(k))
            plane = ShardedAuditPlane(FakeKube(), leader, sup, shards)
            plane_box.append(plane)
            row: dict = {"shards": shards}
            try:
                t0 = time.time()
                sup.start()  # spawn children + bulk per-slice sync
                row["spawn_sync_s"] = round(time.time() - t0, 3)
                t0 = time.time()
                res, _ = plane.sweep(None)  # slice encode + XLA warm
                row["first_round_s"] = round(time.time() - t0, 3)
                row["violations"] = len(res)
                # orphaned-partition re-sweep: fresh slice sync (warm
                # device programs), then one FULL composed round
                for k in range(shards):
                    sup._resync(k)
                t0 = time.time()
                res, stats = plane.sweep(None)
                wall = time.time() - t0
                row["full_sweep_wall_s"] = round(wall, 4)
                row["objects_per_sec"] = round(total / max(wall, 1e-9))
                row["shard_eval_max_s"] = stats.get("shard_eval_max_s")
                # steady incremental round under routed churn: live
                # deltas route owner-only over the backplane, shards
                # re-evaluate dirty rows, the leader recomposes
                plane.attach()
                steady = float("inf")
                for r in range(2):
                    for j in range(churn):
                        leader.add_data(pod((j * 997) % n, tag=f"r{r}"))
                    t0 = time.time()
                    res2, _ = plane.sweep(None)
                    steady = min(steady, time.time() - t0)
                row["steady_churn_sweep_s"] = round(steady, 4)
                row["steady_violations"] = len(res2)
            except Exception as e:
                row["error"] = f"{type(e).__name__}: {e}"[:200]
            finally:
                sup.stop()
                plane.stop()
            per_shards.append(row)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    ok = [r for r in per_shards if "error" not in r]
    best = max(ok, key=lambda r: r["objects_per_sec"]) if ok else None
    out = {
        "config": 13, "metric": "sharded_audit_objects_per_sec",
        "value": best["objects_per_sec"] if best else None,
        "unit": (f"objects/s (sharded inventory plane full composed "
                 f"round, best of shards={shard_counts}; requiredlabels "
                 f"+ uniqueingresshost x {total} objects across {n_ns} "
                 "namespaces)"),
        "objects": total, "host_cores": cores,
        "leader_ingest_s": round(ingest_s, 2),
        "unsharded_sweep_s": round(uns_s, 4),
        "unsharded_violations": uns_n,
        "per_shards": per_shards,
    }
    if best:
        out["best_shards"] = best["shards"]
        out["sweep_wall_s"] = best["full_sweep_wall_s"]
        out["vs_unsharded"] = round(uns_s /
                                    max(best["full_sweep_wall_s"], 1e-9),
                                    2)
        if cores < max(shard_counts):
            out["note"] = (f"{cores} host core(s): shard children "
                           "time-share the core, so shards>1 validates "
                           "the sharded path, not core scaling")
    print(json.dumps(out))


# -------------------------------------------------------------- config 14


def config14():
    """Adaptive serving controller (the PR-18 tentpole), three legs on
    the in-process closed-loop harness:

    Leg A — reference: the config-5 hand-tuned optimum (max_wait=3ms,
    max_batch=256) under an 8-thread closed loop -> `ref_rps`. Low
    concurrency puts the plane in the edge-bound trickle regime PR
    14's scrape showed for the real deployment — the regime the
    controller's max_wait rule exists for (at 64 threads the plane is
    flusher-bound and batch amortization, not the wait window, sets
    the throughput).

    Leg B — convergence: the SAME loop against deliberately mis-tuned
    cold defaults (max_wait=50ms, max_batch=1024 — every batch seals
    on the wait window at ~1% fill, so the wait is pure added latency)
    measured first WITHOUT the controller (`cold_rps`, the gap the
    loop must close), then with an armed AdaptiveController ticked on
    a fixed cadence until `max_wait` lands at its floor. The steady
    window after convergence must reach within ~10% of `ref_rps`
    (`adaptive_converged_frac`, the headline — gate >= 0.9) with zero
    sustained oscillation (actuation-direction flip count gated <= 2)
    and the degradation ladder never leaving rung 0. The kill switch
    (`disarm(restore=True)`) must then restore every knob to the cold
    baseline bit-exactly.

    Leg C — chaos: the test_resilience engine-kill storm with the
    controller ARMED on the serving batcher: a 60-caller admission
    burst over FrontendServer -> BackplaneClient -> BackplaneEngine,
    the engine aborted (the in-process kill -9 analog) mid-burst with
    the `backplane.engine` fault point held down — zero unanswered
    admissions, every caller gets an AdmissionReview per the fail-open
    stance, and the armed controller disarms clean afterwards."""
    import http.client
    import threading

    from gatekeeper_tpu.control.adaptive import AdaptiveController
    from gatekeeper_tpu.control.backplane import (
        BackplaneClient,
        BackplaneEngine,
        FrontendServer,
        default_socket_path,
    )
    from gatekeeper_tpu.control.webhook import (
        MicroBatcher,
        ValidationHandler,
    )
    from gatekeeper_tpu.utils.faults import FAULTS

    _, client = _general_library_client()
    reviews = _mixed_reviews(max(64, int(256 * SCALE)), seed=14)
    n_threads = 8
    window_s = max(1.0, 2.5 * min(SCALE, 1.0))
    tick_s = 0.2

    def closed_loop(batcher, stop_evt, counts):
        def worker(k):
            j = 0
            while not stop_evt.is_set():
                batcher.submit(reviews[(k * 131 + j) % len(reviews)])
                j += 1
                counts[k] += 1  # per-thread slot: no lock on the hot path
        ths = [threading.Thread(target=worker, args=(k,), daemon=True)
               for k in range(n_threads)]
        for t in ths:
            t.start()
        return ths

    def measure_window(counts, duration):
        before = sum(counts)
        t0 = time.time()
        time.sleep(duration)
        return (sum(counts) - before) / (time.time() - t0)

    # --- leg A: hand-tuned reference (config-5 closed-loop optimum)
    batcher_a = MicroBatcher(client, max_wait=0.003, max_batch=256)
    batcher_a.submit(reviews[0])  # warm the flusher + XLA programs
    stop_a = threading.Event()
    counts_a = [0] * n_threads
    ths = closed_loop(batcher_a, stop_a, counts_a)
    time.sleep(0.3)  # let the loop fill before the timed window
    ref_rps = measure_window(counts_a, window_s)
    stop_a.set()
    for t in ths:
        t.join(10)
    batcher_a.stop()

    # --- leg B: cold mis-tuned defaults, then the armed controller
    cold = {"max_wait": 0.05, "max_batch": 1024, "max_queue": 0}
    batcher_b = MicroBatcher(client, **cold)
    ctrl = AdaptiveController(batcher=batcher_b, interval=999.0,
                              cooldown_s=0.1, hysteresis_s=1.0,
                              relax_after_s=1e9, min_seals=2)
    stop_b = threading.Event()
    counts_b = [0] * n_threads
    ths = closed_loop(batcher_b, stop_b, counts_b)
    time.sleep(0.3)
    cold_rps = measure_window(counts_b, window_s)
    ctrl.arm()  # interval=999: the tick thread parks; ticks are manual
    ctrl._sample(time.monotonic())  # prime counter deltas: leg A's
    # seal/shed series live in the same process registry — the first
    # sample must not read their lifetime totals as one tick's delta
    ticks = 0
    wait_floor = ctrl.knobs["batch_max_wait"].lo
    while batcher_b.max_wait > 1.5 * wait_floor and ticks < 60:
        time.sleep(tick_s)
        ctrl.tick()
        ticks += 1
    converged_rps = measure_window(counts_b, window_s)
    stop_b.set()
    for t in ths:
        t.join(10)
    conv_frac = converged_rps / max(ref_rps, 1e-9)
    flips = ctrl.flip_count()
    rung_after = ctrl.ladder.rung
    converged_wait = batcher_b.max_wait
    trail = ctrl.actuations()[-12:]  # already wire-shape dicts
    ctrl.disarm(restore=True)  # the kill switch: bit-exact restore
    restore_exact = (batcher_b.max_wait == cold["max_wait"]
                     and batcher_b.max_batch == cold["max_batch"]
                     and batcher_b.max_queue == cold["max_queue"])
    batcher_b.stop()
    assert conv_frac >= 0.9, \
        f"controller converged to {conv_frac:.2f}x of the hand-tuned " \
        f"reference (gate: within ~10%)"
    assert flips <= 2, f"actuation oscillation: {flips} direction flips"
    assert restore_exact, "kill switch did not restore the baseline " \
        f"bit-exactly: {batcher_b.knob_values()} != {cold}"

    # --- leg C: mid-burst engine kill with the controller armed
    def slow_eval(batch):
        time.sleep(0.05)  # keep a healthy backlog in flight at the kill
        return client.driver.review_batch(TARGET, batch)

    batcher_c = MicroBatcher(client, max_wait=0.002, max_batch=8,
                             evaluate=slow_eval)
    ctrl_c = AdaptiveController(batcher=batcher_c, interval=0.1,
                                cooldown_s=0.1, hysteresis_s=1.0)
    validation = ValidationHandler(client, kube=None, batcher=batcher_c,
                                   decision_cache_size=0,
                                   ladder=ctrl_c.ladder)
    sock = default_socket_path() + ".bench14"
    engine = BackplaneEngine(sock, validation=validation)
    engine.start()
    bc = BackplaneClient(sock, worker_id="bench14")
    frontend = FrontendServer(bc, port=0, addr="127.0.0.1")
    frontend.start()
    ctrl_c.arm()  # the real tick thread rides the kill
    n = 60
    answered: dict = {}
    errors: list = []
    lock = threading.Lock()

    def fire(i):
        obj = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": f"b14-{i}", "namespace": "bench"}}
        payload = {"apiVersion": "admission.k8s.io/v1",
                   "kind": "AdmissionReview",
                   "request": {"uid": f"b14-{i}", "operation": "CREATE",
                               "kind": {"group": "", "version": "v1",
                                        "kind": "Pod"},
                               "name": f"b14-{i}", "namespace": "bench",
                               "userInfo": {"username": "bench"},
                               "object": obj, "timeoutSeconds": 3}}
        try:
            conn = http.client.HTTPConnection("127.0.0.1",
                                              frontend.port, timeout=15)
            conn.request("POST", "/v1/admit?timeout=3s",
                         json.dumps(payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            with lock:
                answered[i] = (resp.status, body["response"])
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append((i, repr(e)))

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(n)]
    try:
        for t in threads:
            t.start()
        # let part of the burst land real verdicts, then kill the
        # engine under the rest; the fault point keeps the reconnect
        # path down for the stragglers
        deadline = time.time() + 10
        while len(answered) < n // 6 and time.time() < deadline:
            time.sleep(0.01)
        FAULTS.inject("backplane.engine", mode="error")
        engine.abort()
        for t in threads:
            t.join(20)
    finally:
        frontend.stop(drain_timeout=2.0)
        ctrl_c.disarm(restore=True)
        batcher_c.stop()
        FAULTS.reset()
    stance = sum(1 for _, resp in answered.values()
                 if (resp.get("status") or {}).get("code") in (503, 504))
    assert not errors, errors[:3]
    assert len(answered) == n, \
        f"unanswered admissions after engine kill: {n - len(answered)}"

    print(json.dumps({
        "config": 14, "metric": "adaptive_converged_frac",
        "value": round(conv_frac, 3),
        "unit": ("x of the hand-tuned config-5 knobs' rps on the same "
                 "edge-bound closed loop, reached from cold defaults "
                 "(max_wait 50ms) by the armed controller; gates: "
                 ">= 0.9, flip count <= 2, zero unanswered admissions "
                 "through a mid-burst engine kill, kill-switch restore "
                 "bit-exact"),
        "ref_rps": round(ref_rps),
        "cold_rps": round(cold_rps),
        "converged_rps": round(converged_rps),
        "ticks_to_converge": ticks,
        "converged_max_wait_ms": round(converged_wait * 1000, 3),
        "flip_count": flips,
        "rung_after": rung_after,
        "kill_switch_restore_exact": restore_exact,
        "actuations": trail,
        "chaos": {"callers": n, "answered": len(answered),
                  "stance_answers": stance, "errors": len(errors)},
    }))


def config15():
    """Chaos MTTR matrix (the PR-19 tentpole): six fault scenarios,
    each injected twice against a live plane, with recovery measured
    HARNESS-side as t_healthy - t_inject (the supervisor's own
    fault_recovery histogram measures detection->resync; this number
    adds detection latency, which is the part an operator feels):

      engine-kill     SIGKILL the child admission engine; healthy =
                      replacement spawned + resynced
      engine-pause    SIGSTOP it (gray failure: alive to waitpid,
                      wedged to callers) — detection must come from
                      the heartbeat deadline, nobody sends SIGCONT
      frontend-kill   SIGKILL one pre-forked frontend slot; healthy =
                      full worker fan-out serving again
      shard-kill      SIGKILL an audit shard child; healthy = slice
                      rebuilt AND the next composed round bit-equal
                      to a clean single-process oracle
      leader-kill     expire the incumbent's lease (the crashed-
                      leader analog); healthy = a candidate holds
                      the lease again
      apiserver-flap  a burst of 5xx on kube writes; healthy = the
                      next status write round-trips

    An admission trickle rides every serve-plane scenario and the
    crash-consistency verifier (gatekeeper_tpu.control.chaos) checks
    the side effects: zero unanswered admissions, audit bit-equality,
    no leaked children/fds//dev/shm segments, no stale gauge series.

    Headlines: `chaos_mttr_p99_s` (max MTTR across the matrix — p99
    over this sample count IS the max; lower-better, gated by
    bench_trend via the c15 series) and `chaos_invariant_violations`
    (asserted == 0 in-bench, so a violation fails the config rather
    than shipping as a number).

    Engine/shard children run on JAX_PLATFORMS=cpu: MTTR measures the
    supervisory plane (detect/kill/respawn/resync), not eval speed,
    and child processes must not fight the parent for an accelerator.
    """
    prev_platform = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        _config15_body()
    finally:
        if prev_platform is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_platform


def _config15_body():
    import tempfile
    import threading

    import tools.chaos_verify as cv
    from gatekeeper_tpu.control.chaos import (
        CheckResult,
        LeakBaseline,
        PlaneHandles,
        Verifier,
    )
    from gatekeeper_tpu.control.main import Runtime, build_parser
    from gatekeeper_tpu.utils.faults import FAULTS

    REPEATS = 2
    verifier = Verifier()
    matrix: dict = {}
    probe_seq = [0]
    answered: dict = {}
    errors: list = []
    lock = threading.Lock()

    def sample(name, fn):
        samples = matrix.setdefault(name, {"samples_s": []})["samples_s"]
        for _ in range(REPEATS):
            samples.append(round(fn(), 3))
            time.sleep(0.3)  # settle between repeats
        matrix[name]["p99_s"] = max(samples)

    def wait_until(pred, timeout=45.0, tag=""):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            time.sleep(0.05)
        raise AssertionError(f"no recovery within {timeout}s: {tag}")

    # ---- serve plane: engine-kill / engine-pause / frontend-kill ----
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--admission-engines", "2"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    baseline = LeakBaseline(PlaneHandles(kube=rt.kube)).capture()
    rt.start()
    rt.frontends.heartbeat_deadline_s = 3.0
    rt.engines.heartbeat_deadline_s = 2.0
    try:
        wait_until(lambda: rt.backplane.connected >= 2, 30,
                   "frontends never connected")
        baseline.plane.frontends = rt.frontends
        baseline.plane.engines = rt.engines
        baseline.track_children()

        def trickle(n=12):
            """An admission load thread riding one outage window."""
            base = probe_seq[0]
            probe_seq[0] += n
            ids = [f"m15-{base + i}" for i in range(n)]
            t = threading.Thread(
                target=cv._load_worker,
                args=(rt.frontends.port, ids, answered, errors, lock),
                daemon=True)
            t.start()
            return t

        def engines_converged():
            return (rt.engines.alive_count()
                    == len(rt.engines.engine_ids)
                    and not any(rt.engines._dirty.values()))

        def engine_fault(pause):
            victim = rt.engines._procs[rt.engines.engine_ids[0]]
            load = trickle()
            t0 = time.monotonic()
            k = rt.engines.engine_ids[0]
            (rt.engines.pause_engine if pause
             else rt.engines.kill_engine)(k)
            wait_until(lambda: rt.engines._procs.get(k) is not victim
                       and engines_converged(),
                       tag="engine pause" if pause else "engine kill")
            mttr = time.monotonic() - t0
            load.join(60)
            return mttr

        def frontend_kill():
            slot = 0
            victim_pid = rt.frontends.child_pids()[slot]
            load = trickle()
            t0 = time.monotonic()
            rt.frontends.kill_child(slot)
            wait_until(lambda: rt.frontends.child_pids().get(slot)
                       not in (None, victim_pid)
                       and rt.frontends.alive()
                       and rt.backplane.connected >= 2,
                       tag="frontend kill")
            mttr = time.monotonic() - t0
            load.join(60)
            return mttr

        sample("engine-kill", lambda: engine_fault(pause=False))
        sample("engine-pause", lambda: engine_fault(pause=True))
        sample("frontend-kill", frontend_kill)

        baseline.track_children()
        verifier.check_admissions(probe_seq[0], answered, errors,
                                  fail_closed=bool(args.fail_closed))
    finally:
        rt.stop()
    verifier.check_leaks(baseline)

    # ---- audit plane: shard-kill --------------------------------------
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.control.audit import AuditManager, ShardedAuditPlane
    from gatekeeper_tpu.control.backplane import AuditShardSupervisor
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    objs = cv._cluster_objects()
    okube = cv._cluster_kube(objs)
    oracle_client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    cv._library(oracle_client)
    oracle_results = [cv._result_key(r) for r in AuditManager(
        okube, oracle_client, interval=3600,
        incremental=True).audit_once()]

    kube = cv._cluster_kube(objs)
    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    sock = os.path.join(tempfile.mkdtemp(prefix="bench15-"), "audit.sock")
    plane_box: list = []
    sup = AuditShardSupervisor(
        2, socket_for=lambda k: f"{sock}.{k}",
        spawn_args=["--log-level", "WARNING"],
        snapshot_provider=lambda k: plane_box[0].sync_snapshot(k),
        heartbeat_deadline_s=2.0)
    splane = ShardedAuditPlane(kube, leader, sup, 2)
    plane_box.append(splane)
    splane.attach()
    cv._library(leader)
    mgr = AuditManager(kube, leader, interval=3600, shard_plane=splane)
    sup.start()
    try:
        round0 = [cv._result_key(r) for r in mgr.audit_once()]
        pre = CheckResult("bench15_audit_clean")
        if round0 != oracle_results:
            pre.violations.append(
                "pre-chaos sharded round differs from oracle")
        verifier.results.append(pre)

        def shard_kill():
            victim = sup._procs[1]
            t0 = time.monotonic()
            sup.kill_engine(1)
            wait_until(lambda: sup._procs.get(1) is not victim
                       and sup.alive_count() == 2
                       and not any(sup._dirty.values()),
                       tag="shard kill")
            mttr = time.monotonic() - t0
            verifier.check_audit_bitequal(
                [cv._result_key(r) for r in mgr.audit_once()],
                oracle_results)
            return mttr

        sample("shard-kill", shard_kill)
    finally:
        sup.stop()
        splane.stop()

    # ---- control plane: leader-kill / apiserver-flap ------------------
    from gatekeeper_tpu.control.kube import FakeKube, LEASE_GVK, LeaseElector

    lkube = FakeKube()
    lkube.register_kind(LEASE_GVK)
    electors = [LeaseElector(lkube, identity=i, lease_duration=0.6,
                             namespace="gk") for i in ("pod-a", "pod-b")]
    for e in electors:
        e.start()
    try:
        wait_until(lambda: any(e.is_leader for e in electors), 15,
                   "no initial leader")

        def leader_kill():
            incumbent = next(e for e in electors if e.is_leader)
            t0 = time.monotonic()
            FAULTS.inject("kube.lease", mode="expire", count=1,
                          match={"identity": incumbent.identity})
            wait_until(lambda: not incumbent.is_leader, 15,
                       "incumbent never deposed")
            wait_until(lambda: any(e.is_leader for e in electors), 15,
                       "no successor elected")
            return time.monotonic() - t0

        sample("leader-kill", leader_kill)
    finally:
        for e in electors:
            e.stop()
        FAULTS.reset()

    from gatekeeper_tpu.control.resilience import GuardedKube

    fkube = FakeKube()
    fkube.register_kind(("constraints.gatekeeper.sh", "v1beta1",
                         "K8sRequiredLabels"))
    fkube.apply({"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                 "kind": "K8sRequiredLabels",
                 "metadata": {"name": "flap-target", "uid": "c-flap"},
                 "spec": {}})
    # the kube.write point lives in GuardedKube's mutating verbs — the
    # flap is felt (and retried through) exactly where production
    # status writes go
    gkube = GuardedKube(fkube)

    def apiserver_flap():
        gvk = ("constraints.gatekeeper.sh", "v1beta1",
               "K8sRequiredLabels")
        t0 = time.monotonic()
        FAULTS.inject("kube.write", mode="error", param="503", count=5)
        while True:
            try:
                obj = fkube.get(gvk, "flap-target")
                obj["status"] = {"probedAt": len(matrix)}
                gkube.update(obj, subresource="status")
                return time.monotonic() - t0
            except Exception:
                if time.monotonic() - t0 > 15:
                    raise
                time.sleep(0.02)

    try:
        sample("apiserver-flap", apiserver_flap)
    finally:
        FAULTS.reset()

    verifier.check_stale_gauges()
    violations = verifier.violation_count()
    mttr_p99 = max(v["p99_s"] for v in matrix.values())
    report = verifier.report()

    print(json.dumps({
        "config": 15, "metric": "chaos_mttr_p99_s",
        "value": round(mttr_p99, 3),
        "unit": ("s, worst harness-measured MTTR (t_healthy - "
                 "t_inject, incl. detection) across engine-kill/"
                 "engine-pause/frontend-kill/shard-kill/leader-kill/"
                 "apiserver-flap x2 repeats; heartbeat deadlines "
                 "2-3s; cpu children; gated alongside "
                 "chaos_invariant_violations == 0"),
        "chaos_invariant_violations": violations,
        "matrix": matrix,
        "checks": [{"name": c["name"], "violations": c["violations"]}
                   for c in report["checks"]],
        "probes": {"submitted": probe_seq[0], "answered": len(answered),
                   "errors": len(errors)},
    }), flush=True)
    assert violations == 0, \
        f"crash-consistency violations under the MTTR matrix: {report}"


# -------------------------------------------------------------- config 16


def _scan_child(cfg_path: str) -> None:
    """--scan-child: one fleet-scan run through the REAL CLI
    (control.scan.scan_main) in a fresh process — cold vs warm AOT is
    a process-boundary property, so each measurement must boot its own
    interpreter. Prints the scan summary JSON line on stdout."""
    import tempfile

    from gatekeeper_tpu.control.scan import scan_main

    with open(cfg_path) as f:
        cfg = json.load(f)
    sf = tempfile.NamedTemporaryFile("w", suffix=".json", delete=False)
    sf.close()
    argv = [cfg["jsonl"], "--format", "jsonl",
            "--loaders", str(cfg.get("loaders", 2)),
            "--batch", str(cfg.get("batch", 256)),
            "--depth", str(cfg.get("depth", 2)),
            "--dedupe", str(cfg.get("dedupe", 65536)),
            "--output", os.devnull, "--summary", sf.name]
    if cfg.get("socket"):
        argv += ["--backplane", cfg["socket"]]
    for p in cfg.get("policies") or []:
        argv += ["--policies", p]
    if cfg.get("aot_dir"):
        argv += ["--aot-dir", cfg["aot_dir"]]
    if cfg.get("compile_cache_dir"):
        argv += ["--compile-cache-dir", cfg["compile_cache_dir"]]
    rc = scan_main(argv)
    with open(sf.name) as f:
        summary = json.load(f)
    os.unlink(sf.name)
    summary["exit"] = rc
    print(json.dumps(summary), flush=True)


def config16():
    """Fleet scan (ISSUE 20): manifests/s through the full
    loader/dedupe/bulk-feed pipeline at 1M+ clusterless manifests
    (BENCH_SCALE-scaled), cold vs warm AOT on the in-process tier plus
    the cross-process backplane tier. The headline is the best warm
    tier — the loader pipeline must keep up with the PR 14 bulk wire
    ceiling, not become the new bottleneck."""
    import shutil
    import subprocess
    import tempfile

    import yaml

    n = int(os.environ.get("BENCH_C16_MANIFESTS",
                           str(int(1_000_000 * SCALE))))
    dup = max(1, int(os.environ.get("BENCH_C16_DUP", "8")))
    unique = max(1, n // dup)
    n = unique * dup
    loaders = int(os.environ.get("BENCH_C16_LOADERS",
                                 str(min(4, os.cpu_count() or 1))))
    work = tempfile.mkdtemp(prefix="gk-bench-scan-")
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        # the inventory export: `unique` distinct objects, each
        # appearing `dup` times (repo trees repeat identical objects
        # heavily — the shape the dedupe tier exists for), in a
        # deterministic shuffle so duplicates interleave instead of
        # clustering
        blobs = [json.dumps(o).encode()
                 for o in synth_mixed_objects(unique, seed=16)]
        order = list(range(unique)) * dup
        random.Random(16).shuffle(order)
        jsonl = os.path.join(work, "inventory.jsonl")
        with open(jsonl, "wb") as f:
            for i in order:
                f.write(blobs[i])
                f.write(b"\n")
        del order
        constraints_yaml = os.path.join(work, "constraints.yaml")
        with open(constraints_yaml, "w") as f:
            yaml.safe_dump_all(
                [{"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                  "kind": kind, "metadata": {"name": cname},
                  "spec": ({"parameters": params} if params else {})}
                 for kind, cname, params in GENERAL_CONSTRAINTS], f)
        policies_dir = os.path.join(
            here, "gatekeeper_tpu", "policies", "general")
        base_cfg = {
            "jsonl": jsonl, "loaders": loaders,
            "policies": [policies_dir, constraints_yaml],
            "aot_dir": os.path.join(work, "aot"),
            "compile_cache_dir": os.path.join(work, "xla-cache"),
        }

        def _run_child(cfg: dict, tag: str) -> dict:
            cfg_path = os.path.join(work, f"scan-{tag}.json")
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--scan-child", cfg_path],
                cwd=here, capture_output=True, text=True, timeout=3600)
            line = (proc.stdout.strip().splitlines() or [""])[-1]
            try:
                return json.loads(line)
            except ValueError:
                return {"error": f"scan child {tag} failed: "
                        + (proc.stderr or "no stderr")[-300:]}

        # cold: empty AOT store + XLA cache, every program compiles
        # inside the measured wall (the short-lived CI invocation)
        cold = _run_child(base_cfg, "cold")
        # warm: same dirs — programs deserialize instead of compiling
        warm = _run_child(base_cfg, "warm")

        # cross-process tier: the scan feeding a separate serving
        # engine over backplane B frames (loader processes pre-encode
        # the envelope bytes)
        bp = {}
        engine = None
        sock = os.path.join(tempfile.gettempdir(),
                            f"gk-bench-scan-{os.getpid()}.sock")
        try:
            engine = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--serve-engine", sock],
                cwd=here, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True)
            line = engine.stdout.readline()
            if "READY" not in (line or ""):
                raise RuntimeError(
                    "scan engine failed to start: "
                    + (engine.stderr.read() or "")[-300:])
            import threading as _th
            _th.Thread(target=engine.stdout.read, daemon=True).start()
            _th.Thread(target=engine.stderr.read, daemon=True).start()
            bp = _run_child({"jsonl": jsonl, "loaders": loaders,
                             "socket": sock}, "backplane")
        except Exception as e:
            bp = {"error": str(e)[:300]}
        finally:
            if engine is not None:
                engine.kill()

        def _rate(s: dict):
            return s.get("manifests_per_sec") if not s.get("error") \
                else None

        warm_rates = [r for r in (_rate(warm), _rate(bp))
                      if r is not None]
        best = max(warm_rates, default=0)
        cold_r, warm_r = _rate(cold), _rate(warm)
        print(json.dumps({
            "config": 16, "metric": "fleet_scan_manifests_per_sec",
            "value": best,
            "unit": f"manifests/s (offline fleet scan, {n} JSONL "
                    f"manifests, {unique} unique x{dup}, general "
                    "library, best warm tier)",
            "fleet_scan_manifests_per_sec": best,
            "manifests": n, "unique": unique, "dup_factor": dup,
            "loaders": loaders,
            "scan_cold_manifests_per_sec": cold_r,
            "scan_warm_manifests_per_sec": warm_r,
            # PR 8's AOT story for short-lived CI invocations: the
            # warm boot must beat the cold one (compile inside vs
            # deserialize) — recorded as the cold->warm speedup
            "cold_warm_speedup": (round(warm_r / cold_r, 2)
                                  if cold_r and warm_r else None),
            "scan_backplane_manifests_per_sec": _rate(bp),
            "tiers": {"inproc_cold": cold, "inproc_warm": warm,
                      "backplane": bp},
            # verdict honesty across tiers: same manifests, same
            # library -> identical deny counts and zero error records
            "denied": warm.get("denied"),
            "tier_verdicts_agree": (
                warm.get("denied") == bp.get("denied")
                if not (warm.get("error") or bp.get("error"))
                else None),
            "errors": (warm.get("errors", 0) or 0)
            + (cold.get("errors", 0) or 0) + (bp.get("errors", 0) or 0),
        }))
    finally:
        shutil.rmtree(work, ignore_errors=True)


def run(which: list[int]) -> int:
    """Run the named configs. A config-level exception no longer kills
    the remaining configs OR vanishes into the log: it prints an
    explicit `{"config": N, "error": ...}` JSON line (bench.py records
    it in the output JSON, so tools/bench_trend.py can tell
    "regressed" from "didn't run") and the process still exits
    nonzero at the end so a blocking CI step on one config fails."""
    table = {1: config1, 2: config2, 3: config3, 5: config5, 6: config6,
             7: config7, 8: config8, 9: config9, 10: config10,
             11: config11, 12: config12, 13: config13, 14: config14,
             15: config15, 16: config16}
    failed = 0
    for c in which:
        if c not in table:
            sys.exit(f"unknown bench config {c}: choose from "
                     f"{sorted(table)} (config 4 is bench.py's headline — "
                     "run `python bench.py` with no --config)")
        try:
            table[c]()
        except Exception as e:
            failed += 1
            import traceback
            traceback.print_exc()
            print(json.dumps({
                "config": c,
                "error": f"{type(e).__name__}: {e}"[:300]}), flush=True)
    return failed


def main() -> None:
    if sys.argv[1:2] == ["--loadgen"]:
        port, rate, duration, seed, out = sys.argv[2:7]
        _loadgen_child(int(port), float(rate), float(duration),
                       int(seed), out)
        return
    if sys.argv[1:2] == ["--serve-engine"]:
        _engine_child(sys.argv[2],
                      decision_cache="--no-decision-cache"
                                     not in sys.argv[3:])
        return
    if sys.argv[1:2] == ["--scan-child"]:
        _scan_child(sys.argv[2])
        return
    if sys.argv[1:2] == ["--mesh-audit"]:
        _mesh_audit_child(int(sys.argv[2]), int(sys.argv[3]))
        return
    if sys.argv[1:2] == ["--coldwarm-child"]:
        _coldwarm_child(sys.argv[2])
        return
    failed = run([int(a) for a in sys.argv[1:]] or [1, 2, 3, 5, 6, 7])
    if failed:
        sys.exit(1)


if __name__ == "__main__":
    main()
