# gatekeeper_tpu control plane image.
# Counterpart of the reference /Dockerfile (go build -> distroless): a
# JAX-enabled Python base carrying the framework and its policy library.
# For TPU nodes use a libtpu-bundled base (e.g. a jax[tpu] image) so the
# device path runs on the hosts' accelerators; on CPU-only clusters the
# same image evaluates through the XLA CPU backend unchanged.
FROM python:3.12-slim AS base

RUN pip install --no-cache-dir "jax[cpu]" pyyaml grpcio \
    && useradd --uid 1000 --gid 0 gatekeeper

WORKDIR /app
COPY gatekeeper_tpu/ gatekeeper_tpu/

USER 1000:999
ENV PYTHONUNBUFFERED=1
ENTRYPOINT ["python", "-m", "gatekeeper_tpu.control.main"]
