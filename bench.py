#!/usr/bin/env python
"""Headline benchmark: the audit cross-product sweep (BASELINE.md config #4).

Workload: 500 K8sRequiredLabels constraints × 100k namespace objects — the
throughput path the reference evaluates one object at a time through the
interpreted Rego engine (pkg/audit/manager.go:250-271 → topdown eval).

Measured: constraint evaluations/second/chip through the compiled device
sweep (extraction amortized across audits; the sweep is what replaces the
reference's per-pair Rego evaluation). Baseline: this framework's own
reference interpreter driver — a faithful local-OPA stand-in (it passes the
reference library's full Rego test corpus) — timed on a subsample of the
same workload and extrapolated.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

import json
import os
import sys
import time

N_OBJECTS = int(os.environ.get("BENCH_OBJECTS", 100_000))
N_CONSTRAINTS = int(os.environ.get("BENCH_CONSTRAINTS", 500))
SAMPLE_OBJECTS = int(os.environ.get("BENCH_BASELINE_OBJECTS", 40))
SAMPLE_CONSTRAINTS = int(os.environ.get("BENCH_BASELINE_CONSTRAINTS", 40))
CHUNK = int(os.environ.get("BENCH_CHUNK", 8192))


def main() -> None:
    t_setup = time.time()
    import numpy as np

    from gatekeeper_tpu.parallel.workload import build_eval_setup

    n_bucket = ((N_OBJECTS + CHUNK - 1) // CHUNK) * CHUNK
    driver, ct, feats, params, table, derived, reviews, cons = \
        build_eval_setup(N_OBJECTS, N_CONSTRAINTS, n_bucket=n_bucket)
    setup_s = time.time() - t_setup

    # ---- compiled sweep (one real chip) -------------------------------
    import jax

    # features/params live on device (the steady-state of a resident audit
    # engine; incremental inventory updates maintain them there)
    feats = jax.tree_util.tree_map(jax.device_put, feats)
    params = jax.tree_util.tree_map(jax.device_put, params)
    table = jax.device_put(table)
    t0 = time.time()
    fires = ct.fires_chunked(feats, params, table, derived, chunk=CHUNK)
    warm_s = time.time() - t0  # includes jit compile
    t0 = time.time()
    iters = 3
    for _ in range(iters):
        fires = ct.fires_chunked(feats, params, table, derived, chunk=CHUNK)
    sweep_s = (time.time() - t0) / iters
    evals = N_OBJECTS * N_CONSTRAINTS
    evals_per_sec = evals / sweep_s
    hits = int(fires[:N_OBJECTS].sum())

    # ---- interpreter baseline (local-OPA stand-in) --------------------
    from gatekeeper_tpu.client.drivers import RegoDriver

    sample_reviews = reviews[:SAMPLE_OBJECTS]
    sample_cons = cons[:SAMPLE_CONSTRAINTS]
    base = RegoDriver()
    # install the same compiled module set
    for name in driver._module_names:
        base.put_module(name, driver._interp.modules[name])
    for c in sample_cons:
        base.put_data(("constraints", "admission.k8s.gatekeeper.sh",
                       "cluster", "constraints.gatekeeper.sh",
                       c["kind"], c["metadata"]["name"]), c)
    t0 = time.time()
    for r in sample_reviews:
        base.query(("hooks", "admission.k8s.gatekeeper.sh", "violation"),
                   {"review": r})
    base_s = time.time() - t0
    base_evals_per_sec = (len(sample_reviews) * len(sample_cons)) / base_s

    out = {
        "metric": "audit_cross_product_evals_per_sec_per_chip",
        "value": round(evals_per_sec),
        "unit": "constraint-evals/s",
        "vs_baseline": round(evals_per_sec / base_evals_per_sec, 1),
        "sweep_wall_s": round(sweep_s, 4),
        "first_call_s": round(warm_s, 2),
        "objects": N_OBJECTS,
        "constraints": N_CONSTRAINTS,
        "violating_pairs": hits,
        "baseline_evals_per_sec": round(base_evals_per_sec),
        "setup_s": round(setup_s, 1),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
