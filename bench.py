#!/usr/bin/env python
"""Headline benchmark: the FULL audit for BASELINE.md config #4.

Workload: 500 K8sRequiredLabels constraints × 100k namespace objects — the
throughput path the reference evaluates one object at a time through the
interpreted Rego engine (pkg/audit/manager.go:250-271 → topdown eval).

Headline metric: wall-clock of one complete `client.audit()` in the steady
state (the recurring --audit-interval sweep of a resident engine): review
flattening + constraint matching + device filter sweep (sparse pair
extraction) + exact host materialization of every firing pair's message.
Inventory extraction and match-signature caches are warm, exactly as they
are between sweeps of a resident audit manager; the cold first sweep is
reported as first_audit_s.

Baseline caveat: vs_baseline compares against this framework's own Python
reference interpreter (a local-OPA stand-in that passes the reference
library's full Rego test corpus), timed on a subsample and extrapolated.
It is a softer target than compiled Go OPA topdown — expect Go to be
~5-20x faster than this baseline, i.e. divide vs_baseline accordingly for
a Go-OPA-relative estimate.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

`--config N [N ...]` runs only the named side configs (bench_configs.py)
in-process and prints their JSON lines — e.g. `python bench.py --config 7`
for the mutation micro-batch bench (reports `mutate_s` + mutation p50).
"""

import argparse
import json
import os
import sys
import time

N_OBJECTS = int(os.environ.get("BENCH_OBJECTS", 100_000))
N_CONSTRAINTS = int(os.environ.get("BENCH_CONSTRAINTS", 500))
SAMPLE_OBJECTS = int(os.environ.get("BENCH_BASELINE_OBJECTS", 40))
SAMPLE_CONSTRAINTS = int(os.environ.get("BENCH_BASELINE_CONSTRAINTS", 40))
TARGET = "admission.k8s.gatekeeper.sh"


def _device_sanity() -> None:
    """A broken accelerator runtime (e.g. a libtpu client/terminal
    mismatch) must degrade this benchmark to CPU, not lose it: probe a
    trivial jit and re-exec under JAX_PLATFORMS=cpu on failure."""
    try:
        import jax
        import numpy as _np
        jax.jit(lambda x: x + 1)(_np.ones(8, _np.float32))
    except Exception as e:
        if os.environ.get("JAX_PLATFORMS") != "cpu":
            print(f"# device probe failed ({type(e).__name__}); "
                  f"falling back to CPU", file=sys.stderr)
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.execv(sys.executable, [sys.executable] + sys.argv)
        raise


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config", type=int, nargs="+", default=None,
                   help="run only these bench_configs.py configs "
                        "(e.g. --config 7 for the mutation micro-batch "
                        "bench) and skip the audit headline")
    p.add_argument("--trend", action="store_true",
                   help="skip the benchmark entirely and print the "
                        "perf-trend report over the committed "
                        "BENCH_r*.json history (tools/bench_trend.py; "
                        "run that directly with --check for the "
                        "CI regression gate)")
    p.add_argument("--trend-check", action="store_true",
                   help="with --trend: exit 1 when any gated headline "
                        "metric's latest round regressed >25%% vs its "
                        "best prior round")
    args = p.parse_args()
    if args.trend or args.trend_check:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tools", "bench_trend.py")
        spec = importlib.util.spec_from_file_location("bench_trend", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.main(["--check"] if args.trend_check else [])
    _device_sanity()
    if args.config:
        import bench_configs
        # per-config failures are recorded (and printed) individually;
        # the exit code still fails a blocking CI step on any of them
        return 1 if bench_configs.run(args.config) else 0
    t_setup = time.time()
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.parallel.workload import (
        REQUIRED_LABELS_TEMPLATE, synth_constraints, synth_objects)
    from gatekeeper_tpu.target import K8sValidationTarget

    driver = TpuDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    for c in synth_constraints(N_CONSTRAINTS, seed=1):
        client.add_constraint(c)
    for o in synth_objects(N_OBJECTS, violate_frac=0.01, seed=0):
        client.add_data(o)
    setup_s = time.time() - t_setup

    # ---- full audit through the public client API ---------------------
    t0 = time.time()
    resp = client.audit()
    first_audit_s = time.time() - t0  # at this scale the async-compile
    # machinery blocks on the background warm (host fallback would cost
    # more than the compile), so this includes jit compile + extraction
    # wait out any remaining background compiles so the steady-state
    # loop measures the device path, not a warming race
    t_warm0 = time.time()
    while driver.warm_status()["compiling"] and \
            time.time() - t_warm0 < 600:
        time.sleep(0.2)
    iters = 4
    audit_s = float("inf")
    for _ in range(iters):
        # the results delta cache (correctly) answers an unchanged
        # re-audit without dispatching; drop it so the HEADLINE keeps
        # measuring the full sweep pipeline (continuity with r1-r5) —
        # the delta steady state is reported separately below
        drop = getattr(driver, "_audit_results_cache", None)
        if drop is not None:
            drop.clear()
        t0 = time.time()
        resp = client.audit()
        audit_s = min(audit_s, time.time() - t0)  # min-of-N: the
        # steady-state capability on a possibly noisy shared host
    n_results = len(resp.results())
    audit_path = driver.last_audit_path  # headline sweep: mesh | single
    # steady state WITH the delta cache: the true recurring-sweep cost
    # when nothing changed between --audit-interval ticks
    delta_audit_s = float("inf")
    for _ in range(2):
        t0 = time.time()
        client.audit()
        delta_audit_s = min(delta_audit_s, time.time() - t0)
    evals = N_OBJECTS * N_CONSTRAINTS
    evals_per_sec = evals / audit_s

    # ---- churn: 1-object mutation between audits ----------------------
    # the incremental path (patch journal) must keep this near the warm
    # steady-state sweep, not force full re-extraction/re-upload
    from gatekeeper_tpu.parallel.workload import LABEL_POOL
    healthy = {k: v[0][0] for k, v in LABEL_POOL.items()}
    mutate_audit_s = float("inf")
    for k in range(2):
        labels = dict(healthy)
        labels["app"] = f"churn{k}"  # healthy value churn: same verdicts
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": "ns-42", "labels": labels}})
        t0 = time.time()
        client.audit()
        mutate_audit_s = min(mutate_audit_s, time.time() - t0)

    # ---- phase breakdown (same warm caches + jits the audit uses) -----
    import numpy as np

    from gatekeeper_tpu.target.batch import match_masks

    reviews = driver._inventory_reviews(TARGET)
    cons = driver._constraints(TARGET)
    lookup_ns = driver._namespace_lookup(TARGET)
    sig_cache = driver._audit_sig_cache(TARGET)
    t0 = time.time()
    mask = match_masks(cons, reviews, lookup_ns, sig_cache)
    match_s = time.time() - t0  # one uncached mask build (the audit
    # itself reuses the generation-keyed mask cache)
    ct = driver.compiled_for("K8sRequiredLabels")
    cand = np.flatnonzero(mask.any(axis=1))
    feat_key = (driver._data_gen, hash(cand.tobytes()))
    cand_reviews = [reviews[int(i)] for i in cand]
    t0 = time.time()
    slabs = list(driver.eval_compiled_pairs_slabbed(
        ct, "K8sRequiredLabels", cand_reviews, cons, feat_key=feat_key))
    sweep_s = time.time() - t0  # device sweep WITHOUT overlap; the
    # audit overlaps slab k+1 with slab k's materialization
    inventory = driver._inventory_tree(TARGET)
    t0 = time.time()
    results = []
    n_pairs = 0
    for rows, cols in slabs:
        keep = mask[cand[rows], cols]
        n_pairs += int(keep.sum())
        results.extend(driver.materialize_pairs(
            TARGET, cons, cand_reviews, rows[keep], cols[keep], inventory))
    mat_s = time.time() - t0

    # ---- interpreter baseline (local-OPA stand-in) --------------------
    from gatekeeper_tpu.client.drivers import RegoDriver

    sample_reviews = reviews[:SAMPLE_OBJECTS]
    sample_cons = cons[:SAMPLE_CONSTRAINTS]
    base = RegoDriver()
    base._codegen_for = lambda *a, **k: None  # pure interpreter baseline
    for name in driver._module_names:
        base.put_module(name, driver._interp.modules[name])
    for c in sample_cons:
        base.put_data(("constraints", TARGET, "cluster",
                       "constraints.gatekeeper.sh",
                       c["kind"], c["metadata"]["name"]), c)
    t0 = time.time()
    for r in sample_reviews:
        base.query(("hooks", TARGET, "violation"), {"review": r})
    base_s = time.time() - t0
    base_evals_per_sec = (len(sample_reviews) * len(sample_cons)) / base_s
    base_full_audit_s = evals / base_evals_per_sec

    # ---- cold vs warm restart: the tentpole's tracked number ----------
    # fresh subprocesses against one initially-empty compile-cache/AOT
    # dir pair: run 1 pays every XLA compile, run 2 boots like a
    # restarted pod with the populated cache volume (deserialize-and-go)
    import bench_configs

    try:
        coldwarm = bench_configs.coldwarm_probe("4")
    except Exception as e:  # never lose the headline to the probe
        coldwarm = {"error": str(e)[:200]}

    # ---- configs #1/#2/#3/#5/#6, driver-captured ----------------------
    import subprocess

    configs = {}
    want_configs = ["1", "2", "3", "5", "6", "7", "9", "10", "11", "12",
                    "13", "14", "15", "16"]
    try:
        # FULL scale by default: BENCH_r0N.json must carry the
        # 10k-object and 50k-pod numbers, not reduced-scale stand-ins
        env = dict(os.environ)
        proc = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "bench_configs.py")]
            + want_configs,
            capture_output=True, text=True, env=env,
            timeout=int(os.environ.get("BENCH_CONFIGS_TIMEOUT", 2700)))
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    d = json.loads(line)
                    configs[str(d.get("config"))] = d
                except ValueError:
                    pass
        if proc.returncode != 0 and not configs:
            configs["error"] = proc.stderr[-500:]
        # a config that produced NO line at all (hard crash before its
        # own error record, cut-off output) must still be
        # distinguishable from "regressed" in the trend table: record
        # an explicit per-config error instead of silent absence
        for c in want_configs:
            if c not in configs:
                configs[c] = {
                    "config": int(c),
                    "error": "no output (crashed or cut off; rc="
                             f"{proc.returncode}) "
                             + proc.stderr[-200:].strip()}
    except subprocess.TimeoutExpired as e:
        for line in (e.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    d = json.loads(line)
                    configs[str(d.get("config"))] = d
                except ValueError:
                    pass
        configs["timeout"] = True
        for c in want_configs:
            if c not in configs:
                configs[c] = {"config": int(c),
                              "error": "timeout before this config ran"}
    except Exception as e:  # never lose the headline to the side configs
        configs["error"] = str(e)[:200]

    out = {
        "metric": "full_audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": "s (one client.audit(), min of 4 warm sweeps: match + "
                "device sparse sweep overlapped with exact message "
                "materialization; 500 constraints x 100k objects)",
        "vs_baseline": round(base_full_audit_s / audit_s, 1),
        "baseline_note": "baseline is this repo's own Python reference "
                         "interpreter (local-OPA stand-in), subsampled and "
                         "extrapolated; compiled Go OPA topdown would be "
                         "~5-20x faster than that baseline",
        "sweep_wall_s": round(sweep_s, 4),
        "match_s": round(match_s, 3),
        "materialize_s": round(mat_s, 3),
        # ROADMAP item 3's gate: <= 1.0 means the steady audit is
        # sweep-bound (message materialization no longer dominates)
        # 3 decimals: at the post-PR 11 ratio scale (~0.03) two-decimal
        # rounding turns one ULP of noise into a >25% trend-gate trip
        "materialize_vs_sweep":
            round(mat_s / sweep_s, 3) if sweep_s > 0 else None,
        "evals_per_sec_per_chip": round(evals_per_sec),
        "first_audit_s": round(first_audit_s, 2),
        # cold restart (no cache volume) vs warm restart (populated XLA
        # cache + AOT serialized-program store) first audit, plus where
        # each run's device programs came from (aot/cache/fresh)
        "cold_first_audit_s": coldwarm.get("cold_first_audit_s"),
        "warm_first_audit_s": coldwarm.get("warm_first_audit_s"),
        "cold_compile_sources": coldwarm.get("cold_compile_sources"),
        "warm_compile_sources": coldwarm.get("warm_compile_sources"),
        # a failed probe must be distinguishable from a missing number:
        # carry the captured reason instead of four silent nulls
        "coldwarm_error": coldwarm.get("error")
        or coldwarm.get("cold_error") or coldwarm.get("warm_error"),
        "delta_audit_s": round(delta_audit_s, 4),
        "audit_path": audit_path,
        "device_programs": driver.warm_status(),
        "n_devices": len(__import__("jax").devices()),
        # execution platform: rounds measured on different JAX
        # backends are not comparable — bench_trend restarts every
        # gated series when this changes (host-class move, not a
        # code regression)
        "jax_backend": __import__("jax").default_backend(),
        "mutate_audit_s": round(mutate_audit_s, 3),
        # mutating-admission headline (config 7): one micro-batch's
        # batched mutate pass at the largest mutator-library size
        "mutate_s": (configs.get("7") or {}).get("mutate_s"),
        # serving-plane headline (config 5): best open-loop HTTP rate
        # meeting the p99<100ms SLO across the pre-forked frontend
        # worker counts (the --admission-workers topology)
        "admission_rps": (configs.get("5") or {}).get("admission_rps"),
        # warm-restart headline (config 9): restore-snapshots
        # time-to-ready vs the cold full list/encode boot
        "warm_boot_s": (configs.get("9") or {}).get("value"),
        "cold_boot_s": (configs.get("9") or {}).get("cold_boot_s"),
        # streaming-audit headline (config 11): violation detection
        # latency (watch event -> constraint-status write) p50/p99
        # under churn, its speedup over the interval polling line, and
        # the warm what-if preview sweep over a 100k+-object inventory
        "violation_detection_ms":
            (configs.get("11") or {}).get("violation_detection_ms"),
        "detection_speedup_p99":
            (configs.get("11") or {}).get("detection_speedup_p99"),
        "whatif_preview_s":
            (configs.get("11") or {}).get("whatif_preview_s"),
        # compiler-widening headline (config 12): the shipped general
        # library's device-compiled fraction (1.0 = no kind audits at
        # interpreter speed) and the best interpreter-vs-device audit
        # speedup on the extended-form corpus the widening unlocked
        "general_library_compiled_fraction":
            (configs.get("12") or {}).get(
                "general_library_compiled_fraction"),
        "compile_widening_speedup": (configs.get("12") or {}).get("value"),
        # sharded-inventory headline (config 13): one composed audit
        # round over the process-sharded plane — objects/s at the best
        # shard count and its full-round wall
        "sharded_objects_per_sec": (configs.get("13") or {}).get("value"),
        "sharded_sweep_wall_s":
            (configs.get("13") or {}).get("sweep_wall_s"),
        "sharded_best_shards": (configs.get("13") or {}).get(
            "best_shards"),
        # chaos headline (config 15): worst harness-measured MTTR over
        # the six-fault matrix, and the crash-consistency verifier's
        # violation count (the config asserts it 0; the copy makes a
        # nonzero impossible to miss in the round record)
        "chaos_mttr_p99_s": (configs.get("15") or {}).get("value"),
        "chaos_invariant_violations":
            (configs.get("15") or {}).get("chaos_invariant_violations"),
        # fleet-scan headline (config 16): offline clusterless
        # manifests/s through the loader/dedupe/bulk-feed pipeline,
        # best warm tier
        "fleet_scan_manifests_per_sec":
            (configs.get("16") or {}).get("value"),
        # multichip headline (config 10): default mesh-sharded audit at
        # 1M+ objects vs the forced single-device path
        "mesh_audit_s": (configs.get("10") or {}).get("value"),
        "mesh_audit_vs_single_device":
            (configs.get("10") or {}).get("vs_single_device"),
        "mesh_audit_path": (configs.get("10") or {}).get("audit_path"),
        "objects": N_OBJECTS,
        "constraints": N_CONSTRAINTS,
        "violating_pairs": n_pairs,
        "violations_materialized": n_results,
        "baseline_evals_per_sec": round(base_evals_per_sec),
        "baseline_full_audit_s": round(base_full_audit_s),
        "setup_s": round(setup_s, 1),
        "configs": configs,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
