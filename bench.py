#!/usr/bin/env python
"""Headline benchmark: the FULL audit for BASELINE.md config #4.

Workload: 500 K8sRequiredLabels constraints × 100k namespace objects — the
throughput path the reference evaluates one object at a time through the
interpreted Rego engine (pkg/audit/manager.go:250-271 → topdown eval).

Headline metric: end-to-end audit wall-clock in the steady state (the
recurring --audit-interval sweep of a resident engine): constraint
matching + device filter sweep + exact host materialization of every
firing pair's messages. Extraction (host JSON → feature tensors) is
cached across audits and reported separately, as are the phase times.

Baseline caveat: vs_baseline compares against this framework's own Python
reference interpreter (a local-OPA stand-in that passes the reference
library's full Rego test corpus), timed on a subsample and extrapolated.
It is a softer target than compiled Go OPA topdown — expect Go to be
~5-20x faster than this baseline, i.e. divide vs_baseline accordingly for
a Go-OPA-relative estimate.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}
"""

import json
import os
import sys
import time

N_OBJECTS = int(os.environ.get("BENCH_OBJECTS", 100_000))
N_CONSTRAINTS = int(os.environ.get("BENCH_CONSTRAINTS", 500))
SAMPLE_OBJECTS = int(os.environ.get("BENCH_BASELINE_OBJECTS", 40))
SAMPLE_CONSTRAINTS = int(os.environ.get("BENCH_BASELINE_CONSTRAINTS", 40))
CHUNK = int(os.environ.get("BENCH_CHUNK", 8192))
TARGET = "admission.k8s.gatekeeper.sh"


def main() -> None:
    t_setup = time.time()
    import numpy as np

    from gatekeeper_tpu.parallel.workload import build_eval_setup

    n_bucket = ((N_OBJECTS + CHUNK - 1) // CHUNK) * CHUNK
    driver, ct, feats, params, table, derived, reviews, cons = \
        build_eval_setup(N_OBJECTS, N_CONSTRAINTS, n_bucket=n_bucket)
    setup_s = time.time() - t_setup

    import jax

    # features/params live on device (steady state of a resident audit
    # engine; incremental inventory updates maintain them there)
    feats = jax.tree_util.tree_map(jax.device_put, feats)
    params = jax.tree_util.tree_map(jax.device_put, params)
    table = jax.device_put(table)

    # ---- phase 1: device filter sweep (one real chip) -----------------
    t0 = time.time()
    fires = ct.fires_chunked(feats, params, table, derived, chunk=CHUNK)
    warm_s = time.time() - t0  # includes jit compile
    t0 = time.time()
    iters = 3
    for _ in range(iters):
        fires = ct.fires_chunked(feats, params, table, derived, chunk=CHUNK)
    sweep_s = (time.time() - t0) / iters
    evals = N_OBJECTS * N_CONSTRAINTS
    fires = fires[:N_OBJECTS]
    hits = int(fires.sum())

    # ---- phase 2: constraint matching (host, grouped) -----------------
    from gatekeeper_tpu.target.batch import match_masks

    lookup_ns = driver._namespace_lookup(TARGET)
    t0 = time.time()
    mask = match_masks(cons, reviews, lookup_ns)
    match_s = time.time() - t0

    # ---- phase 3: exact message materialization (host JIT) ------------
    inventory = driver._inventory_tree(TARGET)
    pairs = np.nonzero(np.logical_and(fires, mask))
    t0 = time.time()
    results = []
    for ri, ci in zip(*pairs):
        results.extend(driver._eval_template_violations(
            TARGET, cons[int(ci)], reviews[int(ri)], "deny", inventory,
            None))
    mat_s = time.time() - t0

    audit_s = sweep_s + match_s + mat_s
    evals_per_sec = evals / audit_s

    # ---- interpreter baseline (local-OPA stand-in) --------------------
    from gatekeeper_tpu.client.drivers import RegoDriver

    sample_reviews = reviews[:SAMPLE_OBJECTS]
    sample_cons = cons[:SAMPLE_CONSTRAINTS]
    base = RegoDriver()
    base._codegen_for = lambda *a, **k: None  # pure interpreter baseline
    for name in driver._module_names:
        base.put_module(name, driver._interp.modules[name])
    for c in sample_cons:
        base.put_data(("constraints", TARGET, "cluster",
                       "constraints.gatekeeper.sh",
                       c["kind"], c["metadata"]["name"]), c)
    t0 = time.time()
    for r in sample_reviews:
        base.query(("hooks", TARGET, "violation"), {"review": r})
    base_s = time.time() - t0
    base_evals_per_sec = (len(sample_reviews) * len(sample_cons)) / base_s
    base_full_audit_s = evals / base_evals_per_sec

    out = {
        "metric": "full_audit_wall_clock_s",
        "value": round(audit_s, 3),
        "unit": "s (match + device sweep + exact message materialization; "
                "500 constraints x 100k objects)",
        "vs_baseline": round(base_full_audit_s / audit_s, 1),
        "baseline_note": "baseline is this repo's own Python reference "
                         "interpreter (local-OPA stand-in), subsampled and "
                         "extrapolated; compiled Go OPA topdown would be "
                         "~5-20x faster than that baseline",
        "sweep_wall_s": round(sweep_s, 4),
        "match_s": round(match_s, 3),
        "materialize_s": round(mat_s, 3),
        "evals_per_sec_per_chip": round(evals_per_sec),
        "first_call_s": round(warm_s, 2),
        "objects": N_OBJECTS,
        "constraints": N_CONSTRAINTS,
        "violating_pairs": hits,
        "violations_materialized": len(results),
        "baseline_evals_per_sec": round(base_evals_per_sec),
        "baseline_full_audit_s": round(base_full_audit_s),
        "setup_s": round(setup_s, 1),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
