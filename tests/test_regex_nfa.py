"""Device regex NFA conformance: byte-NFA subset simulation must agree
with Python re.search on every corpus pattern (the patterns the policy
library actually uses) plus adversarial constructions — on both the host
reference simulation and the single-dispatch device scan."""

import re

import numpy as np
import pytest

from gatekeeper_tpu.ops.regex_nfa import (
    Unsupported,
    compile_pattern,
    scan_vocab,
)

# every re_match/allowedRegex pattern appearing in the reference library
# + the shipped policy library + workload generators
CORPUS_PATTERNS = [
    "^[0-9]+$",
    "^[0-9]+[.][0-9]+$",
    "^(extensions|networking.k8s.io)$",
    "^(extensions|networking.k8s.io)/.+$",
    "^[a-z]+.corp.example$",
    "^[a-z]+$",
    "^prod$|^dev$",
    "^us-",
    "^[a-z0-9-]+$",
    "^cc-[0-9]+$",
    "^[ab]$",
    "^[a-zA-Z]+.agilebank.demo$",
]

ADVERSARIAL_PATTERNS = [
    "", "a", "abc", "a*", "a+b?", "(ab)+c", "a|", "(a|b)*c$",
    "^$", "x^a|b", "a$b|c", "[^a-z]", "[-a]", "[a-]", "[\\]]",
    "\\d+\\.\\d+", "\\w+@\\w+", "ab|cd|ef", "((a|b)(c|d))+",
    ".*middle.*", "end$", "^start", "[A-Fa-f0-9]+$",
]

STRINGS = [
    "", "a", "b", "ab", "abc", "abcabc", "prod", "dev", "production",
    "extensions", "networking.k8s.io", "networking.k8s.io/v1beta1",
    "extensionsX", "1", "123", "1.5", "12.34", "..", "us-east1",
    "team.corp.example", "teamXcorpXexample", "cc-100", "cc-",
    "kernel.msgmax", "net.ipv4.ip_local_port_range", "middle",
    "has middle here", "end", "the end", "endx", "start", "xstart",
    "user@host", "DEADbeef", "a-z", "-", "]", "^", "$", "aa|bb",
    "registry.corp.example/app:v1", "\x01n123", "runtime/default",
]


@pytest.mark.parametrize("pattern", CORPUS_PATTERNS + ADVERSARIAL_PATTERNS)
def test_host_simulation_matches_re(pattern):
    prog = compile_pattern(pattern)
    for s in STRINGS:
        want = re.search(pattern, s) is not None
        got = prog.match_host(s)
        assert got == want, (pattern, s, got, want)


def test_device_scan_matches_re():
    patterns = CORPUS_PATTERNS + ADVERSARIAL_PATTERNS
    got = scan_vocab(patterns, STRINGS, force_device=True)
    assert got is not None
    want = np.array([[re.search(p, s) is not None for s in STRINGS]
                     for p in patterns])
    mism = np.argwhere(got != want)
    assert not len(mism), [(patterns[i], STRINGS[j], bool(got[i, j]))
                           for i, j in mism[:5]]


def test_host_and_device_paths_agree():
    pats = CORPUS_PATTERNS[:4]
    host = scan_vocab(pats, STRINGS, force_device=False)
    dev = scan_vocab(pats, STRINGS, force_device=True)
    assert (host == dev).all()


def test_unsupported_patterns_fall_back():
    for pattern in ("a{3}", "(?i)abc", "(?P<x>a)", "a\\b", "é+"):
        with pytest.raises(Unsupported):
            compile_pattern(pattern)
    assert scan_vocab(["a{3}"], ["aaa"]) is None


def test_non_ascii_strings_fall_back():
    assert scan_vocab(["^.$"], ["é"]) is None  # byte-vs-char '.' semantics


def test_long_strings_fall_back():
    assert scan_vocab(["^a+$"], ["a" * 300]) is None


def test_match_tables_batched_extension_parity(monkeypatch):
    """MatchTables' batched NFA extension must produce bit-identical
    rows to the host re.search path (pad entry, canon-num markers, and
    unsupported-pattern rows included)."""
    import re as _re

    from gatekeeper_tpu.ops import regex_nfa
    from gatekeeper_tpu.ops.strtab import MatchTables, StringTable, canon_num

    monkeypatch.setattr(regex_nfa, "DEVICE_CROSSOVER", 1)

    def build(batched: bool):
        st = StringTable()
        mt = MatchTables(st)
        for s in STRINGS:
            st.intern(s or "x")
        st.intern(canon_num(123))
        pats = CORPUS_PATTERNS + ["a{3}"]  # last one: host-only fallback
        for p in pats:
            mt.row("re_match", p)
        if not batched:
            # force per-row host path by vetoing the batch
            monkeypatch.setattr(regex_nfa, "try_compile_device",
                                lambda p: None)
        return mt.materialize()

    # device build FIRST: the host build's monkeypatch (vetoing
    # try_compile_device) must not leak into it
    dev = build(batched=True)
    host = build(batched=False)
    assert host.shape == dev.shape
    assert (host == dev).all()


def test_newline_and_nul_strings_fall_back():
    """re gives '.' and '$' special newline behavior the byte NFA does
    not model, and NUL is the scan terminator — both must veto the
    device path (r3 code-review findings)."""
    import re as _re

    assert scan_vocab(["a.b"], ["a\nb"]) is None
    assert scan_vocab(["end$"], ["the end\n"]) is None
    assert scan_vocab(["a$"], ["a\x00b"]) is None
    # sanity on what re actually does there
    assert _re.search("a.b", "a\nb") is None
    assert _re.search("end$", "the end\n") is not None
