"""Multi-device sharding tests (virtual 8-device CPU mesh via conftest).

Pin the distributed audit path that the driver's dryrun_multichip
exercises: shard_map + psum over a data×model mesh must agree
bit-for-bit with the single-device sweep — including uneven batch
padding, constraint (model-axis) sharding, and derived vocab columns
flowing through the shard_map as replicated operands.
"""

import jax
import numpy as np
import pytest

from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.ir.features import extract_batch
from gatekeeper_tpu.ir.params import encode_params
from gatekeeper_tpu.parallel.collectives import make_audit_step
from gatekeeper_tpu.parallel.mesh import (
    make_mesh,
    pad_batch,
    shard_features,
    shard_params,
)
from gatekeeper_tpu.parallel.workload import build_eval_setup
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU platform")


def device_setup(template, constraints, objects):
    """Generic analog of workload.build_eval_setup for any template."""
    driver = TpuDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(template)
    for c in constraints:
        client.add_constraint(c)
    kind = constraints[0]["kind"]
    ct = driver.compiled_for(kind)
    assert ct is not None, f"{kind} must device-compile"
    reviews = []
    for o in objects:
        r = {"kind": {"group": "", "version": o.get("apiVersion", "v1"),
                      "kind": o["kind"]},
             "name": o["metadata"]["name"], "object": o}
        if "namespace" in o["metadata"]:
            r["namespace"] = o["metadata"]["namespace"]
        reviews.append(r)
    feats, _, _ = extract_batch(ct.program, driver.strtab, reviews)
    cons = driver._constraints(TARGET)
    pd = [(c.get("spec") or {}).get("parameters") or {} for c in cons]
    params = encode_params(ct.program, pd, driver.strtab,
                           driver.match_tables)
    derived = driver._derived_arrays(kind, ct)
    table = driver.match_tables.materialize_packed()
    return ct, feats, params, table, derived


def run_sharded(ct, feats, params, table, derived, data, model,
                n_valid=None, shard_c=None):
    """n_valid: true object count (the extractor pow2-buckets N, so the
    feature dim may exceed it; rows >= n_valid are masked on device)."""
    mesh = make_mesh(devices=jax.devices()[: data * model], data=data,
                     model=model)
    feats, n_feat = pad_batch(feats, data)
    if n_valid is None:
        n_valid = n_feat
    feats = shard_features(feats, mesh)
    params = shard_params(params, mesh,
                          shard_c=(model > 1 if shard_c is None
                                   else shard_c))
    step = make_audit_step(ct._eval, mesh)
    fires, counts = step(feats, params, table, derived, np.int32(n_valid))
    return np.asarray(fires)[:n_valid], np.asarray(counts)


def test_sharded_equals_single_device():
    _, ct, feats, params, table, derived, reviews, cons = build_eval_setup(
        n_objects=64, n_constraints=8, violate_frac=0.4)
    expected = ct.fires(feats, params, table, derived)
    fires, counts = run_sharded(ct, feats, params, table, derived,
                                data=8, model=1)
    assert (fires == expected).all()
    assert (counts == expected.sum(axis=0)).all()
    assert counts.sum() > 0


def test_uneven_batch_padding_masked():
    """N not divisible by the data axis: padding rows would fire absence
    clauses (empty objects have no labels) — n_valid masking must keep
    them out of both verdicts and psum'd counts."""
    _, ct, feats, params, table, derived, reviews, cons = build_eval_setup(
        n_objects=53, n_constraints=4, violate_frac=0.5)
    expected = ct.fires(feats, params, table, derived)[:53]
    fires, counts = run_sharded(ct, feats, params, table, derived,
                                data=8, model=1, n_valid=53)
    assert fires.shape == (53, len(cons))
    assert (fires == expected).all()
    assert (counts == expected.sum(axis=0)).all()
    assert counts.sum() > 0


def test_model_axis_constraint_sharding():
    """C sharded over the model axis (4x2 mesh): parameter tensors split
    across devices, verdict columns reassembled, counts replicated."""
    _, ct, feats, params, table, derived, reviews, cons = build_eval_setup(
        n_objects=32, n_constraints=6, violate_frac=0.5)
    expected = ct.fires(feats, params, table, derived)
    fires, counts = run_sharded(ct, feats, params, table, derived,
                                data=4, model=2)
    assert (fires == expected).all()
    assert (counts == expected.sum(axis=0)).all()
    assert counts.sum() > 0


def test_derived_columns_through_shard_map():
    """A to_number-derived vocab column (host-precomputed lookup table)
    must flow through shard_map as a replicated operand and agree with
    the single-device sweep."""
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8smaxreplicas"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sMaxReplicas"}}},
            "targets": [{"target": TARGET, "rego": """
package k8smaxreplicas
violation[{"msg": "too many replicas"}] {
  to_number(input.review.object.metadata.labels.replicas) > input.parameters.max
}
"""}],
        },
    }
    constraints = [{
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sMaxReplicas", "metadata": {"name": f"c{i}"},
        "spec": {"parameters": {"max": m}},
    } for i, m in enumerate([2, 5, 7])]
    objects = [{"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": f"n{i}",
                             "labels": {"replicas": str(i)}}}
               for i in range(24)]
    ct, feats, params, table, derived = device_setup(template, constraints,
                                                     objects)
    assert derived, "template must actually produce a derived column"
    expected = ct.fires(feats, params, table, derived)[:24]
    fires, counts = run_sharded(ct, feats, params, table, derived,
                                data=8, model=1, n_valid=24)
    assert (fires == expected).all()
    assert (counts == expected.sum(axis=0)).all()
    assert counts.sum() > 0
    # sanity vs ground truth: replicas i violates max m iff i > m
    want = np.array([[i > m for m in [2, 5, 7]] for i in range(24)])
    assert (expected == want).all()


def test_make_mesh_validates_factorization():
    with pytest.raises(ValueError):
        make_mesh(devices=jax.devices()[:6], data=4, model=2)
    mesh = make_mesh(devices=jax.devices()[:8], model=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}


def _mesh_driver(n_min=64):
    """TpuDriver with the production mesh path forced on: low review
    threshold and a pinned device-latency EMA so the adaptive cost model
    cannot route the sweep back to the host mid-test."""
    drv = TpuDriver()
    assert drv._mesh is not None, "8-device platform must yield a mesh"
    drv.MESH_MIN_REVIEWS = n_min
    drv._dev_batch_lat_s = 1e-4
    return drv


def _labels_workload(client, n):
    from gatekeeper_tpu import policies

    client.add_template(policies.load("general/requiredlabels"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}}})
    for i in range(n):
        o = {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": f"ns{i:05d}"}}
        if i % 3 == 0:
            o["metadata"]["labels"] = {"owner": "me"}
        client.add_data(o)


def _audit_key(results):
    return sorted((r.msg, (r.resource or {}).get("metadata", {})
                   .get("name", "")) for r in results)


def test_driver_mesh_audit_equals_single_device():
    """The PRODUCTION audit path sharded over the mesh (TpuDriver with
    >1 device, through client.audit()) must equal the single-device
    TpuDriver and the interpreter driver exactly — and must actually
    take the mesh path (asserted via last_audit_path, so this cannot
    go vacuous)."""
    from gatekeeper_tpu.client import RegoDriver

    N = 2048
    dm = _mesh_driver()
    cm = Backend(dm).new_client([K8sValidationTarget()])
    _labels_workload(cm, N)
    got_mesh = _audit_key(cm.audit().results())
    assert dm.last_audit_path == "mesh(data=8)", dm.last_audit_path

    ds = TpuDriver()
    ds._mesh = None
    ds._dev_batch_lat_s = 1e-4
    cs = Backend(ds).new_client([K8sValidationTarget()])
    _labels_workload(cs, N)
    got_single = _audit_key(cs.audit().results())
    assert ds.last_audit_path == "single"

    ci = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    _labels_workload(ci, N)
    got_interp = _audit_key(ci.audit().results())

    assert got_mesh == got_single == got_interp
    assert len(got_mesh) == N - (N + 2) // 3, "non-vacuous"

    # steady state re-audit: nothing changed, so the results delta cache
    # answers without re-dispatching the sweep
    assert _audit_key(cm.audit().results()) == got_mesh
    assert dm.last_audit_path == "delta(1/1)", dm.last_audit_path

    # single-object churn via DELETE: the journal breaks, the delta
    # cache is bypassed, and the full mesh sweep must run again over
    # rebuilt sharded buffers
    for c in (cm, cs):
        c.remove_data({"apiVersion": "v1", "kind": "Namespace",
                       "metadata": {"name": "ns00001"}})
    got_mesh2 = _audit_key(cm.audit().results())
    assert dm.last_audit_path == "mesh(data=8)"
    assert got_mesh2 == _audit_key(cs.audit().results())
    assert len(got_mesh2) == len(got_mesh) - 1


def test_driver_mesh_gather_capacity_retry():
    """Every object violating: the per-shard firing-row gather must
    overflow its initial capacity and re-run at a larger one without
    losing rows."""
    dm = _mesh_driver()
    cm = Backend(dm).new_client([K8sValidationTarget()])
    from gatekeeper_tpu import policies

    cm.add_template(policies.load("general/requiredlabels"))
    cm.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}}})
    N = 4096  # 512 firing rows per shard > the 256 initial capacity
    for i in range(N):
        cm.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"ns{i:05d}"}})
    out = cm.audit().results()
    assert dm.last_audit_path == "mesh(data=8)"
    assert len(out) == N, f"{len(out)} != {N} (rows lost in retry?)"
    ct = dm.compiled_for("K8sRequiredLabels")
    assert ct._rows_cap_mesh >= 512


def test_driver_mesh_gather_capacity_ratchets():
    """Alternating small/large mesh sweeps: the per-shard gather
    capacity must RATCHET (like the single-device slab path) instead of
    resetting to each sweep's count — a shrink must not make the next
    grow re-trip the overflow re-run."""
    dm = _mesh_driver()
    cm = Backend(dm).new_client([K8sValidationTarget()])
    from gatekeeper_tpu import policies

    cm.add_template(policies.load("general/requiredlabels"))
    cm.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "need-owner"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}}})
    N = 4096  # 512 firing rows per shard > the 256 initial capacity
    for i in range(N):
        cm.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"ns{i:05d}"}})
    assert len(cm.audit().results()) == N
    assert dm.last_audit_path == "mesh(data=8)"
    ct = dm.compiled_for("K8sRequiredLabels")
    cap_grown = ct._rows_cap_mesh
    assert cap_grown >= 512

    def relabel(owner: bool):
        # in-place churn (same N, same buckets) so every sweep stays on
        # the mesh path with identical tensor shapes
        for i in range(N):
            o = {"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": f"ns{i:05d}"}}
            if owner:
                o["metadata"]["labels"] = {"owner": "me"}
            cm.add_data(o)
        dm._audit_results_cache.clear()  # force the device sweep
        dm._dev_batch_lat_s = 1e-4  # re-pin: the consume path's real
        # CPU latency sample would route the next sweep to the host

    relabel(owner=True)  # shrink: ~0 firing rows
    assert cm.audit().results() == []
    assert dm.last_audit_path == "mesh(data=8)"
    assert ct._rows_cap_mesh >= cap_grown, \
        "gather capacity shrank after a small sweep"

    relabel(owner=False)  # grow again: 512 firing rows per shard
    jit_calls = []
    orig = ct._mesh_pairs_jit

    def counting(*a, **k):
        jit_calls.append(a)
        return orig(*a, **k)

    ct._mesh_pairs_jit = counting
    out = cm.audit().results()
    ct._mesh_pairs_jit = orig
    assert dm.last_audit_path == "mesh(data=8)"
    assert len(out) == N
    # dispatch resolves the jit exactly once; with the pre-ratchet reset
    # the overflow retry loop would resolve it a second time mid-consume
    assert len(jit_calls) == 1, \
        f"overflow re-run re-triggered: {len(jit_calls)} jit lookups"


def test_driver_mesh_respects_min_reviews():
    """Below the mesh threshold the driver stays single-device."""
    dm = _mesh_driver(n_min=1 << 30)
    dm._dev_batch_lat_s = 1e-4
    cm = Backend(dm).new_client([K8sValidationTarget()])
    _labels_workload(cm, 2048)
    out = cm.audit().results()
    assert dm.last_audit_path == "single"
    assert len(out) == 2048 - (2048 + 2) // 3


def test_sharded_inventory_join_membership():
    """The inventory-join membership kernel (ir/join.py: searchsorted
    over the unique-key table with count/identity rules) sharded over
    the mesh's data axis must agree with the single-device answer —
    review keys shard across chips; the key table rides replicated."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from gatekeeper_tpu.ir.join import IK_MULTI, IK_REV_MISSING, KEY_PAD
    from gatekeeper_tpu.parallel.mesh import make_mesh

    try:
        from jax import shard_map as _shard_map
        shard_map = _shard_map.shard_map if hasattr(
            _shard_map, "shard_map") else _shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    rng = np.random.default_rng(0)
    kb, n, h = 256, 64, 4
    u = np.sort(rng.choice(10_000, size=200, replace=False)).astype(
        np.int32)
    u_p = np.full(kb, np.iinfo(np.int32).max, dtype=np.int32)
    u_p[: len(u)] = u
    cnt_p = np.zeros(kb, dtype=np.int32)
    cnt_p[: len(u)] = rng.integers(1, 3, size=len(u))
    sik_p = np.full(kb, IK_MULTI, dtype=np.int32)
    single = cnt_p[: len(u)] == 1
    sik_p[: len(u)][single] = rng.integers(100, 110,
                                           size=int(single.sum()))
    karr = np.where(rng.random((n, h)) < 0.25,
                    rng.choice(u, size=(n, h)),
                    KEY_PAD).astype(np.int32)
    iks = rng.integers(100, 110, size=n).astype(np.int32)

    def kernel(u_p, cnt_p, sik_p, karr, iks):
        pos = jnp.clip(jnp.searchsorted(u_p, karr), 0, u_p.shape[0] - 1)
        found = (u_p[pos] == karr) & (karr != KEY_PAD)
        fire = found & ((cnt_p[pos] >= 2) | (sik_p[pos] != iks[:, None]))
        return jnp.any(fire, axis=1)

    want = np.asarray(jax.jit(kernel)(u_p, cnt_p, sik_p, karr, iks))

    mesh = make_mesh(devices=jax.devices()[:8], data=8, model=1)
    sharded = jax.jit(shard_map(
        kernel, mesh=mesh,
        in_specs=(P(), P(), P(), P("data", None), P("data")),
        out_specs=P("data")))
    got = np.asarray(sharded(u_p, cnt_p, sik_p, karr, iks))
    assert (got == want).all()
    assert want.any() and not want.all(), "non-vacuous membership split"


def test_driver_mesh_slab_loop_equals_mono():
    """The double-buffered mesh SLAB loop (per-shard materialization
    overlapping the next slab's device sweep) must produce exactly the
    monolithic mesh dispatch's results, in the same global row-major
    order, across multiple slabs per shard — including a gather
    capacity overflow inside one slab."""
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.ir.evaljax import _MeshSlabPairs

    N = 16384
    dm = _mesh_driver()
    dm.sweep_chunk = 256
    dm.mesh_slab_local = 512  # n_loc = 2048 -> 4 slabs per shard
    cm = Backend(dm).new_client([K8sValidationTarget()])
    _labels_workload(cm, N)
    handles = []
    orig = dm._dispatch_handle

    def spy(*a, **k):
        h = orig(*a, **k)
        handles.append(h)
        return h

    dm._dispatch_handle = spy
    got = cm.audit().results()
    dm._dispatch_handle = orig
    assert dm.last_audit_path == "mesh(data=8)", dm.last_audit_path
    assert any(isinstance(h, _MeshSlabPairs) for h in handles), \
        "audit did not take the slab loop"

    ds = TpuDriver()
    ds._mesh = None
    ds._dev_batch_lat_s = 1e-4
    cs = Backend(ds).new_client([K8sValidationTarget()])
    _labels_workload(cs, N)
    want = cs.audit().results()
    # exact order parity, not just set equality: the slab loop's blocks
    # interleave shards, and the consume loop must reassemble global
    # row-major order
    assert [(r.msg, (r.resource or {}).get("metadata", {}).get("name"))
            for r in got] == \
        [(r.msg, (r.resource or {}).get("metadata", {}).get("name"))
         for r in want]
    assert len(got) == N - (N + 2) // 3, "non-vacuous"


def test_mesh_slab_dispatch_direct_overflow_and_order():
    """fires_pairs_mesh_dispatch with a forced small slab: every object
    firing overflows the initial 256-per-shard gather capacity inside
    each slab; the retry must lose no rows and the capacity must
    ratchet."""
    driver, ct, feats, params, table, derived, reviews, cons = \
        build_eval_setup(n_objects=4096, n_constraints=1,
                         violate_frac=1.0)
    mesh = make_mesh(devices=jax.devices()[:8], data=8, model=1)
    n_feat = next(iter(next(iter(feats.values())).values())).shape[0]
    assert n_feat % 8 == 0
    ct._rows_cap_mesh = 8  # force the per-slab overflow retry
    handle = ct.fires_pairs_mesh_dispatch(
        feats, params, table, mesh, derived, chunk=128,
        n_true=len(reviews), slab=128)  # n_loc=512 -> 4 slabs
    rows = np.concatenate([r for r, _c in handle.pairs()])
    expected = ct.fires(feats, params, table, derived)[: len(reviews)]
    want_rows = np.flatnonzero(expected.any(axis=1))
    assert sorted(rows.tolist()) == want_rows.tolist()
    assert len(want_rows) > 64, "non-vacuous overflow workload"
    assert ct._rows_cap_mesh > 8, "gather capacity did not ratchet"


def test_review_batch_sparse_mesh_equals_interpreter():
    """Discovery-mode audits stage the whole cluster through
    review_batch: at audit scale it must route through the sparse
    gather (mesh-sharded here) and agree exactly with the interpreter
    driver."""
    from gatekeeper_tpu.client import RegoDriver

    N = 2048
    dm = _mesh_driver()
    dm.SPARSE_BATCH_MIN = 256
    dm.async_warm = False
    cm = Backend(dm).new_client([K8sValidationTarget()])
    _labels_workload(cm, 0)  # template + constraint only

    ri = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    _labels_workload(ri, 0)

    def reviews():
        out = []
        for i in range(N):
            o = {"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": f"ns{i:05d}"}}
            if i % 3 == 0:
                o["metadata"]["labels"] = {"owner": "me"}
            out.append({"kind": {"group": "", "version": "v1",
                                 "kind": "Namespace"},
                        "name": o["metadata"]["name"], "object": o})
        return out

    got = dm.review_batch(TARGET, reviews())
    want = [ri.driver.query(("hooks", TARGET, "violation"),
                            {"review": r}).results
            for r in reviews()]
    assert [sorted(r.msg for r in per) for per in got] == \
        [sorted(r.msg for r in per) for per in want]
    n_fired = sum(1 for per in got if per)
    assert n_fired == N - (N + 2) // 3, "non-vacuous"


# ------------------------------------------------- mesh edge conditions


def test_pad_batch_non_divisible_counts():
    """pad_batch must zero-pad every [N, ...] leaf up to the next
    multiple of the data axis and report the TRUE row count."""
    feats = {"slot": {"a": np.arange(10, dtype=np.int32),
                      "b": np.ones((10, 3), dtype=np.float32)}}
    out, n_true = pad_batch(feats, 8)
    assert n_true == 10
    assert out["slot"]["a"].shape == (16,)
    assert out["slot"]["b"].shape == (16, 3)
    assert (out["slot"]["a"][:10] == np.arange(10)).all()
    assert (out["slot"]["a"][10:] == 0).all()
    assert (out["slot"]["b"][10:] == 0).all()
    # already divisible: returned arrays are unpadded
    out2, n2 = pad_batch(feats, 5)
    assert n2 == 10 and out2["slot"]["a"].shape == (10,)


def test_build_mesh_rounds_down_to_power_of_two(monkeypatch, caplog):
    """6 visible devices must shard over 4 (with a warning), not
    silently never take the mesh path — the divisibility gate checks
    power-of-two extraction buckets against the data axis."""
    monkeypatch.setenv("GATEKEEPER_TPU_MESH", "6")
    import logging

    with caplog.at_level(logging.WARNING,
                         logger="gatekeeper_tpu.ir.driver"):
        drv = TpuDriver()
    assert drv._mesh is not None
    assert dict(drv._mesh.shape) == {"data": 4, "model": 1}
    assert any("rounded down" in r.message for r in caplog.records)


def test_build_mesh_off_and_capped(monkeypatch):
    monkeypatch.setenv("GATEKEEPER_TPU_MESH", "off")
    assert TpuDriver()._mesh is None
    monkeypatch.setenv("GATEKEEPER_TPU_MESH", "2")
    assert dict(TpuDriver()._mesh.shape) == {"data": 2, "model": 1}
    monkeypatch.setenv("GATEKEEPER_TPU_MESH", "1")
    assert TpuDriver()._mesh is None  # one device is not a mesh


def test_shard_and_replicate_specs_on_host_mesh():
    """Placement spec correctness on the 8-device host-platform mesh:
    features split on "data" along the leading axis, params replicated
    by default (sharded over "model" when asked), scalars replicated."""
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(devices=jax.devices()[:8], data=4, model=2)
    feats = {"s": {"a": np.zeros((16, 5), np.int32),
                   "v": np.zeros(16, np.int32)}}
    params = {"s": {"p": np.zeros((8, 3), np.int32)}}
    sf = shard_features(feats, mesh)
    assert sf["s"]["a"].sharding.spec == P("data", None)
    assert sf["s"]["v"].sharding.spec == P("data")
    sp = shard_params(params, mesh)
    assert sp["s"]["p"].sharding.spec == P(None, None)
    sp_c = shard_params(params, mesh, shard_c=True)
    assert sp_c["s"]["p"].sharding.spec == P("model", None)
    from gatekeeper_tpu.parallel.mesh import replicate

    r = replicate(np.float32(3.0), mesh)
    assert r.sharding.spec == P()
    # the placements actually address every device in the mesh
    assert len(sf["s"]["a"].sharding.device_set) == 8


def test_dev_mesh_cache_lru_bounded():
    """TpuDriver._dev_mesh_cache must not grow without bound on a
    churn-heavy audit: live host arrays past DEV_MESH_CACHE_MAX are
    LRU-evicted, and a hit refreshes recency."""
    drv = _mesh_driver()
    drv.DEV_MESH_CACHE_MAX = 8
    keep = [np.full((16,), i, np.int32) for i in range(12)]  # pin alive
    first = keep[0]
    drv._dev_mesh({"s": {"a": first}}, data_leading=True)
    assert (id(first), True) in drv._dev_mesh_cache
    for a in keep[1:8]:
        drv._dev_mesh({"s": {"a": a}}, data_leading=True)
        # touch the first entry so it stays most-recent
        drv._dev_mesh({"s": {"a": first}}, data_leading=True)
    assert len(drv._dev_mesh_cache) == 8
    for a in keep[8:]:
        drv._dev_mesh({"s": {"a": a}}, data_leading=True)
    assert len(drv._dev_mesh_cache) == drv.DEV_MESH_CACHE_MAX
    # the repeatedly-touched entry survived; the single-use early ones
    # were evicted oldest-first
    assert (id(first), True) in drv._dev_mesh_cache
    assert (id(keep[1]), True) not in drv._dev_mesh_cache
    # a hit on a surviving entry still returns the resident buffer
    again = drv._dev_mesh({"s": {"a": first}}, data_leading=True)
    assert np.asarray(again["s"]["a"]).tolist() == first.tolist()
