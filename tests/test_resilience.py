"""Fault-injection chaos suite (resilience tentpole).

Storms injected through gatekeeper_tpu.utils.faults drive the resilience
layer end to end: deadline propagation answers every AdmissionReview
before its propagated deadline, the bounded queue sheds instead of
queueing into certain timeout, the shared kube-write breaker opens /
half-opens / closes observably (and audit defers status writes while it
is open), device-eval failures quarantine one template behind its own
breaker while the interpreter keeps serving, watch drops degrade to
polling, SIGTERM-style shutdown drains in-flight reviews, and the
liveness watchdog flags a wedged pipeline.

Every test runs under a HARD SIGALRM timeout: an injected hang must fail
that test fast instead of eating the CI job budget.
"""

from __future__ import annotations

import http.client
import json
import signal
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.audit import AuditManager
from gatekeeper_tpu.control.health import HealthServer
from gatekeeper_tpu.control.kube import FakeKube, KubeError
from gatekeeper_tpu.control.resilience import (
    BreakerOpen,
    CircuitBreaker,
    GuardedKube,
    RetryBudget,
)
from gatekeeper_tpu.control.webhook import (
    AdmissionDeadline,
    AdmissionShed,
    MicroBatcher,
    ValidationHandler,
    WebhookServer,
    request_deadline,
)
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.faults import FAULTS, FaultError

TARGET = "admission.k8s.gatekeeper.sh"

PER_TEST_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout_and_clean_faults():
    """Hard per-test timeout + fault isolation: no armed fault (or hang)
    leaks into the next test."""

    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        FAULTS.reset()


def _policy_client(driver=None):
    driver = driver if driver is not None else RegoDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    return driver, client


def _review(name, labels=None, timeout_s=None, ns="d"):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": ns}}
    if labels:
        obj["metadata"]["labels"] = labels
    request = {"uid": f"uid-{name}", "operation": "CREATE",
               "kind": {"group": "", "version": "v1", "kind": "Pod"},
               "name": name, "namespace": ns,
               "userInfo": {"username": "chaos"}, "object": obj}
    if timeout_s is not None:
        request["timeoutSeconds"] = timeout_s
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": request}


# ------------------------------------------------- deadline propagation


def test_request_deadline_parsing():
    now = time.monotonic()
    # default 10s minus the 1s margin
    d = request_deadline({})
    assert 8.5 <= d - now <= 9.5
    # explicit 5s minus 20% margin
    d = request_deadline({"timeoutSeconds": 5})
    assert 3.5 <= d - now <= 4.5
    # clamped into [0.5, 30]; junk falls back to the default
    assert request_deadline({"timeoutSeconds": 9999}) - now <= 30
    assert request_deadline({"timeoutSeconds": "bogus"}) - now <= 10


def test_deadline_expiry_answers_failure_stance_before_api_server():
    """A hung flusher must not make the API server time us out: the
    verdict (per the fail-open/fail-closed stance, status=timeout)
    ships before request.timeoutSeconds elapses."""
    _, client = _policy_client()
    release = threading.Event()

    def hang(reviews):
        release.wait(20)
        return [[] for _ in reviews]

    for fail_closed, want_allowed in ((False, True), (True, False)):
        batcher = MicroBatcher(client, evaluate=hang)
        handler = ValidationHandler(client, batcher=batcher,
                                    fail_closed=fail_closed)
        t0 = time.monotonic()
        out = handler.handle(_review("p1", timeout_s=1))
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, "answered after the API server gave up"
        assert out["response"]["allowed"] is want_allowed
        assert out["response"]["status"]["code"] == 504
        assert out["response"]["uid"] == "uid-p1"
        release.set()
        batcher.stop()
        release.clear()


def test_url_timeout_query_param_propagates_deadline():
    """admission.k8s.io/v1 carries NO timeoutSeconds in the body — the
    API server conveys its budget as ?timeout=5s on the webhook URL.
    The HTTP layer must fold it into the request so a hung evaluation
    is answered within the REAL budget, not the configured default."""
    from gatekeeper_tpu.control.webhook import go_duration_s

    assert go_duration_s("5s") == 5.0
    assert go_duration_s("500ms") == 0.5
    assert go_duration_s("1m10s") == 70.0
    assert go_duration_s("junk") is None and go_duration_s(None) is None

    _, client = _policy_client()
    release = threading.Event()

    def hang(reviews):
        release.wait(20)
        return [[] for _ in reviews]

    batcher = MicroBatcher(client, evaluate=hang)
    handler = ValidationHandler(client, batcher=batcher)
    server = WebhookServer(handler, None, port=0)
    server.start()
    try:
        review = _review("qp")          # NO timeoutSeconds in the body
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        t0 = time.monotonic()
        conn.request("POST", "/v1/admit?timeout=1s", json.dumps(review),
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, "ignored the API server's ?timeout budget"
        assert out["response"]["status"]["code"] == 504
    finally:
        release.set()
        server.stop(drain_timeout=1.0)


def test_retry_call_releases_probe_slot_on_unexpected_error():
    """A non-KubeError escaping fn() (LB returning HTML, json garbage)
    must release a claimed half-open probe slot — a wedged breaker
    would block every future write until restart."""
    from gatekeeper_tpu.control.resilience import retry_call

    br = CircuitBreaker("g5", failure_threshold=1, reset_timeout=0.1)
    br.record_failure()  # open
    time.sleep(0.15)     # half-open

    def garbage():
        raise ValueError("not json")

    with pytest.raises(ValueError):
        retry_call(garbage, breaker=br)
    assert br.state == CircuitBreaker.OPEN  # probe failed, re-opened
    time.sleep(0.15)
    assert retry_call(lambda: "ok", breaker=br) == "ok"  # not wedged
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_half_open_probe_lease_expires():
    """A claimed probe slot whose claimant never resolves it (thread
    torn down mid-write) must not wedge the breaker in half-open
    forever: the lease expires after reset_timeout and the slot
    re-opens for the next caller."""
    br = CircuitBreaker("g6", failure_threshold=1, reset_timeout=0.1)
    br.record_failure()  # open
    time.sleep(0.15)     # half-open
    assert br.allow() is True    # claim the probe slot... and vanish
    assert br.allow() is False   # slot held: second caller refused
    time.sleep(0.15)             # lease expires
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow() is True    # slot reclaimed by a live caller
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_abandon_releases_probe_without_verdict():
    """abandon() hands the probe slot back with NO state transition:
    a cancelled probe says nothing about the server, so half-open
    stays half-open (not re-opened as a failure would, not closed as
    a success would)."""
    br = CircuitBreaker("g7", failure_threshold=1, reset_timeout=0.05)
    br.record_failure()
    time.sleep(0.1)
    assert br.allow() is True
    br.abandon()
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow() is True    # immediately available again
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN  # real verdicts still count


def test_retry_call_baseexception_abandons_probe():
    """KeyboardInterrupt/SystemExit skip `except Exception` — the
    probe slot must still be released, and since cancellation is not
    a health verdict the breaker must NOT transition."""
    from gatekeeper_tpu.control.resilience import retry_call

    br = CircuitBreaker("g8", failure_threshold=1, reset_timeout=0.05)
    br.record_failure()
    time.sleep(0.1)
    assert br.state == CircuitBreaker.HALF_OPEN

    def cancelled():
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        retry_call(cancelled, breaker=br)
    assert br.state == CircuitBreaker.HALF_OPEN  # no verdict recorded
    assert retry_call(lambda: "ok", breaker=br) == "ok"  # slot free
    assert br.state == CircuitBreaker.CLOSED


def test_publish_gate_excludes_without_holding_lock():
    """audit's _PublishGate: same mutual exclusion as the Lock it
    replaced, but the internal lock is NOT held while the guarded body
    runs — so a kube-write backoff sleeping inside the gate holds no
    lock (the PR 15 locktrace advisory this closes)."""
    from gatekeeper_tpu.control.audit import _PublishGate

    gate = _PublishGate()
    order: list = []
    entered = threading.Event()
    release = threading.Event()

    def first():
        with gate:
            # the token is held, the internal lock is not: a backoff
            # sleep here runs lock-free
            assert gate._lock.locked() is False
            order.append("first-in")
            entered.set()
            release.wait(5)
            order.append("first-out")

    def second():
        entered.wait(5)
        with gate:
            order.append("second-in")

    t1 = threading.Thread(target=first)
    t2 = threading.Thread(target=second)
    t1.start()
    t2.start()
    time.sleep(0.1)
    assert order == ["first-in"]  # second excluded while first holds
    release.set()
    t1.join(5)
    t2.join(5)
    assert order == ["first-in", "first-out", "second-in"]


def test_submit_many_sheds_each_item_exactly_once():
    """Bulk enqueue against a full queue: every refused item counts
    once on the shed counter, admitted items never count, and the
    draining-batcher path (AdmissionShed without capacity pressure)
    counts nothing."""
    def evaluate(reviews):
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=0.001, max_batch=8,
                     evaluate=evaluate, max_queue=2)
    try:
        # one lock pass admits items 0-1 and sheds 2-4: the flusher
        # cannot drain capacity mid-enqueue, so the split is exact
        outs = b.submit_many([{"i": i} for i in range(5)], timeout=5.0)
        assert b.shed == 3               # items 2..4, exactly once each
        assert [isinstance(o, AdmissionShed) for o in outs] == \
            [False, False, True, True, True]
        assert outs[0] == [] and outs[1] == []
        b.stop()
        outs = b.submit_many([{"i": 9}], timeout=1.0)
        assert isinstance(outs[0], AdmissionShed)
        assert b.shed == 3               # shutdown refusals don't count
    finally:
        b.stop()


def test_batch_seals_for_tightest_member_deadline():
    """A request with a deadline tighter than the collection window
    must not wait out the full window."""
    done = []

    def evaluate(reviews):
        done.append(time.monotonic())
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=5.0, evaluate=evaluate)
    try:
        t0 = time.monotonic()
        b.submit({"r": 1}, deadline=time.monotonic() + 0.5)
        assert time.monotonic() - t0 < 0.5
    finally:
        b.stop()


# ----------------------------------------------------- load shedding


def test_bounded_queue_sheds_beyond_depth():
    """Beyond --admission-max-queue in-flight requests, submits shed
    immediately (status=shed through the handler) instead of queueing
    into certain timeout — and every shed request IS answered."""
    release = threading.Event()

    def hang(reviews):
        release.wait(20)
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=0.001, max_batch=2, evaluate=hang,
                     max_queue=4)
    outcomes: list = []

    def submit(i):
        try:
            b.submit({"i": i}, timeout=5.0)
            outcomes.append("ok")
        except AdmissionShed:
            outcomes.append("shed")
        except AdmissionDeadline:
            outcomes.append("deadline")

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(12)]
    try:
        for t in threads:
            t.start()
        deadline = time.time() + 5
        while len(outcomes) < 8 and time.time() < deadline:
            time.sleep(0.01)  # the 8 beyond-depth submits shed instantly
        assert outcomes.count("shed") == 8, outcomes
        assert b.shed == 8
        release.set()
        for t in threads:
            t.join(10)
        # zero unanswered: every submit resolved one way or another
        assert len(outcomes) == 12
        assert outcomes.count("ok") == 4
    finally:
        release.set()
        b.stop()


def test_shed_reported_as_failure_stance_verdict():
    _, client = _policy_client()
    release = threading.Event()

    def hang(reviews):
        release.wait(20)
        return [[] for _ in reviews]

    batcher = MicroBatcher(client, max_wait=0.001, max_batch=1,
                           evaluate=hang, max_queue=1)
    handler = ValidationHandler(client, batcher=batcher)
    try:
        filler = threading.Thread(
            target=lambda: handler.handle(_review("fill", timeout_s=5)),
            daemon=True)
        filler.start()
        deadline = time.time() + 5
        while batcher._pending < 1 and time.time() < deadline:
            time.sleep(0.005)
        out = handler.handle(_review("shed-me", timeout_s=5))
        assert out["response"]["allowed"] is True  # fail-open stance
        assert out["response"]["status"]["code"] == 429
        release.set()
        filler.join(10)
    finally:
        release.set()
        batcher.stop()


def test_raise_mode_flush_fault_errors_batch_not_flusher():
    """A raise-mode flush fault must fail THAT batch (entries get the
    error, _pending slots release) — not kill the flusher thread and
    leak the shed accounting toward permanent 100% shedding."""
    b = MicroBatcher(None, max_wait=0.001,
                     evaluate=lambda rs: [[] for _ in rs], max_queue=4)
    try:
        FAULTS.inject("webhook.flush", mode="raise", count=1)
        with pytest.raises(FaultError):
            b.submit({"x": 1}, timeout=5.0)
        assert b.healthy()  # flusher survived the injected raise
        with b._cv:
            assert b._pending == 0  # no leaked slots
        assert b.submit({"x": 2}, timeout=5.0) == []  # still serving
    finally:
        b.stop()


# ------------------------------------------------- kube write breaker


def test_circuit_breaker_transitions():
    br = CircuitBreaker("t", failure_threshold=3, reset_timeout=0.2)
    assert br.state == CircuitBreaker.CLOSED
    for _ in range(3):
        assert br.allow()
        br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()
    time.sleep(0.25)
    assert br.state == CircuitBreaker.HALF_OPEN
    # exactly one probe slot
    assert br.allow()
    assert not br.allow()
    br.record_failure()  # probe failed: re-open
    assert br.state == CircuitBreaker.OPEN
    time.sleep(0.25)
    assert br.allow()
    br.record_success()  # probe succeeded: close
    assert br.state == CircuitBreaker.CLOSED


def test_guarded_kube_retries_transient_then_succeeds():
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    guard = GuardedKube(kube, CircuitBreaker("g1", failure_threshold=10),
                        RetryBudget(10))
    FAULTS.inject("kube.write", mode="error", param="503", count=2)
    out = guard.create({"apiVersion": "v1", "kind": "Namespace",
                        "metadata": {"name": "ns1"}})
    assert out["metadata"]["name"] == "ns1"
    assert FAULTS.fired("kube.write") == 2  # two injected 503s retried


def test_guarded_kube_breaker_opens_and_fails_fast_under_storm():
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    br = CircuitBreaker("g2", failure_threshold=4, reset_timeout=0.3)
    guard = GuardedKube(kube, br, RetryBudget(3, refill_per_s=0.0),
                        attempts=3)
    FAULTS.inject("kube.write", mode="error", param="503")

    def ns(i):
        return {"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": f"s{i}"}}

    with pytest.raises(KubeError):
        guard.create(ns(0))
    # storm continues until the breaker opens, then writes are refused
    # locally without touching the API
    for i in range(1, 6):
        with pytest.raises(KubeError):
            guard.create(ns(i))
    assert br.state == CircuitBreaker.OPEN
    calls_before = len(kube.calls)
    with pytest.raises(BreakerOpen):
        guard.create(ns(99))
    assert len(kube.calls) == calls_before  # fast fail: no API call
    # storm ends; breaker half-opens and the probe write closes it
    FAULTS.clear("kube.write")
    time.sleep(0.35)
    out = guard.create(ns(7))
    assert out["metadata"]["name"] == "s7"
    assert br.state == CircuitBreaker.CLOSED


def test_audit_defers_status_writes_while_breaker_open():
    """Under a kube 5xx storm the audit keeps sweeping but defers
    constraint-status PATCHes (no hot-loop); the pending delta is
    written on the first healthy sweep."""
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("constraints.gatekeeper.sh", "v1beta1",
                        "K8sNeedOwner"), namespaced=False)
    _, client = _policy_client()
    kube.create({"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                 "kind": "K8sNeedOwner",
                 "metadata": {"name": "need-owner"}, "spec": {}})
    client.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "bad-ns"}})
    # threshold 1: the breaker counts failed WRITES (one per sweep
    # here), so the first storm-failed status write opens it
    br = CircuitBreaker("audit-w", failure_threshold=1, reset_timeout=0.3)
    guard = GuardedKube(kube, br, RetryBudget(2, refill_per_s=0.0),
                        attempts=2)
    mgr = AuditManager(guard, client, audit_from_cache=True,
                       write_breaker=br)
    FAULTS.inject("kube.write", mode="error", param="503")
    results = mgr.audit_once()  # storm: writes fail, breaker opens
    assert len(results) == 1  # the sweep itself still found violations
    assert br.state == CircuitBreaker.OPEN
    updates_while_open = len([c for c in kube.calls if c[0] == "update"])
    results = mgr.audit_once()  # breaker open: writes fully deferred
    assert mgr.last_sweep_stats is not None
    assert len([c for c in kube.calls if c[0] == "update"]) == \
        updates_while_open, "status writes not deferred while open"
    # storm ends: the next sweep (post reset) writes the pending status
    FAULTS.clear("kube.write")
    time.sleep(0.35)
    mgr.audit_once()
    status = kube.get(("constraints.gatekeeper.sh", "v1beta1",
                       "K8sNeedOwner"), "need-owner").get("status") or {}
    assert status.get("totalViolations") == 1
    assert br.state == CircuitBreaker.CLOSED


def test_client_errors_do_not_trip_breaker():
    """A deterministic 4xx (RBAC 403, schema 422) means the server
    ANSWERED: no retry, and the shared breaker must not open — a config
    mistake must not escalate into a serving outage."""
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    br = CircuitBreaker("g4", failure_threshold=2, reset_timeout=30)
    guard = GuardedKube(kube, br, RetryBudget(10))
    FAULTS.inject("kube.write", mode="error", param="403")
    for i in range(6):
        with pytest.raises(KubeError) as ei:
            guard.create({"apiVersion": "v1", "kind": "Namespace",
                          "metadata": {"name": f"x{i}"}})
        assert not isinstance(ei.value, BreakerOpen)
    assert br.state == CircuitBreaker.CLOSED
    assert FAULTS.fired("kube.write") == 6  # exactly one attempt each


# ------------------------------------------- device-eval quarantine


def test_eval_failure_quarantines_template_and_interp_serves():
    from gatekeeper_tpu.ir import TpuDriver

    driver, client = _policy_client(TpuDriver())
    for i in range(6):
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": f"n{i}"}})
    driver.quarantine_base_s = 0.3
    FAULTS.inject("eval.device", mode="raise",
                  match={"kind": "K8sNeedOwner"})
    results = client.audit().results()
    # availability held: the interpreter served every violation
    assert len(results) == 6
    q = driver.quarantine_status()
    assert "K8sNeedOwner" in q and q["K8sNeedOwner"]["fails"] == 1
    from gatekeeper_tpu.control.metrics import REGISTRY
    assert 'gatekeeper_tpu_template_quarantined{kind="K8sNeedOwner"} 1' \
        in REGISTRY.render()
    # while quarantined, the device path is not even attempted
    fired = FAULTS.fired("eval.device")
    assert len(client.audit().results()) == 6
    assert FAULTS.fired("eval.device") == fired
    # storm ends; after the backoff the half-open probe restores the
    # device path and clears the quarantine
    FAULTS.clear("eval.device")
    time.sleep(0.35)
    driver._dev_batch_lat_s = 1e-4   # cost model: prefer the device
    driver._host_pair_rate = 1.0
    assert len(client.audit().results()) == 6
    assert driver.quarantine_status() == {}
    assert 'gatekeeper_tpu_template_quarantined{kind="K8sNeedOwner"} 0' \
        in REGISTRY.render()


def test_quarantine_half_open_allows_single_probe():
    """After the backoff expires, exactly ONE caller takes the probe
    lease; concurrent callers stay on the interpreter instead of a
    thundering herd of doomed device evals."""
    from gatekeeper_tpu.ir import TpuDriver

    driver, _client = _policy_client(TpuDriver())
    driver.quarantine_base_s = 0.01
    driver._quarantine_kind("K8sNeedOwner", "review-eval",
                            RuntimeError("injected"))
    time.sleep(0.05)  # backoff expired: half-open
    assert driver._quarantined("K8sNeedOwner") is False  # takes the lease
    assert driver._quarantined("K8sNeedOwner") is True   # probe in flight
    assert driver.compiled_for("K8sNeedOwner") is None
    # probe failure re-quarantines (doubled backoff) and resets the lease
    driver._quarantine_kind("K8sNeedOwner", "review-eval",
                            RuntimeError("probe failed"))
    assert driver._quarantined("K8sNeedOwner") is True
    assert driver.quarantine_status()["K8sNeedOwner"]["fails"] == 2


def test_one_bad_template_does_not_take_down_cobatched_reviews():
    from gatekeeper_tpu.ir import TpuDriver

    driver, client = _policy_client(TpuDriver())
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedteam"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedTeam"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedteam
violation[{"msg": "no team label"}] {
  not input.review.object.metadata.labels.team
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedTeam", "metadata": {"name": "need-team"},
        "spec": {}})
    driver.quarantine_base_s = 30.0
    driver._dev_batch_lat_s = 1e-4
    driver._host_pair_rate = 1.0
    FAULTS.inject("eval.device", match={"kind": "K8sNeedOwner"})
    reviews = [_review(f"p{i}")["request"] for i in range(8)]
    outs = driver.review_batch(TARGET, reviews)
    # every co-batched review got BOTH verdicts: the faulted kind from
    # the interpreter fallback, the healthy kind wherever it ran
    assert len(outs) == 8
    for per_review in outs:
        kinds = sorted((r.constraint or {}).get("kind")
                       for r in per_review)
        assert kinds == ["K8sNeedOwner", "K8sNeedTeam"]
    assert "K8sNeedOwner" in driver.quarantine_status()
    assert "K8sNeedTeam" not in driver.quarantine_status()


# ----------------------------------------------------- watch drops


def test_watch_drop_storm_degrades_to_polling_then_heals():
    from gatekeeper_tpu.control.audit import InventoryTracker

    kube = FakeKube()
    kube.register_kind(("", "v1", "Pod"))
    guard = GuardedKube(kube)
    _, client = _policy_client()
    tracker = InventoryTracker(guard, client)
    FAULTS.inject("kube.watch", mode="error")
    tracker.set_gvks([("", "v1", "Pod")])
    assert tracker._poll == {("", "v1", "Pod")}  # degraded to polling
    kube.create({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "p1", "namespace": "d"}})
    stats = tracker.apply_pending()  # re-list diff still syncs state
    assert stats["total"] == 1
    # the storm ends: the next sweep quietly re-subscribes the stream
    FAULTS.clear("kube.watch")
    tracker.apply_pending()
    assert tracker._poll == set()
    kube.create({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "p2", "namespace": "d"}})
    assert tracker.apply_pending()["total"] == 2
    tracker.stop()


# ------------------------------------------------- graceful shutdown


def test_graceful_shutdown_drains_inflight_reviews():
    """stop() must answer in-flight reviews (drain) instead of dropping
    sockets mid-review."""
    _, client = _policy_client()

    def slowish(reviews):
        time.sleep(0.3)
        from gatekeeper_tpu.control.webhook import MicroBatcher as MB
        return MB._evaluate_violations(batcher, reviews)

    batcher = MicroBatcher(client, evaluate=slowish)
    handler = ValidationHandler(client, batcher=batcher)
    server = WebhookServer(handler, None, port=0)
    server.start()
    results: list = []

    def post(i):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                              timeout=10)
            conn.request("POST", "/v1/admit",
                         json.dumps(_review(f"g{i}", timeout_s=10)),
                         {"Content-Type": "application/json"})
            results.append(json.loads(conn.getresponse().read()))
        except Exception as e:  # pragma: no cover - the failure mode
            results.append(e)

    threads = [threading.Thread(target=post, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    deadline = time.time() + 5
    while time.time() < deadline:
        if server.http.inflight() >= 4:
            break
        time.sleep(0.005)
    server.stop(drain_timeout=10.0)
    for t in threads:
        t.join(10)
    assert len(results) == 4
    for r in results:
        assert isinstance(r, dict) and "response" in r, r
        # a real verdict (deny: pods lack the owner label), not an
        # error-stance answer synthesized from a dropped evaluation
        assert r["response"]["allowed"] is False


# ------------------------------------------------- liveness watchdog


def test_liveness_watchdog_flags_wedged_flusher():
    release = threading.Event()

    def hang(reviews):
        release.wait(30)
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=0.001, evaluate=hang)
    try:
        assert b.healthy()
        t = threading.Thread(
            target=lambda: _swallow(lambda: b.submit({"x": 1},
                                                     timeout=0.4)),
            daemon=True)
        t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with b._scv:
                if b._flushing:
                    break
            time.sleep(0.005)
        time.sleep(0.3)
        assert not b.healthy(max_stall=0.2)  # wedged: stale heartbeat
        srv = HealthServer("127.0.0.1", 0)
        srv.add_liveness("batcher", lambda: b.healthy(max_stall=0.2))
        srv.start()
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=5)
        conn.request("GET", "/healthz")
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 503 and b"batcher" in body
        srv.shutdown()
    finally:
        release.set()
        b.stop()


def test_liveness_watchdog_flags_dead_audit_loop():
    kube = FakeKube()
    _, client = _policy_client()
    mgr = AuditManager(kube, client, interval=0.1, audit_from_cache=True)
    assert mgr.healthy()  # not started: vacuously alive
    mgr.start()
    time.sleep(0.05)
    assert mgr.healthy()
    mgr.stop()
    deadline = time.time() + 5
    while mgr._thread.is_alive() and time.time() < deadline:
        time.sleep(0.02)
    assert mgr.healthy()  # stopped on purpose: not a liveness failure
    mgr._stop.clear()     # simulate a CRASHED (not stopped) loop
    assert not mgr.healthy()


def _swallow(fn):
    try:
        fn()
    except Exception:
        pass


# --------------------------------------------------- end-to-end chaos


def test_chaos_storm_every_admission_answered():
    """The acceptance storm: kube 5xx on every write, slowed flushes,
    and a per-template device-eval fault — every submitted
    AdmissionReview receives a verdict before its propagated deadline,
    the process survives, and breaker/quarantine state is observable."""
    from gatekeeper_tpu.control.main import Runtime, build_parser
    from gatekeeper_tpu.control.metrics import REGISTRY

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--audit-interval", "0.2",
        "--health-addr", "127.0.0.1:0",
        "--kube-breaker-threshold", "3", "--kube-breaker-reset", "0.5",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        rt.kube.create({
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8sneedowner"},
            "spec": {
                "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
                "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
        })
        rt.manager.drain()
        rt.kube.create({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sNeedOwner",
            "metadata": {"name": "need-owner"}, "spec": {}})
        rt.manager.drain()
        # the storm: every kube write 503s, device eval raises
        FAULTS.inject("kube.write", mode="error", param="503")
        FAULTS.inject("eval.device", mode="raise")
        answers: list = []

        def post(i):
            labels = {"owner": "me"} if i % 2 else None
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", rt.webhook.port, timeout=10)
                t0 = time.monotonic()
                conn.request(
                    "POST", "/v1/admit",
                    json.dumps(_review(f"c{i}", labels, timeout_s=5)),
                    {"Content-Type": "application/json"})
                out = json.loads(conn.getresponse().read())
                answers.append((i, time.monotonic() - t0, out))
            except Exception as e:  # pragma: no cover - failure mode
                answers.append((i, -1.0, e))

        threads = [threading.Thread(target=post, args=(i,))
                   for i in range(30)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(answers) == 30, "unanswered admissions"
        for i, elapsed, out in answers:
            assert isinstance(out, dict) and "response" in out, (i, out)
            assert 0 <= elapsed < 5.0, (i, elapsed)
            # policy verdicts held through the storm (interpreter path)
            assert out["response"]["allowed"] is bool(i % 2), (i, out)
        # two audit sweeps under the storm: loop alive, writes deferred
        time.sleep(0.5)
        assert rt.audit.healthy()
        rendered = REGISTRY.render()
        assert "gatekeeper_tpu_circuit_breaker_state" in rendered
        # a WEBHOOK pod must stay ready through a write brownout:
        # serving is read-only, and pulling every replica's endpoint at
        # once would turn the partial degradation into a full admission
        # outage (audit-only pods DO report the breaker — see
        # test_audit_only_pod_readiness_reports_open_breaker)
        conn = http.client.HTTPConnection("127.0.0.1", rt.health.port,
                                          timeout=5)
        conn.request("GET", "/readyz")
        assert conn.getresponse().status == 200
        # storm ends: the breaker closes on the next successful write
        FAULTS.reset()
        time.sleep(0.6)
        deadline = time.time() + 10
        while rt.write_breaker.is_open and time.time() < deadline:
            time.sleep(0.1)
        assert not rt.write_breaker.is_open
    finally:
        FAULTS.reset()
        rt.stop()


def test_audit_only_pod_readiness_reports_open_breaker():
    """An audit-only pod (no admission serving to protect) surfaces the
    open kube-write breaker through /readyz."""
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--operation", "audit", "--prometheus-port", "0",
        "--disable-cert-rotation", "--audit-interval", "0.1",
        "--health-addr", "127.0.0.1:0",
        "--kube-breaker-threshold", "2", "--kube-breaker-reset", "30",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        rt.kube.create({
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8sneedowner"},
            "spec": {
                "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
                "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
        })
        rt.manager.drain()
        rt.kube.create({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sNeedOwner",
            "metadata": {"name": "need-owner"}, "spec": {}})
        rt.manager.drain()
        FAULTS.inject("kube.write", mode="error", param="503")
        deadline = time.time() + 15
        while not rt.write_breaker.is_open and time.time() < deadline:
            time.sleep(0.05)  # audit sweeps' status writes open it
        assert rt.write_breaker.is_open
        conn = http.client.HTTPConnection("127.0.0.1", rt.health.port,
                                          timeout=5)
        conn.request("GET", "/readyz")
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 503 and b"kube-writes" in body
    finally:
        FAULTS.reset()
        rt.stop()


# ----------------------------------------------------- fault plumbing


def test_fault_spec_parsing_and_counters():
    FAULTS.configure("kube.write:error:503@1.0#2,webhook.flush:sleep:0.01")
    assert FAULTS.armed() == ["kube.write", "webhook.flush"]
    with pytest.raises(FaultError) as ei:
        FAULTS.fire("kube.write")
    assert ei.value.code() == 503
    FAULTS.fire("unarmed.point")  # no-op
    with pytest.raises(FaultError):
        FAULTS.fire("kube.write")
    FAULTS.fire("kube.write")  # count exhausted: disarmed
    assert FAULTS.fired("kube.write") == 2
    t0 = time.monotonic()
    FAULTS.fire("webhook.flush")
    assert time.monotonic() - t0 >= 0.01


# ------------------------------------------- serving-plane chaos (PR 5)


def test_backplane_engine_kill_mid_burst_zero_unanswered():
    """The serving-plane acceptance storm: the engine is killed (abort,
    the in-process analog of kill -9) in the middle of an admission
    burst with the `backplane.engine` fault point armed for the
    aftermath — every HTTP caller still gets an AdmissionReview
    response per the fail-open stance. Zero unanswered admissions."""
    from gatekeeper_tpu.control.backplane import (
        BackplaneClient,
        BackplaneEngine,
        FrontendServer,
        default_socket_path,
    )

    _, client = _policy_client()

    def slow_eval(reviews):
        time.sleep(0.05)  # keep a healthy backlog in flight at the kill
        resp = client.driver.review_batch(TARGET, reviews)
        return resp

    batcher = MicroBatcher(client, max_wait=0.002, max_batch=8,
                           evaluate=slow_eval)
    validation = ValidationHandler(client, kube=None, batcher=batcher,
                                   decision_cache_size=0)
    sock = default_socket_path() + ".kill"
    engine = BackplaneEngine(sock, validation=validation)
    engine.start()
    bc = BackplaneClient(sock, worker_id="chaos")
    frontend = FrontendServer(bc, port=0, addr="127.0.0.1")
    frontend.start()
    n = 60
    answered: dict[int, dict] = {}
    errors: list = []
    lock = threading.Lock()

    def fire(i):
        try:
            conn = http.client.HTTPConnection("127.0.0.1", frontend.port,
                                              timeout=15)
            conn.request("POST", "/v1/admit?timeout=3s",
                         json.dumps(_review(f"k{i}", timeout_s=3)),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            conn.close()
            with lock:
                answered[i] = (resp.status, body["response"])
        except Exception as e:  # noqa: BLE001
            with lock:
                errors.append((i, repr(e)))

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(n)]
    try:
        for t in threads:
            t.start()
        # let part of the burst land real verdicts, then kill the
        # engine under the rest; arm the fault point so even the
        # reconnect path stays down for the stragglers
        deadline = time.time() + 10
        while len(answered) < n // 6 and time.time() < deadline:
            time.sleep(0.01)
        FAULTS.inject("backplane.engine", mode="error")
        engine.abort()
        for t in threads:
            t.join(20)
            assert not t.is_alive(), "caller wedged past its deadline"
    finally:
        frontend.stop(drain_timeout=2.0)
        batcher.stop()
        FAULTS.reset()
    assert not errors, errors[:3]
    assert len(answered) == n, "unanswered admissions after engine kill"
    stance = 0
    for i, (status, resp) in answered.items():
        assert status == 200
        assert "allowed" in resp
        code = (resp.get("status") or {}).get("code")
        if code in (503, 504):
            stance += 1
            assert resp["allowed"] is True  # fail-open stance
    assert stance > 0, "the kill landed after the whole burst finished"
