"""Regressions for compiler/driver defects found in review.

Each case pins an under-fire or crash scenario: duplicate-sensitive set
counts, count of set comprehensions, bare scalar guards, large-integer
equality, autoreject union semantics, and template-update cache staleness.
"""

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget


def mk(template_rego, kind="K8sTest", name=None):
    return {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": name or kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": template_rego}],
        },
    }


def constraint(kind, name, params=None):
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind, "metadata": {"name": name},
        "spec": {"parameters": params or {}},
    }


def both_clients(template, constraints, objects):
    clients = []
    drivers = []
    for cls in (RegoDriver, TpuDriver):
        d = cls()
        c = Backend(d).new_client([K8sValidationTarget()])
        c.add_template(template)
        for con in constraints:
            c.add_constraint(con)
        for o in objects:
            c.add_data(o)
        drivers.append(d)
        clients.append(c)
    return drivers, clients


def names(results):
    return sorted(r.resource["metadata"]["name"] for r in results)


def test_dup_sensitive_count_never_underfires():
    """count(required - provided) == 1 with duplicated required values must
    not be compiled with a duplicate-counting sum."""
    rego = """
package k8stest
violation[{"msg": "exactly one missing"}] {
  required := {l | l := input.parameters.labels[_]}
  provided := {l | input.review.object.metadata.labels[l]}
  missing := required - provided
  count(missing) == 1
}
"""
    objs = [{"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "n0"}}]
    (rd, td), (rc, tc) = both_clients(
        mk(rego), [constraint("K8sTest", "c", {"labels": ["a", "a"]})], objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == ["n0"]


def test_count_of_set_comprehension():
    rego = """
package k8stest
violation[{"msg": "no labels"}] {
  provided := {l | input.review.object.metadata.labels[l]}
  count(provided) == 0
}
"""
    objs = [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": "bare"}},
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": "labeled", "labels": {"x": "y"}}},
    ]
    (rd, td), (rc, tc) = both_clients(mk(rego), [constraint("K8sTest", "c")],
                                      objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == ["bare"]


def test_bare_scalar_guard():
    rego = """
package k8stest
violation[{"msg": "hit"}] {
  true
  input.review.object.metadata.name == "target"
}
"""
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "target", "namespace": "d"}},
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "other", "namespace": "d"}}]
    (rd, td), (rc, tc) = both_clients(mk(rego), [constraint("K8sTest", "c")],
                                      objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == \
        ["target"]


def test_large_integer_equality_exact():
    rego = """
package k8stest
violation[{"msg": "uid mismatch"}] {
  input.review.object.spec.uid != input.parameters.uid
}
"""
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "big", "namespace": "d"},
             "spec": {"uid": 16777216}},
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "same", "namespace": "d"},
             "spec": {"uid": 16777217}}]
    (rd, td), (rc, tc) = both_clients(
        mk(rego), [constraint("K8sTest", "c", {"uid": 16777217})], objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == \
        ["big"]


def test_autoreject_unions_with_matching():
    """For a Namespace-kind review, autoreject AND template violations both
    surface (reference hook rules 1+2 union) on every driver path."""
    rego = """
package k8stest
violation[{"msg": "always"}] { input.review.object.metadata.name }
"""
    con = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sTest", "metadata": {"name": "c"},
        "spec": {"match": {"namespaceSelector": {
            "matchLabels": {"team": "a"}}}},
    }
    ns = {"apiVersion": "v1", "kind": "Namespace",
          "metadata": {"name": "n", "labels": {"team": "a"}}}
    for cls in (RegoDriver, TpuDriver):
        d = cls()
        c = Backend(d).new_client([K8sValidationTarget()])
        c.add_template(mk(rego))
        c.add_constraint(con)
        msgs = sorted(r.msg for r in c.review(AugmentedUnstructured(ns)).results())
        assert msgs == ["Namespace is not cached in OPA.", "always"], \
            f"{cls.__name__}: {msgs}"


def test_template_update_invalidates_param_cache():
    """Updating a template's rego with unchanged constraints must re-encode
    parameters for the new program."""
    rego_a = """
package k8stest
violation[{"msg": "a"}] {
  c := input.review.object.spec.containers[_]
  startswith(c.image, input.parameters.prefix)
}
"""
    rego_b = """
package k8stest
violation[{"msg": "b"}] {
  input.review.object.metadata.name == input.parameters.name
}
"""
    d = TpuDriver()
    c = Backend(d).new_client([K8sValidationTarget()])
    c.add_template(mk(rego_a))
    c.add_constraint(constraint("K8sTest", "c",
                                {"prefix": "evil/", "name": "p2"}))
    c.add_data({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "p1", "namespace": "d"},
                "spec": {"containers": [{"image": "evil/x"}]}})
    c.add_data({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "p2", "namespace": "d"},
                "spec": {"containers": [{"image": "good/x"}]}})
    assert names(c.audit().results()) == ["p1"]
    c.add_template(mk(rego_b))
    assert names(c.audit().results()) == ["p2"]


def test_demotion_is_logged_and_counted(caplog):
    """A device-lowering failure must never be silent (VERDICT r2 weak #5:
    a bare-except demotion hid a broken lowering for a whole round). The
    fallback still answers correctly, but emits a warning log and bumps
    gatekeeper_tpu_device_demotions_total."""
    import logging

    from gatekeeper_tpu.control.metrics import REGISTRY
    from gatekeeper_tpu.ir.prog import DerivedSpec

    d = TpuDriver()
    c = Backend(d).new_client([K8sValidationTarget()])
    c.add_template(mk("""
package k8stest
violation[{"msg": "m"}] {
  input.review.object.metadata.name == input.parameters.name
}
"""))
    # corrupt the compiled program with a derived kind the driver cannot
    # lower (stands in for any future compile.py/driver.py drift)
    from dataclasses import replace
    prog = d._programs["K8sTest"]
    d._programs["K8sTest"] = replace(
        prog, derived=prog.derived + (DerivedSpec(99, "no-such-kind", "x"),))

    def metric() -> float:
        m = REGISTRY._metrics.get("gatekeeper_tpu_device_demotions_total")
        return sum(m.values.values()) if m else 0.0

    before = metric()
    with caplog.at_level(logging.WARNING, "gatekeeper_tpu.ir.driver"):
        assert d.compiled_for("K8sTest") is None
    assert metric() == before + 1
    assert any("demoted" in r.message and "K8sTest" in r.message
               for r in caplog.records)

    # the interpreter fallback still audits correctly
    c.add_constraint(constraint("K8sTest", "c", {"name": "p1"}))
    c.add_data({"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "p1", "namespace": "d"}})
    assert names(c.audit().results()) == ["p1"]


def test_trim_empty_cutset_is_identity():
    """Rego trim(s, "") strips nothing; the pattern-transform table must
    not fall back to Python's whitespace strip (ADVICE r2)."""
    rego = """
package k8stest
violation[{"msg": "prefix"}] {
  startswith(input.review.object.metadata.name, trim(input.parameters.p, ""))
}
"""
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": " padded", "namespace": "d"}},
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "padded", "namespace": "d"}}]
    (rd, td), (rc, tc) = both_clients(
        mk(rego), [constraint("K8sTest", "c", {"p": " pad"})], objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == \
        [" padded"]


def test_trim_cutset_containing_at_sign():
    """Transform args are escaped into the op@tag:arg encoding, so a
    cutset containing "@" must not corrupt tag parsing (ADVICE r2)."""
    rego = """
package k8stest
violation[{"msg": "prefix"}] {
  startswith(input.review.object.metadata.name, trim(input.parameters.p, "@"))
}
"""
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "core-x", "namespace": "d"}},
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": "other", "namespace": "d"}}]
    (rd, td), (rc, tc) = both_clients(
        mk(rego), [constraint("K8sTest", "c", {"p": "@core@"})], objs)
    assert names(rc.audit().results()) == names(tc.audit().results()) == \
        ["core-x"]


def test_fires_pairs_matches_dense_with_padding_and_regather():
    """The sparse device pair-extraction path must agree exactly with the
    dense verdict tensor — including extraction bucket padding (empty
    padding objects legitimately fire absence clauses and must be masked
    on device) and a deliberately undersized gather capacity (forces the
    count-miss re-gather loop)."""
    import numpy as np
    from gatekeeper_tpu.parallel.workload import build_eval_setup

    n, c = 3000, 40
    driver, ct, feats, params, table, derived, reviews, cons = \
        build_eval_setup(n, c, n_bucket=4096, violate_frac=0.3)
    dense = ct.fires_chunked(feats, params, table, derived, chunk=1024)
    want = np.nonzero(dense[:n])
    ct._rows_cap = 16  # force at least one capacity re-gather
    rows, cols = ct.fires_pairs(feats, params, table, derived, chunk=1024,
                                n_true=n)
    assert rows.shape == want[0].shape
    assert (rows == want[0]).all() and (cols == want[1]).all()
    assert ct._rows_cap >= len(np.unique(rows))
    # steady state: second call reuses the remembered capacity
    rows2, cols2 = ct.fires_pairs(feats, params, table, derived, chunk=1024,
                                  n_true=n)
    assert (rows2 == rows).all() and (cols2 == cols).all()


def test_audit_results_identical_across_drivers_after_pairs_path():
    """End-to-end: the TpuDriver audit (sparse pairs + codegen
    materialization) returns byte-identical results to the interpreter
    driver on a mixed violating/clean workload."""
    from gatekeeper_tpu.parallel.workload import (
        REQUIRED_LABELS_TEMPLATE, synth_constraints, synth_objects)

    objs = synth_objects(60, violate_frac=0.4, seed=3)
    constraints = synth_constraints(10, seed=4)
    (rd, td), (rc, tc) = both_clients(REQUIRED_LABELS_TEMPLATE, constraints,
                                      objs)
    a = [(r.resource["metadata"]["name"],
          r.constraint["metadata"]["name"], r.msg)
         for r in rc.audit().results()]
    b = [(r.resource["metadata"]["name"],
          r.constraint["metadata"]["name"], r.msg)
         for r in tc.audit().results()]
    assert sorted(a) == sorted(b) and len(a) > 0


def test_parameterless_template_fires_for_every_constraint():
    """A parameterless program's device verdicts are [N, 1]
    (constraint-independent); the sparse pairs path must expand firing
    rows to ALL constraints like the dense [N,1] & mask[N,C] broadcast
    did (r3 code-review finding: only cons[0] was materialized)."""
    rego = """
package k8stest
violation[{"msg": "no owner"}] {
  not input.review.object.metadata.labels.owner
}
"""
    objs = [{"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"p{i}", "namespace": "d"}}
            for i in range(40)]  # > MIN_DEVICE_BATCH and forces device path
    constraints = [constraint("K8sTest", "c1"), constraint("K8sTest", "c2")]
    (rd, td), (rc, tc) = both_clients(mk(rego), constraints, objs)
    a = sorted((r.resource["metadata"]["name"],
                r.constraint["metadata"]["name"])
               for r in rc.audit().results())
    b = sorted((r.resource["metadata"]["name"],
                r.constraint["metadata"]["name"])
               for r in tc.audit().results())
    assert a == b
    assert len(b) == 80  # every (object, constraint) pair


def test_vocab_stabilizes_across_audits():
    """Derived-column materialization must not intern new vocab entries
    forever (r3 finding: each audit re-derived the previous audit's
    outputs, growing the vocab 32 strings/audit, reshaping the match
    table, and forcing a full XLA recompile EVERY audit)."""
    from gatekeeper_tpu import policies

    d = TpuDriver()
    c = Backend(d).new_client([K8sValidationTarget()])
    c.add_template(policies.load("general/containerlimits"))
    c.add_constraint(constraint("K8sContainerLimits", "cl",
                                {"cpu": "2", "memory": "1Gi"}))
    for i in range(30):
        c.add_data({"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": f"p{i}", "namespace": "d"},
                    "spec": {"containers": [{
                        "name": "m", "image": "img",
                        "resources": {"limits": {
                            "cpu": f"{100 + i}m",
                            "memory": f"{i + 1}Gi"}}}]}})
    sizes = []
    for _ in range(4):
        c.audit()
        sizes.append(len(d.strtab))
    # growth must stop (bounded chain depth), not continue per audit
    assert sizes[-1] == sizes[-2], sizes


def test_computed_key_bracket_compiles_and_matches():
    """m[<computed key>] (labels[spec.key]) desugars to iteration + key
    equality on the device path and must agree with the interpreter."""
    rego = """
package k8stest
violation[{"msg": "bad value"}] {
  spec := input.parameters.entries[_]
  val := input.review.object.metadata.labels[spec.key]
  val != spec.want
}
"""
    objs = [
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": "ok", "labels": {"env": "prod", "x": "y"}}},
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": "wrong", "labels": {"env": "dev"}}},
        {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": "absent", "labels": {"x": "y"}}},
    ]
    cons = [constraint("K8sTest", "c",
                       {"entries": [{"key": "env", "want": "prod"}]})]
    (rd, td), (rc, tc) = both_clients(mk(rego), cons, objs)
    assert td.compiled_for("K8sTest") is not None, \
        "computed-key bracket must device-compile"
    assert names(rc.audit().results()) == names(tc.audit().results()) == \
        ["wrong"]


def test_async_warm_serves_host_then_hot_swaps():
    """Async device compile: the first audit at a new sweep shape must
    return CORRECT results immediately from the host path while the
    device program warms in the background; once warm, the same audit
    takes the device path and agrees exactly."""
    import time

    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    def load(client, n=600):
        client.add_template(policies.load("general/requiredlabels"))
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels", "metadata": {"name": "owner"},
            "spec": {"parameters": {"labels": [{"key": "owner"}]}}})
        for i in range(n):
            o = {"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": f"ns{i:04d}"}}
            if i % 2 == 0:
                o["metadata"]["labels"] = {"owner": "me"}
            client.add_data(o)

    drv = TpuDriver()
    drv._mesh = None
    drv.async_warm = True
    drv._dev_batch_lat_s = 1e-4  # cost model would pick the device
    client = Backend(drv).new_client([K8sValidationTarget()])
    load(client)

    ref = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    load(ref)
    want = sorted((r.msg, r.resource["metadata"]["name"])
                  for r in ref.audit().results())

    # first audit: host path (warm kicked off in the background)
    got1 = sorted((r.msg, r.resource["metadata"]["name"])
                  for r in client.audit().results())
    assert got1 == want and len(want) == 300
    st = drv.warm_status()
    assert st["warm"] + st["compiling"] >= 1, "no warm-up was started"

    # wait for the hot-swap, then the device path must serve and agree
    deadline = time.time() + 120
    while time.time() < deadline:
        if drv.warm_status()["warm"] >= 1:
            break
        time.sleep(0.05)
    assert drv.warm_status()["warm"] >= 1, "device program never warmed"
    # the results delta cache would (correctly) answer this unchanged
    # re-audit without dispatching; drop it so the test exercises the
    # post-warm DEVICE sweep it exists to pin
    drv._audit_results_cache.clear()
    got2 = sorted((r.msg, r.resource["metadata"]["name"])
                  for r in client.audit().results())
    assert got2 == want
    # non-vacuous: the device consume path updates the latency EMA,
    # proving the post-warm audit actually ran on the device
    assert drv._dev_batch_lat_s != 1e-4, "post-warm audit stayed on host"
