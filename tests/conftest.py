"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
(parallel/) is exercised without TPU hardware, per the project's testing
strategy (the driver separately dry-runs the multichip path).

Must run before any jax import — pytest imports conftest first.
"""

import os

# force CPU: tests must be hermetic (the TPU tunnel, when present, would
# otherwise win the platform election and every test pays remote compiles)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# persistent XLA compilation cache: identical policy programs re-jitted by
# every test hit the disk cache instead of recompiling
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
# deterministic dispatch in tests: the first device sweep compiles
# inline instead of warming in the background (dedicated async tests
# flip driver.async_warm back on)
os.environ.setdefault("GATEKEEPER_TPU_ASYNC_COMPILE", "0")

# lockset tracer (GATEKEEPER_TPU_LOCKTRACE=1): must install BEFORE any
# serving module constructs a lock, so the chaos/concurrency suites run
# with every Lock/RLock traced for order inversions and cycles — the
# runtime companion to tools/gklint's static no-block checker. A no-op
# unless the env var arms it (the CI locktrace job does).
from gatekeeper_tpu.utils import locktrace  # noqa: E402

locktrace.install()

# a sitecustomize hook (PYTHONPATH site injection) may have imported jax at
# interpreter startup and captured JAX_PLATFORMS from the outer environment
# (e.g. a remote-TPU plugin); the env assignments above are then too late.
# Backends initialize lazily, so updating the config here still wins as
# long as no test ran a computation yet.
import jax

jax.config.update("jax_platforms", "cpu")
if hasattr(jax.config, "jax_num_cpu_devices"):
    # newer jax: the host-device count is a config knob (the XLA_FLAGS
    # path above covers older versions, where this attribute is absent)
    if not jax.config.jax_num_cpu_devices or \
            jax.config.jax_num_cpu_devices < 8:
        jax.config.update("jax_num_cpu_devices", 8)

import pathlib

import pytest

REFERENCE = pathlib.Path("/root/reference")


def reference_available() -> bool:
    return (REFERENCE / "library").is_dir()


requires_reference = pytest.mark.skipif(
    not reference_available(),
    reason="reference corpus not mounted at /root/reference",
)


@pytest.fixture(autouse=True)
def _dump_stacks_on_wedge(request):
    """All-thread stack dumps for wedged tests.

    The chaos/concurrency/serving suites each run under a hard
    per-test SIGALRM (their module-level PER_TEST_TIMEOUT_S): an
    injected hang fails that test fast — but the alarm handler only
    shows the MAIN thread's stack, and the wedged thread (a stuck
    flusher, a deadlocked pair) is exactly the one not shown. This
    arms faulthandler.dump_traceback_later one second BEFORE the
    alarm, so a timeout failure ships every thread's stack to stderr
    first — the runtime companion to gklint's deadlock checkers."""
    import faulthandler

    timeout = getattr(request.module, "PER_TEST_TIMEOUT_S", None)
    if not timeout or timeout <= 2:
        yield
        return
    faulthandler.dump_traceback_later(timeout - 1, exit=False)
    try:
        yield
    finally:
        faulthandler.cancel_dump_traceback_later()
