"""Interpreter unit tests + conformance against the reference policy corpus.

The conformance part runs the reference library's own src_test.rego suites
(4,027 lines across 23 templates, reference library/**/src_test.rego)
through our interpreter — the tier-1 test strategy of SURVEY.md §4 without
needing the opa binary.
"""

import pathlib

import pytest

from gatekeeper_tpu.rego.interp import UNDEF, Interpreter
from gatekeeper_tpu.rego.parser import parse_module
from gatekeeper_tpu.utils.values import freeze, thaw

from .conftest import REFERENCE, requires_reference


def run(src: str, rule: str, input_value=None, data=None):
    m = parse_module(src, "<test>")
    interp = Interpreter({"m": m})
    if data:
        for path, v in data.items():
            interp.put_data(tuple(path.split("/")), v)
    return interp.eval_rule(m.package, rule, input_value)


def test_complete_rule_and_arith():
    assert run("package t\nx = 1 + 2 * 3 { true }", "x") == 7


def test_undefined_vs_false():
    src = """
package t
a { false }
b { not missing_thing_ref }
missing_thing_ref { input.nope }
c { input.zero == 0 }
"""
    assert run(src, "a") is UNDEF
    assert run(src, "b") is True
    assert run(src, "c", {"zero": 0}) is True


def test_iteration_and_partial_set():
    src = """
package t
hosts[h] { h := input.rules[_].host }
"""
    v = run(src, "hosts", {"rules": [{"host": "a"}, {"host": "b"}, {}]})
    assert thaw(v) == ["a", "b"]


def test_set_algebra_and_comprehensions():
    src = """
package t
missing = m {
  required := {l | l := input.required[_]}
  provided := {l | input.labels[l]}
  m := required - provided
}
"""
    v = run(src, "missing", {"required": ["a", "b"], "labels": {"b": "x"}})
    assert thaw(v) == ["a"]


def test_function_clauses_and_builtin_error_undefined():
    src = """
package t
canon(x) = out { is_number(x); out := x * 1000 }
canon(x) = out { not is_number(x); endswith(x, "m"); out := to_number(replace(x, "m", "")) }
bad { not canon(input.v) }
good = canon(input.v) { true }
"""
    assert run(src, "good", {"v": "100m"}) == 100
    assert run(src, "good", {"v": 2}) == 2000
    assert run(src, "bad", {"v": "xyz"}) is True


def test_unification_destructure():
    src = """
package t
gv = [g, v] { [g, v] := split(input.api, "/") }
"""
    assert thaw(run(src, "gv", {"api": "apps/v1"})) == ["apps", "v1"]


def test_with_input_override():
    src = """
package t
deny[m] { input.bad; m := "bad" }
check = c { c := count(deny) with input as {"bad": true} }
"""
    assert run(src, "check", {"bad": False}) == 1


def test_object_key_iteration_binds():
    src = """
package t
keys[k] { input.labels[k] }
vals[v] { v := input.labels[_] }
"""
    assert thaw(run(src, "keys", {"labels": {"a": 1, "b": 2}})) == ["a", "b"]
    assert thaw(run(src, "vals", {"labels": {"a": 1, "b": 2}})) == [1, 2]


def test_data_iteration_with_unbound_vars():
    src = """
package t
pairs[[ns, name]] { data.inv.namespace[ns]["v1"]["Pod"][name] }
"""
    v = run(
        src,
        "pairs",
        data={
            "inv/namespace/default/v1/Pod/p1": {"x": 1},
            "inv/namespace/kube/v1/Pod/p2": {"x": 2},
        },
    )
    assert thaw(v) == [["default", "p1"], ["kube", "p2"]]


def test_default_rule():
    src = """
package t
default allow = false
allow { input.ok }
"""
    assert run(src, "allow", {}) is False
    assert run(src, "allow", {"ok": True}) is True


def test_sprintf_formatting():
    src = """
package t
m = msg { msg := sprintf("missing: %v count %d", [{"a", "b"}, 3]) }
"""
    assert run(src, "m") == 'missing: {"a", "b"} count 3'


# ---------------------------------------------------------------- conformance


def _library_dirs():
    if not (REFERENCE / "library").is_dir():
        return []
    out = []
    for sub in ("general", "pod-security-policy"):
        base = REFERENCE / "library" / sub
        if base.is_dir():
            for d in sorted(base.iterdir()):
                if (d / "src.rego").is_file() and (d / "src_test.rego").is_file():
                    out.append(d)
    return out


# Suites that are red against their own src at the pinned reference commit
# (none of the library rego suites are wired into the reference's CI — only
# pod-security-policy/test.sh exists and no Makefile/workflow target runs it).
# Verified by hand-deriving OPA topdown semantics:
#  * httpsonly: test helpers build reviews without review.kind, but the
#    violation rule requires input.review.kind.kind == "Ingress", so the
#    expected violations can never fire (src_test.rego vs src.rego mismatch).
#  * selinux: *_in_list tests pass allowedSELinuxOptions as a LIST while
#    src.rego matches object fields (.level/.role/...) — list support landed
#    upstream after this pin.
KNOWN_RED_AT_PIN = {
    "httpsonly": {
        "test_boolean_annotation",
        "test_true_annotation",
        "test_missing_annotation",
        "test_empty_tls",
        "test_missing_tls",
        "test_missing_all",
    },
    "selinux": {
        "test_input_seLinux_options_allowed_in_list",
        "test_input_seLinux_options_allowed_in_list_subset",
        "test_input_seLinux_options_many_allowed_in_list",
        "test_input_seLinux_options_no_security_context",
    },
}


@requires_reference
@pytest.mark.parametrize("libdir", _library_dirs(), ids=lambda d: d.name)
def test_reference_library_suite(libdir: pathlib.Path):
    src = (libdir / "src.rego").read_text()
    test_src = (libdir / "src_test.rego").read_text()
    m1 = parse_module(src, str(libdir / "src.rego"))
    m2 = parse_module(test_src, str(libdir / "src_test.rego"))
    interp = Interpreter({"src": m1, "test": m2})
    results = interp.run_tests(m2.package)
    assert results, f"no test_ rules found in {libdir}"
    failed = set(n for n, ok in results.items() if not ok)
    expected = KNOWN_RED_AT_PIN.get(libdir.name, set())
    assert failed == expected, (
        f"{libdir.name}: failures {sorted(failed)} != expected-at-pin "
        f"{sorted(expected)} (total {len(results)})"
    )


@requires_reference
def test_reference_target_matcher_suites():
    regolib = REFERENCE / "pkg" / "target" / "regolib"
    src = (regolib / "src.rego").read_text()
    # the matcher library templates {{.ConstraintsRoot}}/{{.DataRoot}} — mount
    # them the way the framework does (constraint framework client.go:79-86)
    src = src.replace('{{.ConstraintsRoot}}', "constraints").replace(
        '{{.DataRoot}}', "external"
    )
    mods = {"target": parse_module(src, "target/src.rego")}
    for tf in sorted(regolib.glob("*_test.rego")):
        tsrc = tf.read_text().replace('{{.ConstraintsRoot}}', "constraints").replace(
            '{{.DataRoot}}', "external"
        )
        mods[tf.name] = parse_module(tsrc, tf.name)
    interp = Interpreter(mods)
    all_results = {}
    for name, m in mods.items():
        if name == "target":
            continue
        all_results.update(
            {f"{name}:{k}": v for k, v in interp.run_tests(m.package).items()}
        )
    assert all_results
    failed = sorted(n for n, ok in all_results.items() if not ok)
    # test_with_undefined_ns is red at pin: with input.review as {} the three
    # `not` guards in autoreject_review all succeed (undefined namespace), so
    # a rejection IS produced while the test expects none. Like the library
    # suites, the regolib tests are not run by the reference's CI.
    failed = [n for n in failed if not n.endswith(":test_with_undefined_ns")]
    assert not failed, f"{len(failed)}/{len(all_results)} matcher tests failed: {failed}"


def test_breadth_builtins():
    """r3 breadth batch: json/base64/urlquery-free glob/range/sets/trim
    builtins match OPA-documented semantics."""
    from gatekeeper_tpu.rego.interp import Interpreter, UNDEF
    from gatekeeper_tpu.rego.parser import parse_module
    from gatekeeper_tpu.utils.values import thaw

    cases = {
        "a": ('json.marshal({"b": [1, "x"], "a": true})',
              '{"a":true,"b":[1,"x"]}'),
        "b": ('json.unmarshal("[1, {\\"k\\": \\"v\\"}]")',
              [1, {"k": "v"}]),
        "c": ('base64.encode("hi")', "aGk="),
        "d": ('base64.decode("aGk=")', "hi"),
        "e": ('glob.match("*.example.com", [], "api.example.com")', True),
        "f": ('glob.match("*.example.com", [], "a.b.example.com")', False),
        "g": ('glob.match("**.example.com", [], "a.b.example.com")', True),
        "h": ('glob.match("{api,web}.corp", [], "web.corp")', True),
        "i": ("numbers.range(1, 4)", [1, 2, 3, 4]),
        "j": ("numbers.range(3, 1)", [3, 2, 1]),
        "k": ("union({{1, 2}, {2, 3}})", [1, 2, 3]),  # thaw: set -> list
        "l": ("intersection({{1, 2}, {2, 3}})", [2]),
        "m": ('type_name([1])', "array"),
        "n": ('trim_left("xxabcxx", "x")', "abcxx"),
        "o": ('trim_right("xxabcxx", "x")', "xxabc"),
        "p": ('trim_prefix("k8s.io/foo", "k8s.io/")', "foo"),
        "q": ('trim_suffix("name.yaml", ".yaml")', "name"),
        "r": ('trim_suffix("name.yaml", ".json")', "name.yaml"),
    }
    rules = "\n".join(f"{name} = out {{ out := {expr} }}"
                      for name, (expr, _) in cases.items())
    mod = parse_module("package t\n" + rules)
    interp = Interpreter({"m": mod})
    for name, (expr, want) in cases.items():
        got = interp.eval_rule(mod.package, name, {})
        assert got is not UNDEF, (name, expr)
        got = thaw(got)
        if isinstance(got, (set, frozenset)):
            got = sorted(got, key=repr)
        elif isinstance(got, tuple):
            got = list(got)
        assert got == want, (name, expr, got, want)


def test_breadth_builtins_round4():
    """Round-4 builtin batch evaluated through actual rego (interpreter
    AND codegen must agree; OPA semantics pinned by literal expecteds)."""
    src = '''
package b4

out[x] {
  x := {
    "keys": object.keys({"a": 1, "b": 2}),
    "removed": object.remove({"a": 1, "b": 2}, ["a"]),
    "union": object.union({"a": {"x": 1}}, {"a": {"y": 2}}),
    "rsplit": regex.split("-", "a-b-c"),
    "rrepl": regex.replace("aaa", "a", "b"),
    "rvalid": [regex.is_valid("^a+$"), regex.is_valid("(")],
    "rev": strings.reverse("abc"),
    "cnt": strings.count("banana", "na"),
    "idxn": indexof_n("banana", "na"),
    "hex": hex.decode(hex.encode("hi")),
    "url": urlquery.decode(urlquery.encode("a b&c")),
    "jvalid": [json.is_valid("{}"), json.is_valid("{")],
    "yaml": yaml.unmarshal("a: 1"),
    "sha": crypto.sha256("abc"),
    "hmac_eq": crypto.hmac.equal(crypto.hmac.sha256("m", "k"),
                                 crypto.hmac.sha256("m", "k")),
    "ceilfloor": [ceil(1.2), floor(1.8)],
    "steps": numbers.range_step(1, 7, 2),
    "arev": array.reverse([1, 2, 3]),
    "t": time.date(time.parse_rfc3339_ns("2020-01-01T00:00:00Z")),
    "wd": time.weekday(time.parse_rfc3339_ns("2020-01-01T00:00:00Z")),
    "units": [units.parse("10Ki"), units.parse_bytes("1KiB")],
    "cidr": [net.cidr_contains("10.0.0.0/8", "10.1.2.3"),
             net.cidr_intersects("10.0.0.0/8", "11.0.0.0/8")],
    "semver": [semver.compare("1.2.3", "1.10.0"),
               semver.compare("1.0.0-alpha", "1.0.0")],
    "bits": [bits.or(5, 3), bits.lsh(1, 4), bits.negate(0)],
  }
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    out = interp.eval_rule(("b4",), "out", {})
    assert out is not UNDEF
    # the codegen evaluator must agree with the interpreter exactly
    from gatekeeper_tpu.rego.codegen import compile_module
    from gatekeeper_tpu.utils.values import freeze
    fn = compile_module(module, entry="out")
    assert fn.__input_call__(freeze({}), freeze({})) == out
    got = thaw(list(out)[0])
    assert got["keys"] == ["a", "b"]
    assert got["removed"] == {"b": 2}
    assert got["union"] == {"a": {"x": 1, "y": 2}}
    assert got["rsplit"] == ["a", "b", "c"]
    assert got["rrepl"] == "bbb"
    assert got["rvalid"] == [True, False]
    assert got["rev"] == "cba"
    assert got["cnt"] == 2
    assert got["idxn"] == [2, 4]
    assert got["hex"] == "hi"
    assert got["url"] == "a b&c"
    assert got["jvalid"] == [True, False]
    assert got["yaml"] == {"a": 1}
    assert got["sha"].startswith("ba7816bf")
    assert got["hmac_eq"] is True
    assert got["ceilfloor"] == [2, 1]
    assert got["steps"] == [1, 3, 5, 7]
    assert got["arev"] == [3, 2, 1]
    assert got["t"] == [2020, 1, 1]
    assert got["wd"] == "Wednesday"
    assert got["units"] == [10240, 1024]
    assert got["cidr"] == [True, False]
    assert got["semver"] == [-1, -1]
    assert got["bits"] == [7, 16, -1]


def test_walk_builtin():
    """walk(x) enumerates all [path, value] pairs (OPA topdown/walk.go);
    templates using it stay on the interpreter path (codegen/device
    treat it as unsupported) but must evaluate correctly end-to-end."""
    src = '''
package w

secrets[p] {
  [p, v] := walk(input.review.object)
  is_string(v)
  contains(v, "SECRET")
}

depth2[v] {
  [path, v] := walk(input.review.object)
  count(path) == 2
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    inp = {"review": {"object": {
        "a": {"b": "SECRET1", "c": "ok"},
        "d": ["x", {"e": "SECRET2"}],
    }}}
    out = thaw(interp.eval_rule(("w",), "secrets", inp))
    assert sorted(out) == [["a", "b"], ["d", 1, "e"]]
    d2 = thaw(interp.eval_rule(("w",), "depth2", inp))
    assert sorted(d2, key=str) == sorted(["SECRET1", "ok", "x",
                                          {"e": "SECRET2"}], key=str)
    # end-to-end through both drivers (TpuDriver must fall back loudly
    # but correctly)
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import AugmentedUnstructured, \
        K8sValidationTarget
    tpl = {"apiVersion": "templates.gatekeeper.sh/v1beta1",
           "kind": "ConstraintTemplate", "metadata": {"name": "tnosecret"},
           "spec": {"crd": {"spec": {"names": {"kind": "TNoSecret"}}},
                    "targets": [{"target": "admission.k8s.gatekeeper.sh",
                                 "rego": '''
package tnosecret
violation[{"msg": msg}] {
  [path, v] := walk(input.review.object)
  is_string(v)
  contains(v, "hunter2")
  msg := sprintf("secret-looking value at %v", [path])
}
'''}]}}
    outs = []
    for drv in (RegoDriver(), TpuDriver()):
        c = Backend(drv).new_client([K8sValidationTarget()])
        c.add_template(tpl)
        c.add_constraint({"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                          "kind": "TNoSecret", "metadata": {"name": "t"},
                          "spec": {}})
        bad = {"apiVersion": "v1", "kind": "Pod",
               "metadata": {"name": "p", "namespace": "d"},
               "spec": {"containers": [{"name": "m", "env": [
                   {"name": "PW", "value": "hunter2"}]}]}}
        outs.append(sorted(
            r.msg for r in c.review(AugmentedUnstructured(bad)).results()))
    assert outs[0] == outs[1]
    assert outs[0] and "spec" in outs[0][0]


def test_breadth_builtins_batch3():
    src = '''
package b5

out[x] {
  x := {
    "filter": json.filter({"a": {"b": 1, "c": 2}}, ["a/b"]),
    "remove": json.remove({"a": {"b": 1, "c": 2}}, ["a/b"]),
    "subset": [object.subset({"a": {"b": 1}, "x": 2}, {"a": {"b": 1}}),
               object.subset({"a": 1}, {"a": 2})],
    "reach": graph.reachable({"a": ["b"], "b": ["c"], "c": [],
                              "z": ["a"]}, ["a"]),
    "nopad": base64url.encode_no_pad("hi?"),
  }
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    out = interp.eval_rule(("b5",), "out", {})
    assert out is not UNDEF
    from gatekeeper_tpu.rego.codegen import compile_module
    from gatekeeper_tpu.utils.values import freeze
    fn = compile_module(module, entry="out")
    assert fn.__input_call__(freeze({}), freeze({})) == out
    got = thaw(list(out)[0])
    assert got["filter"] == {"a": {"b": 1}}
    assert got["remove"] == {"a": {"c": 2}}
    assert got["subset"] == [True, False]
    assert sorted(got["reach"]) == ["a", "b", "c"]
    assert got["nopad"] == "aGk_"


def test_jwt_decode_verify():
    import base64 as b64
    import hashlib
    import hmac as hmac_mod
    import json as pyjson

    def seg(d):
        return b64.urlsafe_b64encode(
            pyjson.dumps(d).encode()).decode().rstrip("=")

    hdr, pl = seg({"alg": "HS256"}), seg({"sub": "me", "admin": True})
    sig = b64.urlsafe_b64encode(hmac_mod.new(
        b"topsecret", f"{hdr}.{pl}".encode(),
        hashlib.sha256).digest()).decode().rstrip("=")
    token = f"{hdr}.{pl}.{sig}"
    src = '''
package jwt

claims[p] {
  [_, p, _] := io.jwt.decode(input.review.token)
  io.jwt.verify_hs256(input.review.token, "topsecret")
}

forged[p] {
  [_, p, _] := io.jwt.decode(input.review.token)
  io.jwt.verify_hs256(input.review.token, "wrong")
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    out = thaw(interp.eval_rule(("jwt",), "claims",
                                {"review": {"token": token}}))
    assert out == [{"sub": "me", "admin": True}]
    out2 = interp.eval_rule(("jwt",), "forged", {"review": {"token": token}})
    assert out2 is UNDEF or thaw(out2) == []


def _hs_token(alg, payload, secret=b"topsecret"):
    import base64 as b64
    import hashlib
    import hmac as hmac_mod
    import json as pyjson

    digest = {"HS256": hashlib.sha256, "HS384": hashlib.sha384,
              "HS512": hashlib.sha512}[alg]

    def seg(d):
        return b64.urlsafe_b64encode(
            pyjson.dumps(d).encode()).decode().rstrip("=")

    hdr, pl = seg({"alg": alg}), seg(payload)
    sig = b64.urlsafe_b64encode(hmac_mod.new(
        secret, f"{hdr}.{pl}".encode(), digest).digest()
    ).decode().rstrip("=")
    return f"{hdr}.{pl}.{sig}"


def test_jwt_decode_verify_key_constraints_and_hs_variants():
    """OPA parity: decode_verify errors on zero or duplicate key
    constraints; HS384/HS512 (and the standalone verify_hs384/512
    builtins) verify correctly."""
    from gatekeeper_tpu.rego.builtins import BUILTINS, BuiltinError
    from gatekeeper_tpu.utils.values import thaw as _thaw

    dv = BUILTINS[("io", "jwt", "decode_verify")]
    tok = _hs_token("HS384", {"sub": "me"})
    with pytest.raises(BuiltinError, match="no key constraint"):
        dv(tok, freeze({}))
    with pytest.raises(BuiltinError, match="duplicate key constraints"):
        dv(tok, freeze({"secret": "topsecret", "cert": "x"}))
    ok, _hdr, payload = dv(tok, freeze({"secret": "topsecret"}))
    assert ok is True and _thaw(payload) == {"sub": "me"}
    bad, h, p = dv(tok, freeze({"secret": "wrong"}))
    assert (bad, _thaw(h), _thaw(p)) == (False, {}, {})
    # alg pin must reject a mismatched header
    assert dv(tok, freeze({"secret": "topsecret", "alg": "HS256"}))[0] \
        is False
    for alg in ("HS384", "HS512"):
        t = _hs_token(alg, {"a": 1})
        assert BUILTINS[("io", "jwt", f"verify_{alg.lower()}")](
            t, "topsecret") is True
        assert BUILTINS[("io", "jwt", f"verify_{alg.lower()}")](
            t, "wrong") is False
        assert dv(t, freeze({"secret": "topsecret"}))[0] is True
    # registry carries every RS/PS/ES 256/384/512 variant OPA supports
    for fam in ("rs", "ps", "es"):
        for bits in ("256", "384", "512"):
            assert ("io", "jwt", f"verify_{fam}{bits}") in BUILTINS


def test_go_layout_dotted_dates_and_fractions():
    """Go nextStdChunk parity: a dot before a digit run is only a
    fractional-second token when the run ends the digit string — dotted
    date layouts like 2006.01.02 must parse and format as literals."""
    from gatekeeper_tpu.rego.builtins import (
        _bi_time_format,
        _bi_time_parse_ns,
        _go_layout_convert,
    )

    fmt, fraction, _tz = _go_layout_convert("2006.01.02", "t", False)
    assert (fmt, fraction) == ("%Y.%m.%d", None)
    fmt, fraction, _tz = _go_layout_convert("15:04:05.000", "t", False)
    assert fmt == "%H:%M:%S" and fraction == ("0", 3)
    # dotted date round-trips (parse landed on 2021-03-04 00:00 UTC)
    ns = _bi_time_parse_ns("2006.01.02", "2021.03.04")
    assert ns == 1614816000000000000
    assert _bi_time_format((ns, "UTC", "2006.01.02")) == "2021.03.04"
    assert _bi_time_format((ns, "UTC", "02.01.2006")) == "04.03.2021"
    # fractions still work when the digit run ends the digit string
    assert _bi_time_format((ns + 123_456_789, "UTC",
                            "15:04:05.000")) == "00:00:00.123"
    assert _bi_time_format((ns + 120_000_000, "UTC",
                            "15:04:05.999")) == "00:00:00.12"


def test_breadth_builtins_round5():
    """Round-5 builtin tail (crypto.x509/io.jwt asymmetric/time parse+
    format/cidr tail/regex tail/named operators) through actual rego;
    interpreter AND codegen must agree; literal expecteds pin OPA
    semantics."""
    src = '''
package b6

out[x] {
  x := {
    "pns": time.parse_ns("2006-01-02 15:04:05", "2020-05-01 10:30:00"),
    "dur": time.parse_duration_ns("1h30m"),
    "fmt": time.format([1588328999000000000, "UTC",
                        "2006-01-02T15:04:05Z07:00"]),
    "expand": net.cidr_expand("10.0.0.0/30"),
    "merged": net.cidr_merge(["10.0.0.0/25", "10.0.0.128/25"]),
    "cidrmatch": net.cidr_contains_matches(["10.0.0.0/8", "1.1.1.0/24"],
                                           ["10.2.3.4", "8.8.8.8"]),
    "overlap": net.cidr_overlap("10.0.0.0/8", "10.1.1.1"),
    "tmpl": [regex.template_match("urn:foo:{.*}", "urn:foo:bar:baz",
                                  "{", "}"),
             regex.template_match("urn:foo:{[0-9]+}", "urn:foo:abc",
                                  "{", "}")],
    "globs": [regex.globs_match("a.b[0-9]*", "a.b3"),
              regex.globs_match("abc*", "xyz")],
    "fasn": regex.find_all_string_submatch_n("a(b+)", "abbabbb", -1),
    "quote": glob.quote_meta("*.github.com"),
    "ops": [plus(1, 2), minus(5, 3), mul(3, 4), div(8, 2), rem(7, 3),
            minus({1, 2, 3}, {2}), and({1, 2}, {2, 3}), or({1}, {2})],
    "cmp": [lt(1, 2), gt("b", "a"), lte(1, 1), gte(1, 2), eq(3, 3),
            lt(1, "a")],
    "sdiff": set_diff({1, 2}, {1}),
    "casts": [cast_null(null), cast_object({"a": 1}), cast_set({1})],
    "parsed": rego.parse_module("m.rego", "package p\\nq[x] { x := 1 }"),
  }
}

gated[m] {
  not http.send({"method": "GET", "url": "http://127.0.0.1:1/x"})
  m := "http.send undefined while gated"
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    out = interp.eval_rule(("b6",), "out", {})
    assert out is not UNDEF
    from gatekeeper_tpu.rego.codegen import compile_module
    from gatekeeper_tpu.utils.values import freeze
    fn = compile_module(module, entry="out")
    assert fn.__input_call__(freeze({}), freeze({})) == out
    got = thaw(list(out)[0])
    assert got["pns"] == 1588329000000000000
    assert got["dur"] == 5400 * 10**9
    assert got["fmt"] == "2020-05-01T10:29:59Z"
    assert sorted(got["expand"]) == ["10.0.0.0", "10.0.0.1", "10.0.0.2",
                                     "10.0.0.3"]
    assert got["merged"] == ["10.0.0.0/24"]
    assert got["cidrmatch"] == [[0, 0]]
    assert got["overlap"] is True
    assert got["tmpl"] == [True, False]
    assert got["globs"] == [True, False]
    assert got["fasn"] == [["abb", "bb"], ["abbb", "bbb"]]
    assert got["quote"] == "\\*.github.com"
    assert got["ops"] == [3, 2, 12, 4, 1, [1, 3], [2], [1, 2]]
    assert got["cmp"] == [True, True, True, False, True, True]
    assert got["sdiff"] == [2]
    assert got["casts"] == [None, {"a": 1}, [1]]
    assert got["parsed"]["package"]["path"] == ["data", "p"]
    assert got["parsed"]["rules"][0]["name"] == "q"
    # http.send is gated off by default: the call is undefined, `not`
    # succeeds (interpreter and codegen agree)
    gated = interp.eval_rule(("b6",), "gated", {})
    assert thaw(gated) == ["http.send undefined while gated"]
    g2 = compile_module(module, entry="gated")
    assert g2.__input_call__(freeze({}), freeze({})) == gated


def test_x509_and_asymmetric_jwt_in_rego():
    """x509 parse + RS256/ES256 verification exercised rego-level with
    real keys, through interpreter and codegen."""
    import base64 as b64

    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    from gatekeeper_tpu.control.certs import (
        _pem_cert,
        generate_ca,
        generate_server_cert,
    )

    ca_key, ca_cert = generate_ca()
    _, cert = generate_server_cert(ca_key, ca_cert, ["web.prod.svc"])
    chain_pem = _pem_cert(cert).decode() + _pem_cert(ca_cert).decode()

    priv = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    nums = priv.private_numbers()

    def b64i(i):
        bs = i.to_bytes((i.bit_length() + 7) // 8 or 1, "big")
        return b64.urlsafe_b64encode(bs).decode().rstrip("=")

    jwk = {"kty": "RSA", "n": b64i(nums.public_numbers.n),
           "e": b64i(nums.public_numbers.e), "d": b64i(nums.d),
           "p": b64i(nums.p), "q": b64i(nums.q), "dp": b64i(nums.dmp1),
           "dq": b64i(nums.dmq1), "qi": b64i(nums.iqmp)}
    pub_pem = priv.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo).decode()

    src = '''
package x509jwt

certnames[n] {
  certs := crypto.x509.parse_certificates(input.review.chain)
  n := certs[_].Subject.CommonName
}

ca_count = n {
  certs := crypto.x509.parse_certificates(input.review.chain)
  n := count([c | c := certs[_]; c.IsCA])
}

token = t {
  t := io.jwt.encode_sign({"alg": "RS256"}, {"iss": "tester"},
                          input.review.jwk)
}

verified = v {
  t := io.jwt.encode_sign({"alg": "RS256"}, {"iss": "tester"},
                          input.review.jwk)
  v := io.jwt.verify_rs256(t, input.review.pub)
}

checked = out {
  t := io.jwt.encode_sign({"alg": "RS256"}, {"iss": "tester"},
                          input.review.jwk)
  out := io.jwt.decode_verify(t, {"cert": input.review.pub,
                                  "iss": "tester"})
}
'''
    module = parse_module(src)
    interp = Interpreter({"m": module})
    inp = {"review": {"chain": chain_pem, "jwk": jwk, "pub": pub_pem}}
    names = thaw(interp.eval_rule(("x509jwt",), "certnames", inp))
    assert sorted(names) == ["gatekeeper-ca", "web.prod.svc"]
    assert thaw(interp.eval_rule(("x509jwt",), "ca_count", inp)) == 1
    assert thaw(interp.eval_rule(("x509jwt",), "verified", inp)) is True
    ok, _hdr, payload = thaw(interp.eval_rule(("x509jwt",), "checked", inp))
    assert ok is True and payload["iss"] == "tester"
    # codegen agreement on the full set
    from gatekeeper_tpu.rego.codegen import compile_module
    from gatekeeper_tpu.utils.values import freeze
    for entry in ("certnames", "ca_count", "verified", "checked"):
        fn = compile_module(module, entry=entry)
        assert fn.__input_call__(freeze(inp), freeze({})) == \
            interp.eval_rule(("x509jwt",), entry, inp), entry
