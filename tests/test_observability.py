"""Observability (PR 6 tentpole): end-to-end request tracing, latency
attribution, and the flight recorder.

Covers:
  * W3C traceparent parse/format + stride sampling + the preallocated
    no-op context (an UNSAMPLED request must allocate zero span
    objects on the hot path — asserted via the module allocation
    counter);
  * a traced admission request through 2 real subprocess frontends:
    one trace whose stage spans are complete, ordered, and sum to
    ~wall clock; inbound traceparent honored; X-Trace-Id answered;
    /debug/traces + /debug/templates + /metrics scraped over HTTP and
    validated (what the CI `observability` job boots);
  * audit-plane sweep traces with phase spans + stage histograms;
  * the Registry bucket-skew regression (bounds frozen at first
    registration, mismatch raises);
  * process self-metrics (start time, RSS, FDs, threads, GC);
  * a STRICT text-exposition parse of a loaded Runtime's full /metrics
    output (HELP/TYPE present, +Inf == _count, label escaping);
  * ISSUE 13 (saturation/SLO/trend): OpenMetrics exemplar round-trip
    + content negotiation, batch seal reasons/fill ratio, queue-depth
    saturation probes, engine duty-cycle EMA, build-info gauge, SLO
    burn-rate math + /debug/slo, and the bench_trend watchdog
    (passes on committed history, fails on a synthetic regression,
    unit-change series restarts, did-not-run error records).

Every test runs under a hard SIGALRM timeout.
"""

from __future__ import annotations

import http.client
import json
import re
import signal
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control import metrics as gm
from gatekeeper_tpu.control import trace as gt
from gatekeeper_tpu.control.backplane import _StatsAccumulator
from gatekeeper_tpu.control.webhook import (
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
)
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.faults import FAULTS

TARGET = "admission.k8s.gatekeeper.sh"
PER_TEST_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout_and_tracer_reset():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    rate, slow = gt.TRACER.sample_rate, gt.TRACER.slow_threshold_s
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        gt.TRACER.configure(rate, slow)
        gt.TRACER.recorder.clear()
        FAULTS.reset()


def _policy_client():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    return client


def _review(name, labels=None, uid=None):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": "d"}}
    if labels:
        obj["metadata"]["labels"] = labels
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid or f"uid-{name}", "operation": "CREATE",
                        "kind": {"group": "", "version": "v1",
                                 "kind": "Pod"},
                        "name": name, "namespace": "d",
                        "userInfo": {"username": "obs"}, "object": obj}}


# ------------------------------------------------------ traceparent + sampling


def test_traceparent_parse_and_format():
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    parsed, sampled = gt.parse_traceparent(
        f"00-{tid}-00f067aa0ba902b7-01")
    assert parsed == tid and sampled is True
    parsed, sampled = gt.parse_traceparent(
        f"00-{tid}-00f067aa0ba902b7-00")
    assert parsed == tid and sampled is False
    # malformed / all-zero never raise, never sample
    for bad in (None, "", "junk", "00-short-x-01", "00-" + "0" * 32
                + "-00f067aa0ba902b7-01", "zz-" + tid + "-gg-01"):
        assert gt.parse_traceparent(bad) == (None, False)
    # STRICT hex ids only: int(x, 16) would accept these, but they
    # would blow up bytes.fromhex when the context rides the backplane
    # frame (regression: 500 + a leaked frontend waiter per request)
    for evil in ("0x" + "a" * 30, "a_" * 16, "+" + "a" * 31,
                 " " + "a" * 31):
        assert gt.parse_traceparent(
            f"00-{evil}-00f067aa0ba902b7-01") == (None, False), evil
    # uppercase ids normalize to lowercase (fromhex-safe either way)
    assert gt.parse_traceparent(
        "00-" + "AB" * 16 + "-00f067aa0ba902b7-01")[0] == "ab" * 16
    hdr = gt.format_traceparent(tid)
    assert gt.parse_traceparent(hdr) == (tid, True)


def test_stride_sampling_and_forced_traceparent():
    tracer = gt.Tracer(sample_rate=0.5, metrics_sink=False)
    kinds = [tracer.start("admission") is gt.NOOP for _ in range(10)]
    assert kinds.count(False) == 5  # every 2nd samples
    tracer.configure(0.0)
    assert tracer.start("admission") is gt.NOOP
    # an inbound sampled traceparent forces tracing past rate 0 AND
    # carries its trace id through
    tid = "ab" * 16
    tr = tracer.start("admission", f"00-{tid}-00f067aa0ba902b7-01")
    assert tr is not gt.NOOP and tr.trace_id == tid
    tr.finish()
    # sample_context (the frontend edge) agrees
    assert tracer.sample_context() is None
    assert tracer.sample_context(f"00-{tid}-00f067aa0ba902b7-01") == tid


def test_unsampled_request_allocates_no_span_objects():
    """The acceptance bar for hot-path cost: with sampling off, a full
    admission round trip through the real HTTP server must not
    construct a single Span/Trace object."""
    gt.TRACER.configure(0.0)
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    server = WebhookServer(handler, NamespaceLabelHandler(()), port=0)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("POST", "/v1/admit",
                     json.dumps(_review("warm", {"owner": "x"})),
                     {"Content-Type": "application/json"})
        conn.getresponse().read()  # warm the path first
        before = gt.ALLOCATIONS
        for i in range(20):
            conn.request("POST", "/v1/admit",
                         json.dumps(_review(f"p{i}", {"owner": "x"})),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            assert resp.getheader("X-Trace-Id") is None
        assert gt.ALLOCATIONS == before, \
            "unsampled requests allocated span objects on the hot path"
    finally:
        server.stop(drain_timeout=1.0)


# ---------------------------------------------------------- flight recorder


def _mk_trace(tracer, duration, plane="admission"):
    tr = tracer.start(plane, force=True)
    tr.add_span("evaluate", tr.t0, tr.t0 + duration)
    tr.t1 = tr.t0 + duration
    return tr


def test_flight_recorder_keeps_recent_and_slowest():
    rec = gt.FlightRecorder(keep=3)
    tracer = gt.Tracer(sample_rate=1.0, recorder=rec,
                       metrics_sink=False, slow_threshold_s=0)
    durations = [0.1, 5.0, 0.2, 4.0, 0.3, 3.0, 0.05]
    for d in durations:
        tr = _mk_trace(tracer, d)
        rec.record(tr)
    dump = rec.dump()["planes"]["admission"]
    assert len(dump["recent"]) == 3 and len(dump["slowest"]) == 3
    # recent = last three, oldest first
    assert [t["duration_s"] for t in dump["recent"]] == [0.3, 3.0, 0.05]
    # slowest = global top three, slowest first — the 5.0s outlier is
    # retained long after it aged out of the recent ring
    assert [t["duration_s"] for t in dump["slowest"]] == [5.0, 4.0, 3.0]
    # per-plane isolation
    rec.record(_mk_trace(tracer, 9.0, plane="audit"))
    planes = rec.dump()["planes"]
    assert planes["audit"]["slowest"][0]["duration_s"] == 9.0
    assert planes["admission"]["slowest"][0]["duration_s"] == 5.0


def test_slow_trace_logs_structured_line(caplog):
    import logging as pylog

    tracer = gt.Tracer(sample_rate=1.0, slow_threshold_s=0.0001,
                       metrics_sink=False)
    with caplog.at_level(pylog.WARNING, logger="gatekeeper.trace"):
        tr = tracer.start("admission", force=True)
        time.sleep(0.002)
        tr.finish()
    assert any("slow request trace" in r.getMessage()
               for r in caplog.records)


# ------------------------------------------------- registry bucket freeze


def test_histogram_buckets_freeze_at_first_registration():
    """Regression for the bucket-skew bug: two call sites passing
    different bounds for the same metric silently mis-bucketed counts
    against stale lists (m.buckets was re-assigned on every observe)."""
    reg = gm.Registry()
    reg.observe("skew_test_seconds", "h", 0.3, buckets=(0.1, 1.0))
    with pytest.raises(ValueError, match="skew_test_seconds"):
        reg.observe("skew_test_seconds", "h", 0.3,
                    buckets=(0.5, 2.0, 10.0))
    # the original bounds survived, counts landed against them
    reg.observe("skew_test_seconds", "h", 0.05, buckets=(0.1, 1.0))
    text = reg.render()
    assert 'skew_test_seconds_bucket{le="0.1"} 1' in text
    assert 'skew_test_seconds_bucket{le="1"} 2' in text
    assert 'le="0.5"' not in text
    # observe_bucketed enforces the same freeze
    with pytest.raises(ValueError):
        reg.observe_bucketed("skew_test_seconds", "h", (9.9,), [1, 0],
                             0.1, 1)


def test_label_values_are_escaped():
    reg = gm.Registry()
    reg.counter_add("esc_total", "c", kind='we"ird\\na\nme')
    text = reg.render()
    assert 'kind="we\\"ird\\\\na\\nme"' in text


# ---------------------------------------------------- process self-metrics


def test_process_self_metrics_exposed():
    reg = gm.Registry()
    gm.update_process_metrics(reg)
    text = reg.render()
    for name in ("process_start_time_seconds",
                 "process_resident_memory_bytes", "process_open_fds",
                 "process_threads", "python_gc_objects_tracked"):
        assert name in text, f"{name} missing from exposition"
    start = float(re.search(
        r"^process_start_time_seconds (\S+)$", text, re.M).group(1))
    assert 0 < start <= time.time()
    rss = float(re.search(
        r"^process_resident_memory_bytes (\S+)$", text, re.M).group(1))
    assert rss > 1 << 20  # a live interpreter holds > 1MiB


# -------------------------------------------------- exposition strict parse


_EXEMPLAR_RE = re.compile(
    r' # \{trace_id="((?:[^"\\]|\\.)*)"\} (\S+) (\S+)$')


def _parse_exposition_strict(text: str, openmetrics: bool = False
                             ) -> dict:
    """Strict text-format parse: every sample must belong to an
    announced metric family (HELP + TYPE first), histogram +Inf bucket
    must equal _count, label values must round-trip the escaping.
    `openmetrics=True` additionally requires the terminal `# EOF` and
    accepts (collecting) per-bucket exemplar clauses.
    Returns {family: {"type", "samples": [(name, labels, value)]}}
    plus, under the reserved "__exemplars__" key, every
    (sample_line_prefix, trace_id, value, ts) exemplar found."""
    families: dict = {}
    exemplars: list = []
    cur = None
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$")
    label_re = re.compile(
        r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(,|$)')
    lines = text.splitlines()
    if openmetrics:
        assert lines and lines[-1] == "# EOF", \
            "OpenMetrics exposition must end with # EOF"
        lines = lines[:-1]
    orig_lines = lines
    lines = []
    for line in orig_lines:
        m = _EXEMPLAR_RE.search(line)
        if m:
            assert openmetrics, \
                "exemplar syntax leaked into the plain text format"
            exemplars.append((line[: m.start()], m.group(1),
                              float(m.group(2)), float(m.group(3))))
            line = line[: m.start()]
        lines.append(line)
    for line in lines:
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"type": None, "samples": []}
            cur = name
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[2] == cur, "TYPE does not follow its HELP"
            assert families[cur]["type"] is None, "duplicate TYPE"
            assert parts[3] in ("counter", "gauge", "histogram")
            families[cur]["type"] = parts[3]
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, _, labeltext, value = m.groups()
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = name if name in families else base
        if fam not in families and openmetrics and \
                name.endswith("_total"):
            # OpenMetrics counter naming: the family drops _total, the
            # sample carries it — and the spec REQUIRES counters to
            # sample as _total
            fam = name[:-6]
            assert families.get(fam, {}).get("type") == "counter", \
                f"{name}: _total sample without a counter family"
        if openmetrics and families.get(fam, {}).get("type") \
                == "counter":
            assert name.endswith("_total"), \
                f"OpenMetrics counter sample {name} must end _total"
        assert fam in families, f"sample {name} has no HELP/TYPE"
        assert families[fam]["type"] is not None
        labels = {}
        if labeltext:
            consumed = 0
            for lm in label_re.finditer(labeltext):
                labels[lm.group(1)] = lm.group(2)
                consumed = lm.end()
            assert consumed == len(labeltext), \
                f"bad label syntax: {labeltext!r}"
        float(value)  # must be numeric
        families[fam]["samples"].append((name, labels, float(value)))
    # histogram invariants
    for fam, data in families.items():
        if data["type"] != "histogram":
            continue
        series: dict = {}
        for name, labels, value in data["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            ent = series.setdefault(key, {})
            if name.endswith("_bucket"):
                ent.setdefault("buckets", {})[labels["le"]] = value
            elif name.endswith("_count"):
                ent["count"] = value
            elif name.endswith("_sum"):
                ent["sum"] = value
        for key, ent in series.items():
            assert "+Inf" in ent.get("buckets", {}), \
                f"{fam}{dict(key)} missing +Inf bucket"
            assert ent["buckets"]["+Inf"] == ent["count"], \
                f"{fam}{dict(key)}: +Inf bucket != _count"
            # cumulative buckets must be monotonic
            prev = 0.0
            for le, v in sorted(
                    ent["buckets"].items(),
                    key=lambda kv: float("inf") if kv[0] == "+Inf"
                    else float(kv[0])):
                assert v >= prev, f"{fam}: non-monotonic buckets"
                prev = v
    families["__exemplars__"] = {"type": "reserved",
                                 "samples": exemplars}
    return families


def test_full_runtime_exposition_parses_strictly():
    """The whole /metrics output of a LOADED runtime — histograms,
    escaped labels, merged bucketed deltas — must satisfy a strict
    text-format parser, so malformed series can never ship again."""
    gt.TRACER.configure(1.0, 10.0)
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    for i in range(5):
        handler.handle(_review(f"ok{i}", {"owner": "me"}))
        handler.handle(_review(f"bad{i}"))
    # a pre-aggregated delta merge (the backplane stats path)
    gm.report_backplane_forward(
        "w0", [1] * (len(gm.FORWARD_BUCKETS) + 1), 0.5,
        len(gm.FORWARD_BUCKETS) + 1)
    gm.report_stage_bucketed(
        "admission", "frontend_parse",
        [2] * (len(gm.STAGE_BUCKETS) + 1), 0.1,
        2 * (len(gm.STAGE_BUCKETS) + 1))
    # a label value that needs escaping
    gm.REGISTRY.counter_add("gatekeeper_tpu_test_escape_total", "t",
                            kind='K8s"Weird\\Kind')
    gm.update_process_metrics()
    families = _parse_exposition_strict(gm.REGISTRY.render())
    assert families["request_duration_seconds"]["type"] == "histogram"
    assert families["gatekeeper_tpu_stage_duration_seconds"]["type"] \
        == "histogram"
    esc = families["gatekeeper_tpu_test_escape_total"]["samples"]
    assert esc[0][1]["kind"] == 'K8s\\"Weird\\\\Kind'
    handler.batcher.stop()


# ----------------------------------------------- single-process trace path


def test_single_process_trace_decomposition_and_header():
    gt.TRACER.configure(1.0, slow_threshold_s=0)
    gt.TRACER.recorder.clear()
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.002))
    server = WebhookServer(handler, None, port=0)
    server.start()
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("POST", "/v1/admit",
                     json.dumps(_review("t1", {"owner": "x"})),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Trace-Id") == tid, \
            "inbound traceparent trace id not honored"
        dump = gt.TRACER.recorder.dump()["planes"]["admission"]
        trace = next(t for t in dump["recent"] if t["trace_id"] == tid)
        stages = [s["stage"] for s in trace["spans"]]
        for want in ("frontend_parse", "batch_seal", "evaluate",
                     "serialize"):
            assert want in stages, f"stage {want} missing: {stages}"
        assert trace["status"] == "allow"
    finally:
        server.stop(drain_timeout=1.0)


def test_cache_hit_stage_replaces_evaluate():
    gt.TRACER.configure(1.0, slow_threshold_s=0)
    gt.TRACER.recorder.clear()
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    handler.handle(_review("same", {"owner": "x"}, uid="u1"))
    tr = gt.TRACER.start(gt.ADMISSION, force=True)
    handler.handle(_review("same", {"owner": "x"}, uid="u2"), trace=tr)
    tr.finish()
    stages = [s["stage"] for s in tr.to_dict()["spans"]]
    assert "cache_hit" in stages and "evaluate" not in stages
    handler.batcher.stop()


# ------------------------------------------------- backplane stats plumbing


def test_stats_accumulator_ships_stage_deltas():
    acc = _StatsAccumulator()
    acc.observe(0.001)
    acc.observe_stage("frontend_parse", 0.0002)
    acc.observe_stage("frontend_parse", 0.3)
    acc.observe_stage("some_other_stage", 0.001)
    out = acc.drain("w3")
    assert out["count"] == 1
    stages = out["stages"]
    assert stages["frontend_parse"]["count"] == 2
    assert abs(stages["frontend_parse"]["sum"] - 0.3002) < 1e-6
    assert sum(stages["frontend_parse"]["buckets"]) == 2
    assert stages["some_other_stage"]["count"] == 1
    # drained clean
    assert acc.drain("w3") is None


# --------------------------------------------------------- audit plane trace


def test_audit_sweep_trace_phases_and_histograms():
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    gt.TRACER.recorder.clear()
    kube = FakeKube()
    for gvk, namespaced in [(("", "v1", "Namespace"), False),
                            (("", "v1", "Pod"), True)]:
        kube.register_kind(gvk, namespaced=namespaced)
    kube.create({"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": "d"}})
    for i in range(4):
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "namespace": "d"}})
    client = _policy_client()
    mgr = AuditManager(kube, client, incremental=True,
                       gc_stale_statuses=False)
    mgr.audit_once()
    mgr.audit_once()  # one incremental sweep too
    mgr.stop()
    dump = gt.TRACER.recorder.dump()["planes"]
    assert "audit" in dump, "audit sweeps must always trace"
    stages_seen = set()
    for t in dump["audit"]["recent"]:
        stages_seen.update(s["stage"] for s in t["spans"])
    for want in ("list_delta_apply", "evaluate", "status_writes"):
        assert want in stages_seen
    statuses = {t["status"] for t in dump["audit"]["recent"]}
    assert {"full_resync", "incremental"} <= statuses
    text = gm.REGISTRY.render()
    assert 'gatekeeper_tpu_stage_duration_seconds_count' \
        '{engine="",plane="audit",stage="evaluate"}' in text


def test_failed_sweep_still_records_error_trace():
    """A sweep that blows up mid-evaluation must still land in the
    flight recorder with status=error — the failing sweeps are exactly
    the ones worth diagnosing after the fact."""
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    gt.TRACER.recorder.clear()
    client = _policy_client()

    def boom():
        raise RuntimeError("device on fire")

    client.audit = boom
    mgr = AuditManager(FakeKube(), client, audit_from_cache=True,
                       gc_stale_statuses=False)
    with pytest.raises(RuntimeError):
        mgr.audit_once()
    dump = gt.TRACER.recorder.dump()["planes"]["audit"]["recent"]
    assert dump and dump[-1]["status"] == "error"
    assert "device on fire" in dump[-1]["attrs"]["error"]


# ------------------------------------- full plane: subprocess frontends


def _get(conn_host, port, path):
    conn = http.client.HTTPConnection(conn_host, port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_traced_request_through_subprocess_frontends():
    """The acceptance path: a Runtime with 2 pre-forked frontend
    PROCESSES at sample rate 1.0 — a traced request yields ONE trace
    whose stage spans are complete, ordered, and sum to ~wall clock;
    /metrics and /debug/* validate over real HTTP."""
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--trace-sample-rate", "1.0", "--trace-slow-threshold", "0"])
    rt = Runtime(args)
    rt.start()
    # load a real template so the traced request evaluates something
    # and /debug/templates has per-kind state to report
    rt.opa.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]}})
    rt.opa.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    tid = "aabbccddeeff00112233445566778899"
    try:
        deadline = time.monotonic() + 10
        while rt.backplane.connected < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rt.backplane.connected == 2
        mport = rt.metrics_server.server_address[1]
        hport = rt.health.port
        conn = http.client.HTTPConnection("127.0.0.1", rt.frontends.port,
                                          timeout=15)
        conn.request("POST", "/v1/admit?timeout=10s",
                     json.dumps(_review("traced")),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Trace-Id") == tid
        # the engine records the trace at respond time; poll the dump
        trace = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            status, body = _get("127.0.0.1", mport, "/debug/traces")
            assert status == 200
            planes = json.loads(body).get("planes", {})
            for t in planes.get("admission", {}).get("recent", []):
                if t["trace_id"] == tid:
                    trace = t
                    break
            if trace:
                break
            time.sleep(0.1)
        assert trace is not None, "traced request never reached the " \
            "flight recorder"
        stages = [s["stage"] for s in trace["spans"]]
        # >= 5 named stages spanning frontend -> backplane -> engine ->
        # eval path
        for want in ("frontend_parse", "backplane_forward",
                     "batch_seal", "evaluate", "serialize", "respond"):
            assert want in stages, f"{want} missing from {stages}"
        assert len(stages) >= 5
        # complete + ordered: spans start in order, live inside the
        # trace window, and sum to ~wall clock (sequential stages;
        # small gaps for untimed glue are expected)
        starts = [s["start_s"] for s in trace["spans"]]
        assert starts == sorted(starts), "stage spans out of order"
        total = trace["duration_s"]
        span_sum = sum(s["duration_s"] for s in trace["spans"])
        assert all(0 <= s["start_s"] <= total + 1e-6
                   for s in trace["spans"])
        assert span_sum <= total * 1.10 + 1e-4
        assert span_sum >= total * 0.5, \
            f"spans cover too little of the trace: {span_sum} / {total}"
        # a second, uid-churned request serves from the decision cache
        # and still decomposes (cache_hit path)
        conn.request("POST", "/v1/admit?timeout=10s",
                     json.dumps(_review("traced", uid="uid-2")),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        tid2 = resp.getheader("X-Trace-Id")
        assert tid2 and tid2 != tid
        # /metrics: engine-side stages appear immediately; the
        # frontend-shipped stage deltas land within one S-frame
        # interval (2s)
        deadline = time.monotonic() + 8
        text = ""
        while time.monotonic() < deadline:
            status, body = _get("127.0.0.1", mport, "/metrics")
            assert status == 200
            text = body.decode()
            if ('stage="frontend_parse"' in text
                    and 'stage="evaluate"' in text):
                break
            time.sleep(0.2)
        for frag in ('plane="admission"', 'stage="evaluate"',
                     'stage="frontend_parse"', 'stage="respond"',
                     "gatekeeper_tpu_traces_total",
                     "process_resident_memory_bytes",
                     # ISSUE 13: the capacity-attribution families a
                     # single scrape of a loaded plane must carry
                     "gatekeeper_tpu_batch_seal_total",
                     "gatekeeper_tpu_batch_fill_ratio_bucket",
                     'gatekeeper_tpu_queue_depth'
                     '{engine="",queue="admission"}',
                     'gatekeeper_tpu_queue_depth'
                     '{engine="0",queue="backplane_engine"}',
                     "gatekeeper_tpu_device_duty_cycle",
                     "gatekeeper_tpu_build_info",
                     "gatekeeper_tpu_slo_burn_rate",
                     "gatekeeper_tpu_slo_target"):
            assert frag in text, f"{frag} missing from /metrics"
        _parse_exposition_strict(text)
        # OpenMetrics negotiation on the same loaded runtime: a stage
        # histogram bucket must carry a trace-id exemplar that
        # RESOLVES in the flight recorder (/debug/traces)
        conn2 = http.client.HTTPConnection("127.0.0.1", mport,
                                           timeout=10)
        conn2.request("GET", "/metrics",
                      headers={"Accept":
                               "application/openmetrics-text"})
        resp = conn2.getresponse()
        om = resp.read().decode()
        conn2.close()
        assert resp.getheader("Content-Type").startswith(
            "application/openmetrics-text")
        fams = _parse_exposition_strict(om.rstrip("\n"),
                                        openmetrics=True)
        ex_tids = {e[1] for e in fams["__exemplars__"]["samples"]
                   if e[0].startswith(
                       "gatekeeper_tpu_stage_duration_seconds")}
        assert ex_tids, "no stage bucket carries a trace-id exemplar"
        _status, tr_body = _get("127.0.0.1", mport, "/debug/traces")
        recorded = {t["trace_id"] for t in json.loads(tr_body)
                    .get("planes", {}).get("admission", {})
                    .get("recent", [])}
        assert ex_tids & recorded, \
            f"exemplar ids {ex_tids} resolve to no recorded trace"
        # /debug/templates on the metrics port, /debug/traces on the
        # health port (same registry), unknown endpoints 404
        status, body = _get("127.0.0.1", mport, "/debug/templates")
        assert status == 200
        tmpl = json.loads(body)
        assert "K8sNeedOwner" in tmpl["templates"]
        status, _ = _get("127.0.0.1", hport, "/debug/traces")
        assert status == 200
        # /debug/slo answers the compliance picture
        status, body = _get("127.0.0.1", mport, "/debug/slo")
        assert status == 200
        slo = json.loads(body)
        names = {o["name"] for o in slo["objectives"]}
        assert "admission_p99_latency" in names
        assert "availability" in names
        status, body = _get("127.0.0.1", mport, "/debug/nope")
        assert status == 404
        assert "available" in json.loads(body)
    finally:
        rt.stop()


# ----------------------------------- exemplars + OpenMetrics negotiation


def test_openmetrics_exemplar_round_trip():
    """An observation carrying a trace-id exemplar renders in the
    OpenMetrics dialect on exactly the bucket it landed in, round-trips
    the strict parser, and never leaks into the plain text format."""
    reg = gm.Registry()
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    reg.observe("om_stage_seconds", "h", 0.03, buckets=(0.01, 0.1, 1.0),
                exemplar=tid, stage="evaluate")
    reg.observe("om_stage_seconds", "h", 5.0, buckets=(0.01, 0.1, 1.0),
                exemplar="ff" * 16, stage="evaluate")  # +Inf overflow
    reg.observe("om_stage_seconds", "h", 0.02, buckets=(0.01, 0.1, 1.0),
                stage="evaluate")  # unsampled: no exemplar attached
    om = reg.render(openmetrics=True)
    fams = _parse_exposition_strict(om, openmetrics=True)
    exemplars = fams["__exemplars__"]["samples"]
    assert len(exemplars) == 2, om
    by_tid = {e[1]: e for e in exemplars}
    line, _tid, value, ts = by_tid[tid]
    assert 'le="0.1"' in line  # the bucket 0.03 landed in
    assert value == 0.03 and ts > 0
    assert 'le="+Inf"' in by_tid["ff" * 16][0]
    # the LATEST exemplar per bucket wins
    tid2 = "ab" * 16
    reg.observe("om_stage_seconds", "h", 0.05, buckets=(0.01, 0.1, 1.0),
                exemplar=tid2, stage="evaluate")
    om2 = reg.render(openmetrics=True)
    assert tid2 in om2 and tid not in om2
    # plain text format: identical series, zero exemplar syntax
    text = reg.render()
    _parse_exposition_strict(text)
    assert "trace_id" not in text and "# EOF" not in text


def test_metrics_content_negotiation_over_http():
    """GET /metrics honors Accept: a scraper asking for
    application/openmetrics-text gets the exemplar-bearing dialect
    (+ # EOF); everyone else gets the classic text format."""
    reg = gm.Registry()
    reg.observe("nego_seconds", "h", 0.3, buckets=(0.1, 1.0),
                exemplar="cd" * 16)
    # counters in BOTH naming styles: the OpenMetrics dialect must
    # sample every counter as <family>_total (strict scrapers —
    # Prometheus's openmetrics parser included — reject the whole
    # exposition otherwise), while the text format keeps legacy names
    reg.counter_add("legacy_count", "c", 3)
    reg.counter_add("modern_total", "c", 4)
    server = gm.serve(0, registry=reg, addr="127.0.0.1")
    try:
        port = server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/metrics",
                     headers={"Accept": "application/openmetrics-text; "
                                        "version=1.0.0"})
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.getheader("Content-Type").startswith(
            "application/openmetrics-text")
        assert body.rstrip().endswith("# EOF")
        assert 'trace_id="' + "cd" * 16 + '"' in body
        assert "# TYPE legacy_count counter" in body
        assert "\nlegacy_count_total 3" in body
        assert "# TYPE modern counter" in body
        assert "\nmodern_total 4" in body
        _parse_exposition_strict(body.rstrip("\n"), openmetrics=True)
        # no Accept (or a plain one): classic text format, no exemplars,
        # legacy counter names untouched
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = resp.read().decode()
        assert resp.getheader("Content-Type").startswith("text/plain")
        assert "trace_id" not in body and "# EOF" not in body
        assert "\nlegacy_count 3" in body
        assert "legacy_count_total" not in body
        conn.close()
    finally:
        server.shutdown()


def test_trace_span_feeds_stage_exemplar():
    """A finished sampled trace attaches its id to the stage-histogram
    buckets it observed (control/trace.py -> report_stage exemplar)."""
    tid = "1234567890abcdef1234567890abcdef"
    tr = gt.TRACER.start(gt.ADMISSION, f"00-{tid}-00f067aa0ba902b7-01")
    with tr.span("evaluate"):
        time.sleep(0.001)
    tr.finish()
    om = gm.REGISTRY.render(openmetrics=True)
    stage_lines = [ln for ln in om.splitlines()
                   if ln.startswith("gatekeeper_tpu_stage_duration_"
                                    "seconds_bucket") and tid in ln]
    assert stage_lines, "trace id never reached a stage bucket exemplar"


# ------------------------------------------ batch economics + saturation


def _seal_counts(plane="admission"):
    snap = gm.REGISTRY.snapshot(("gatekeeper_tpu_batch_seal_total",))
    ent = snap.get("gatekeeper_tpu_batch_seal_total") or {}
    # label values ordered by sorted label names: (plane, reason)
    return {k[1]: v for k, v in
            ((tuple(lk), v) for lk, v in ent.get("values") or [])
            if k[0] == plane}


def test_batch_seal_reasons_and_fill_ratio():
    evaluate = lambda reviews: [[] for _ in reviews]  # noqa: E731

    # FULL: two submits against max_batch=2 seal a full batch
    before = _seal_counts()
    b = MicroBatcher(None, max_wait=0.5, max_batch=2, evaluate=evaluate)
    import threading as _threading
    t = _threading.Thread(
        target=lambda: b.submit(_review("f1", {"owner": "x"}),
                                timeout=10))
    t.start()
    b.submit(_review("f2", {"owner": "x"}), timeout=10)
    t.join(10)
    b.stop()
    after = _seal_counts()
    assert after.get("full", 0) > before.get("full", 0), (before, after)

    # MAX_WAIT: a lone submit with a far deadline seals when the
    # collection window elapses
    before = after
    b = MicroBatcher(None, max_wait=0.01, max_batch=64,
                     evaluate=evaluate)
    b.submit(_review("w1", {"owner": "x"}), timeout=30)
    b.stop()
    after = _seal_counts()
    assert after.get("max_wait", 0) > before.get("max_wait", 0), \
        (before, after)

    # DEADLINE: a tight member deadline forces the seal well before
    # the (long) collection window
    before = after
    b = MicroBatcher(None, max_wait=5.0, max_batch=64,
                     evaluate=evaluate)
    b.submit(_review("d1", {"owner": "x"}),
             deadline=time.monotonic() + 0.8)
    b.stop()
    after = _seal_counts()
    assert after.get("deadline", 0) > before.get("deadline", 0), \
        (before, after)

    # fill-ratio histogram populated alongside
    text = gm.REGISTRY.render()
    assert "gatekeeper_tpu_batch_fill_ratio_bucket" in text
    m = re.search(r'gatekeeper_tpu_batch_fill_ratio_count'
                  r'\{plane="admission"\} (\d+)', text)
    assert m and int(m.group(1)) >= 3


def test_queue_depth_probe_and_stream_pending_gauge():
    calls = []
    gm.register_saturation_probe(
        "test-probe", lambda: calls.append(1) or gm.report_queue_depth(
            "admission", 7))
    try:
        gm.run_saturation_probes()
        assert calls
        text = gm.REGISTRY.render()
        assert ('gatekeeper_tpu_queue_depth'
                '{engine="",queue="admission"} 7') in text
    finally:
        gm.unregister_saturation_probe("test-probe")
    # a raising probe must not fail the scrape
    gm.register_saturation_probe(
        "test-bad", lambda: (_ for _ in ()).throw(RuntimeError("x")))
    try:
        gm.run_saturation_probes()
    finally:
        gm.unregister_saturation_probe("test-bad")
    # stream backlog gauge (satellite: was logs-only)
    gm.report_stream_pending(42)
    assert ('gatekeeper_tpu_audit_stream_pending_events 42'
            in gm.REGISTRY.render())


def test_build_info_gauge():
    gm.report_build_info()
    text = gm.REGISTRY.render()
    m = re.search(r'^gatekeeper_tpu_build_info\{(.*)\} 1$', text, re.M)
    assert m, "build info gauge missing"
    labels = m.group(1)
    for want in ("version=", "jax_version=", "platform=",
                 "device_count="):
        assert want in labels, labels


# ------------------------------------------------------ duty cycle (EMA)


def test_duty_cycle_ema():
    from gatekeeper_tpu.ir import TpuDriver

    drv = TpuDriver()
    # saturate the first sample window: raw clamps to 1.0 and seeds
    # the EMA directly (no decay from a meaningless zero)
    drv.note_busy(100.0)
    time.sleep(0.06)
    first = drv.duty_cycle()
    assert first == pytest.approx(1.0)
    # a scrape storm (second sample inside the window) reuses the
    # sample — the window is widened here so a loaded CI runner's
    # scheduler stall between the two calls can't flake the assert
    assert drv.duty_cycle(min_window_s=30.0) == pytest.approx(first)
    # an idle window decays the EMA toward zero at alpha=0.3
    time.sleep(0.06)
    second = drv.duty_cycle()
    assert second == pytest.approx(0.7, abs=0.01)
    # eval paths actually accumulate busy time (note_eval seconds arg)
    drv.note_eval("K8sX", "device", seconds=0.5)
    time.sleep(0.06)
    assert drv.duty_cycle() > second


# ----------------------------------------------------------- SLO layer


def _slo_registry():
    reg = gm.Registry()
    for v in (0.01, 0.02, 0.05, 0.05, 0.05):  # all under 0.1
        reg.observe("request_duration_seconds", "h", v,
                    admission_status="allow")
    reg.counter_add("request_count", "c", 100, admission_status="allow")
    return reg


def test_slo_burn_rates_multi_window():
    from gatekeeper_tpu.control.slo import SloEngine, default_objectives

    reg = _slo_registry()
    eng = SloEngine(default_objectives(admission_p99_s=0.1,
                                       availability_target=0.99),
                    registry=reg, sample_interval_s=15)
    eng.sample(now=0.0)
    # healthy traffic: zero burn on every objective/window
    rates = eng.burn_rates(now=400.0)
    for slo, by_window in rates.items():
        for w, ent in by_window.items():
            assert ent["burn_rate"] == 0.0, (slo, w, ent)
    # 10 good + 10 shed in the next window: bad fraction 0.5 against a
    # 1% budget = burn 50 on both windows (the 1h anchor is the same
    # sample while history is short — lifetime-honest)
    reg.counter_add("request_count", "c", 10, admission_status="allow")
    reg.counter_add("request_count", "c", 10, admission_status="shed")
    rates = eng.burn_rates(now=400.0)
    av = rates["availability"]
    assert av["5m"]["burn_rate"] == pytest.approx(50.0)
    assert av["5m"]["bad"] == 10 and av["5m"]["total"] == 20
    # latency: 3 of 8 in-window requests past the 0.1s threshold burn
    # the p99 budget (bad fraction 0.375 over a 1% budget = 37.5)
    for _ in range(5):
        reg.observe("request_duration_seconds", "h", 0.05,
                    admission_status="allow")
    for _ in range(3):
        reg.observe("request_duration_seconds", "h", 2.0,
                    admission_status="allow")
    rates = eng.burn_rates(now=401.0)
    lat = rates["admission_p99_latency"]["5m"]
    assert lat["bad"] == 3 and lat["total"] == 8
    assert lat["burn_rate"] == pytest.approx(37.5)
    # export refreshes the gauges
    eng.export(now=402.0)
    text = reg.render()
    assert 'gatekeeper_tpu_slo_burn_rate{slo="availability"' \
        ',window="5m"}' in text
    assert 'gatekeeper_tpu_slo_target{slo="admission_p99_latency"} ' \
        '0.99' in text


def test_slo_window_anchoring_prefers_full_window():
    """With enough history, the 5m window reads a 5m-old anchor while
    the 1h window reads an older one — the two burn rates diverge when
    the bad traffic is recent."""
    from gatekeeper_tpu.control.slo import SloEngine, default_objectives

    reg = _slo_registry()
    eng = SloEngine(default_objectives(availability_target=0.99),
                    registry=reg, sample_interval_s=15)
    eng.sample(now=0.0)
    # an hour of healthy samples
    for t in range(1, 240):
        eng.sample(now=t * 15.0)
    # a recent burst of bad traffic (inside the last 5m)
    reg.counter_add("request_count", "c", 10, admission_status="error")
    now = 240 * 15.0
    rates = eng.burn_rates(now=now)
    av = rates["availability"]
    # both windows see the same 10 bad events, but over different
    # anchors; the FAST window must see a full-strength burn
    assert av["5m"]["burn_rate"] > 0
    assert av["5m"]["window_actual_s"] >= 300
    assert av["1h"]["window_actual_s"] >= 3600
    assert av["5m"]["total"] <= av["1h"]["total"]


def test_slo_objective_validation():
    from gatekeeper_tpu.control.slo import SloObjective

    with pytest.raises(ValueError):
        SloObjective("x", "latency", 1.0, "m", threshold_s=0.1)
    with pytest.raises(ValueError):
        SloObjective("x", "latency", 0.99, "m")  # no threshold
    with pytest.raises(ValueError):
        SloObjective("x", "weird", 0.99, "m")


def test_debug_slo_provider_shape():
    from gatekeeper_tpu.control.slo import SloEngine, default_objectives

    reg = _slo_registry()
    eng = SloEngine(default_objectives(), registry=reg,
                    sample_interval_s=15)
    eng.sample(now=0.0)
    status = eng.status(now=10.0)
    names = {o["name"] for o in status["objectives"]}
    assert names == {"admission_p99_latency", "availability",
                     "violation_detection_p99"}
    for o in status["objectives"]:
        assert "windows" in o and "target" in o
    assert status["alert_reference_burn_rates"]["5m"] == 14.4


# ------------------------------------------------- perf-trend watchdog


def _bench_trend():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "bench_trend.py")
    spec = importlib.util.spec_from_file_location("bench_trend", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_round(d, n, doc):
    import os
    with open(os.path.join(d, f"BENCH_r{n:02d}.json"), "w") as f:
        json.dump({"parsed": doc}, f)


def test_bench_trend_check_passes_on_committed_history():
    """The acceptance gate: the committed BENCH_r01-r05 trajectory must
    pass --check (scale changes between rounds restart series via the
    unit string; they are not regressions)."""
    import io
    import os
    from contextlib import redirect_stdout

    bt = _bench_trend()
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bt.main(["--dir", root, "--check"])
    assert rc == 0, buf.getvalue()
    report = buf.getvalue()
    assert "# Benchmark trend" in report
    assert "r01" in report and "r05" in report


def test_bench_trend_fails_on_synthetic_regression(tmp_path):
    import io
    from contextlib import redirect_stdout

    bt = _bench_trend()
    d = str(tmp_path)
    _write_round(d, 1, {"metric": "full_audit_wall_clock_s",
                        "value": 1.0, "unit": "u"})
    _write_round(d, 2, {"metric": "full_audit_wall_clock_s",
                        "value": 1.1, "unit": "u"})
    _write_round(d, 3, {"metric": "full_audit_wall_clock_s",
                        "value": 1.6, "unit": "u"})  # >25% vs best=1.0
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 1
    assert "full_audit_wall_clock_s" in buf.getvalue()
    # higher-is-better direction flags drops (config series, which
    # carries a unit — the top-level admission_rps COPY is ungated)
    c5 = {"metric": "admission_requests_per_sec", "unit": "rps"}
    _write_round(d, 4, {"metric": "full_audit_wall_clock_s",
                        "value": 1.0, "unit": "u", "admission_rps": 900,
                        "configs": {"5": {**c5, "value": 1000}}})
    _write_round(d, 5, {"metric": "full_audit_wall_clock_s",
                        "value": 1.0, "unit": "u", "admission_rps": 350,
                        "configs": {"5": {**c5, "value": 400}}})
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 1
    # exactly the unit-carrying config series flagged, not the copy
    assert "c5.admission_requests_per_sec" in buf.getvalue()
    assert "**350" not in buf.getvalue()


def test_bench_trend_unit_change_restarts_series(tmp_path):
    import io
    from contextlib import redirect_stdout

    bt = _bench_trend()
    d = str(tmp_path)
    _write_round(d, 1, {"metric": "audit_wall_clock_s", "value": 0.1,
                        "unit": "s (x 1000 objects)"})
    # 10x slower, but at 10x the scale: a series restart, not a
    # regression
    _write_round(d, 2, {"metric": "audit_wall_clock_s", "value": 1.0,
                        "unit": "s (x 10000 objects)"})
    with redirect_stdout(io.StringIO()):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 0
    # same unit, same slowdown: NOW it flags
    _write_round(d, 3, {"metric": "audit_wall_clock_s", "value": 2.0,
                        "unit": "s (x 10000 objects)"})
    with redirect_stdout(io.StringIO()):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 1


def test_bench_trend_error_configs_reported_not_regressed(tmp_path):
    import io
    from contextlib import redirect_stdout

    bt = _bench_trend()
    d = str(tmp_path)
    _write_round(d, 1, {"metric": "full_audit_wall_clock_s",
                        "value": 1.0, "unit": "u", "configs": {
                            "5": {"metric": "admission_requests_per_sec",
                                  "value": 1000, "unit": "rps"}}})
    # config 5 DID NOT RUN in round 2: an error record, not a zero —
    # must be listed as such and must not flag a regression
    _write_round(d, 2, {"metric": "full_audit_wall_clock_s",
                        "value": 1.0, "unit": "u", "configs": {
                            "5": {"config": 5,
                                  "error": "loadgen crashed"}}})
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 0, buf.getvalue()
    out = buf.getvalue()
    assert "Did not run" in out and "loadgen crashed" in out


def test_bench_trend_recovers_truncated_tail():
    """The r05 shape: parsed=null and the headline JSON line truncated
    at the FRONT inside the captured tail — the loader recovers the
    trailing top-level fields instead of dropping the round."""
    bt = _bench_trend()
    doc = bt._recover_fragment(
        'path": "single", "mutate_audit_s": 1.132, "setup_s": 2.8, '
        '"configs": {"3": {"config": 3, "metric": "audit_wall_clock_s", '
        '"value": 7.666, "unit": "s (50000 pods)"}}}')
    assert doc is not None
    assert doc["mutate_audit_s"] == 1.132
    assert doc["configs"]["3"]["value"] == 7.666
    metrics, errors, units = bt.flatten_round(doc)
    assert metrics["c3.audit_wall_clock_s"] == 7.666
    assert units["c3.audit_wall_clock_s"] == "s (50000 pods)"


def test_bench_trend_ended_series_never_gates(tmp_path):
    """A metric whose series ended before the newest round (config
    dropped/renamed) is immutable history — its old final regression
    must not fail every future --check forever."""
    import io
    from contextlib import redirect_stdout

    bt = _bench_trend()
    d = str(tmp_path)
    _write_round(d, 1, {"metric": "audit_wall_clock_s", "value": 1.0,
                        "unit": "u"})
    _write_round(d, 2, {"metric": "audit_wall_clock_s", "value": 2.0,
                        "unit": "u"})  # regressed... in history
    # newest round no longer carries the metric at all
    _write_round(d, 3, {"metric": "other_wall_clock_s", "value": 5.0,
                        "unit": "v"})
    with redirect_stdout(io.StringIO()):
        rc = bt.main(["--dir", d, "--check"])
    assert rc == 0
    # --all-history still SHOWS it in the report
    buf = io.StringIO()
    with redirect_stdout(buf):
        bt.main(["--dir", d, "--all-history"])
    assert "audit_wall_clock_s" in buf.getvalue()


def test_slo_engine_stop_zeroes_burn_gauges():
    from gatekeeper_tpu.control.slo import SloEngine, default_objectives

    reg = _slo_registry()
    eng = SloEngine(default_objectives(availability_target=0.99),
                    registry=reg, sample_interval_s=15)
    eng.sample(now=0.0)
    reg.counter_add("request_count", "c", 10, admission_status="shed")
    eng.export(now=400.0)
    m = re.search(r'gatekeeper_tpu_slo_burn_rate\{slo="availability",'
                  r'window="5m"\} (\S+)', reg.render())
    assert m and float(m.group(1)) > 0
    eng.stop()
    text = reg.render()
    for line in text.splitlines():
        if line.startswith("gatekeeper_tpu_slo_burn_rate"):
            assert line.endswith(" 0"), line
