"""Observability (PR 6 tentpole): end-to-end request tracing, latency
attribution, and the flight recorder.

Covers:
  * W3C traceparent parse/format + stride sampling + the preallocated
    no-op context (an UNSAMPLED request must allocate zero span
    objects on the hot path — asserted via the module allocation
    counter);
  * a traced admission request through 2 real subprocess frontends:
    one trace whose stage spans are complete, ordered, and sum to
    ~wall clock; inbound traceparent honored; X-Trace-Id answered;
    /debug/traces + /debug/templates + /metrics scraped over HTTP and
    validated (what the CI `observability` job boots);
  * audit-plane sweep traces with phase spans + stage histograms;
  * the Registry bucket-skew regression (bounds frozen at first
    registration, mismatch raises);
  * process self-metrics (start time, RSS, FDs, threads, GC);
  * a STRICT text-exposition parse of a loaded Runtime's full /metrics
    output (HELP/TYPE present, +Inf == _count, label escaping).

Every test runs under a hard SIGALRM timeout.
"""

from __future__ import annotations

import http.client
import json
import re
import signal
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control import metrics as gm
from gatekeeper_tpu.control import trace as gt
from gatekeeper_tpu.control.backplane import _StatsAccumulator
from gatekeeper_tpu.control.webhook import (
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
)
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.faults import FAULTS

TARGET = "admission.k8s.gatekeeper.sh"
PER_TEST_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout_and_tracer_reset():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    rate, slow = gt.TRACER.sample_rate, gt.TRACER.slow_threshold_s
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        gt.TRACER.configure(rate, slow)
        gt.TRACER.recorder.clear()
        FAULTS.reset()


def _policy_client():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    return client


def _review(name, labels=None, uid=None):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": "d"}}
    if labels:
        obj["metadata"]["labels"] = labels
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": uid or f"uid-{name}", "operation": "CREATE",
                        "kind": {"group": "", "version": "v1",
                                 "kind": "Pod"},
                        "name": name, "namespace": "d",
                        "userInfo": {"username": "obs"}, "object": obj}}


# ------------------------------------------------------ traceparent + sampling


def test_traceparent_parse_and_format():
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    parsed, sampled = gt.parse_traceparent(
        f"00-{tid}-00f067aa0ba902b7-01")
    assert parsed == tid and sampled is True
    parsed, sampled = gt.parse_traceparent(
        f"00-{tid}-00f067aa0ba902b7-00")
    assert parsed == tid and sampled is False
    # malformed / all-zero never raise, never sample
    for bad in (None, "", "junk", "00-short-x-01", "00-" + "0" * 32
                + "-00f067aa0ba902b7-01", "zz-" + tid + "-gg-01"):
        assert gt.parse_traceparent(bad) == (None, False)
    # STRICT hex ids only: int(x, 16) would accept these, but they
    # would blow up bytes.fromhex when the context rides the backplane
    # frame (regression: 500 + a leaked frontend waiter per request)
    for evil in ("0x" + "a" * 30, "a_" * 16, "+" + "a" * 31,
                 " " + "a" * 31):
        assert gt.parse_traceparent(
            f"00-{evil}-00f067aa0ba902b7-01") == (None, False), evil
    # uppercase ids normalize to lowercase (fromhex-safe either way)
    assert gt.parse_traceparent(
        "00-" + "AB" * 16 + "-00f067aa0ba902b7-01")[0] == "ab" * 16
    hdr = gt.format_traceparent(tid)
    assert gt.parse_traceparent(hdr) == (tid, True)


def test_stride_sampling_and_forced_traceparent():
    tracer = gt.Tracer(sample_rate=0.5, metrics_sink=False)
    kinds = [tracer.start("admission") is gt.NOOP for _ in range(10)]
    assert kinds.count(False) == 5  # every 2nd samples
    tracer.configure(0.0)
    assert tracer.start("admission") is gt.NOOP
    # an inbound sampled traceparent forces tracing past rate 0 AND
    # carries its trace id through
    tid = "ab" * 16
    tr = tracer.start("admission", f"00-{tid}-00f067aa0ba902b7-01")
    assert tr is not gt.NOOP and tr.trace_id == tid
    tr.finish()
    # sample_context (the frontend edge) agrees
    assert tracer.sample_context() is None
    assert tracer.sample_context(f"00-{tid}-00f067aa0ba902b7-01") == tid


def test_unsampled_request_allocates_no_span_objects():
    """The acceptance bar for hot-path cost: with sampling off, a full
    admission round trip through the real HTTP server must not
    construct a single Span/Trace object."""
    gt.TRACER.configure(0.0)
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    server = WebhookServer(handler, NamespaceLabelHandler(()), port=0)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("POST", "/v1/admit",
                     json.dumps(_review("warm", {"owner": "x"})),
                     {"Content-Type": "application/json"})
        conn.getresponse().read()  # warm the path first
        before = gt.ALLOCATIONS
        for i in range(20):
            conn.request("POST", "/v1/admit",
                         json.dumps(_review(f"p{i}", {"owner": "x"})),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            assert resp.getheader("X-Trace-Id") is None
        assert gt.ALLOCATIONS == before, \
            "unsampled requests allocated span objects on the hot path"
    finally:
        server.stop(drain_timeout=1.0)


# ---------------------------------------------------------- flight recorder


def _mk_trace(tracer, duration, plane="admission"):
    tr = tracer.start(plane, force=True)
    tr.add_span("evaluate", tr.t0, tr.t0 + duration)
    tr.t1 = tr.t0 + duration
    return tr


def test_flight_recorder_keeps_recent_and_slowest():
    rec = gt.FlightRecorder(keep=3)
    tracer = gt.Tracer(sample_rate=1.0, recorder=rec,
                       metrics_sink=False, slow_threshold_s=0)
    durations = [0.1, 5.0, 0.2, 4.0, 0.3, 3.0, 0.05]
    for d in durations:
        tr = _mk_trace(tracer, d)
        rec.record(tr)
    dump = rec.dump()["planes"]["admission"]
    assert len(dump["recent"]) == 3 and len(dump["slowest"]) == 3
    # recent = last three, oldest first
    assert [t["duration_s"] for t in dump["recent"]] == [0.3, 3.0, 0.05]
    # slowest = global top three, slowest first — the 5.0s outlier is
    # retained long after it aged out of the recent ring
    assert [t["duration_s"] for t in dump["slowest"]] == [5.0, 4.0, 3.0]
    # per-plane isolation
    rec.record(_mk_trace(tracer, 9.0, plane="audit"))
    planes = rec.dump()["planes"]
    assert planes["audit"]["slowest"][0]["duration_s"] == 9.0
    assert planes["admission"]["slowest"][0]["duration_s"] == 5.0


def test_slow_trace_logs_structured_line(caplog):
    import logging as pylog

    tracer = gt.Tracer(sample_rate=1.0, slow_threshold_s=0.0001,
                       metrics_sink=False)
    with caplog.at_level(pylog.WARNING, logger="gatekeeper.trace"):
        tr = tracer.start("admission", force=True)
        time.sleep(0.002)
        tr.finish()
    assert any("slow request trace" in r.getMessage()
               for r in caplog.records)


# ------------------------------------------------- registry bucket freeze


def test_histogram_buckets_freeze_at_first_registration():
    """Regression for the bucket-skew bug: two call sites passing
    different bounds for the same metric silently mis-bucketed counts
    against stale lists (m.buckets was re-assigned on every observe)."""
    reg = gm.Registry()
    reg.observe("skew_test_seconds", "h", 0.3, buckets=(0.1, 1.0))
    with pytest.raises(ValueError, match="skew_test_seconds"):
        reg.observe("skew_test_seconds", "h", 0.3,
                    buckets=(0.5, 2.0, 10.0))
    # the original bounds survived, counts landed against them
    reg.observe("skew_test_seconds", "h", 0.05, buckets=(0.1, 1.0))
    text = reg.render()
    assert 'skew_test_seconds_bucket{le="0.1"} 1' in text
    assert 'skew_test_seconds_bucket{le="1"} 2' in text
    assert 'le="0.5"' not in text
    # observe_bucketed enforces the same freeze
    with pytest.raises(ValueError):
        reg.observe_bucketed("skew_test_seconds", "h", (9.9,), [1, 0],
                             0.1, 1)


def test_label_values_are_escaped():
    reg = gm.Registry()
    reg.counter_add("esc_total", "c", kind='we"ird\\na\nme')
    text = reg.render()
    assert 'kind="we\\"ird\\\\na\\nme"' in text


# ---------------------------------------------------- process self-metrics


def test_process_self_metrics_exposed():
    reg = gm.Registry()
    gm.update_process_metrics(reg)
    text = reg.render()
    for name in ("process_start_time_seconds",
                 "process_resident_memory_bytes", "process_open_fds",
                 "process_threads", "python_gc_objects_tracked"):
        assert name in text, f"{name} missing from exposition"
    start = float(re.search(
        r"^process_start_time_seconds (\S+)$", text, re.M).group(1))
    assert 0 < start <= time.time()
    rss = float(re.search(
        r"^process_resident_memory_bytes (\S+)$", text, re.M).group(1))
    assert rss > 1 << 20  # a live interpreter holds > 1MiB


# -------------------------------------------------- exposition strict parse


def _parse_exposition_strict(text: str) -> dict:
    """Strict text-format parse: every sample must belong to an
    announced metric family (HELP + TYPE first), histogram +Inf bucket
    must equal _count, label values must round-trip the escaping.
    Returns {family: {"type", "samples": [(name, labels, value)]}}."""
    families: dict = {}
    cur = None
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$")
    label_re = re.compile(
        r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"(,|$)')
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in families, f"duplicate HELP for {name}"
            families[name] = {"type": None, "samples": []}
            cur = name
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            assert parts[2] == cur, "TYPE does not follow its HELP"
            assert families[cur]["type"] is None, "duplicate TYPE"
            assert parts[3] in ("counter", "gauge", "histogram")
            families[cur]["type"] = parts[3]
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, _, labeltext, value = m.groups()
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = name if name in families else base
        assert fam in families, f"sample {name} has no HELP/TYPE"
        assert families[fam]["type"] is not None
        labels = {}
        if labeltext:
            consumed = 0
            for lm in label_re.finditer(labeltext):
                labels[lm.group(1)] = lm.group(2)
                consumed = lm.end()
            assert consumed == len(labeltext), \
                f"bad label syntax: {labeltext!r}"
        float(value)  # must be numeric
        families[fam]["samples"].append((name, labels, float(value)))
    # histogram invariants
    for fam, data in families.items():
        if data["type"] != "histogram":
            continue
        series: dict = {}
        for name, labels, value in data["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            ent = series.setdefault(key, {})
            if name.endswith("_bucket"):
                ent.setdefault("buckets", {})[labels["le"]] = value
            elif name.endswith("_count"):
                ent["count"] = value
            elif name.endswith("_sum"):
                ent["sum"] = value
        for key, ent in series.items():
            assert "+Inf" in ent.get("buckets", {}), \
                f"{fam}{dict(key)} missing +Inf bucket"
            assert ent["buckets"]["+Inf"] == ent["count"], \
                f"{fam}{dict(key)}: +Inf bucket != _count"
            # cumulative buckets must be monotonic
            prev = 0.0
            for le, v in sorted(
                    ent["buckets"].items(),
                    key=lambda kv: float("inf") if kv[0] == "+Inf"
                    else float(kv[0])):
                assert v >= prev, f"{fam}: non-monotonic buckets"
                prev = v
    return families


def test_full_runtime_exposition_parses_strictly():
    """The whole /metrics output of a LOADED runtime — histograms,
    escaped labels, merged bucketed deltas — must satisfy a strict
    text-format parser, so malformed series can never ship again."""
    gt.TRACER.configure(1.0, 10.0)
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    for i in range(5):
        handler.handle(_review(f"ok{i}", {"owner": "me"}))
        handler.handle(_review(f"bad{i}"))
    # a pre-aggregated delta merge (the backplane stats path)
    gm.report_backplane_forward(
        "w0", [1] * (len(gm.FORWARD_BUCKETS) + 1), 0.5,
        len(gm.FORWARD_BUCKETS) + 1)
    gm.report_stage_bucketed(
        "admission", "frontend_parse",
        [2] * (len(gm.STAGE_BUCKETS) + 1), 0.1,
        2 * (len(gm.STAGE_BUCKETS) + 1))
    # a label value that needs escaping
    gm.REGISTRY.counter_add("gatekeeper_tpu_test_escape_total", "t",
                            kind='K8s"Weird\\Kind')
    gm.update_process_metrics()
    families = _parse_exposition_strict(gm.REGISTRY.render())
    assert families["request_duration_seconds"]["type"] == "histogram"
    assert families["gatekeeper_tpu_stage_duration_seconds"]["type"] \
        == "histogram"
    esc = families["gatekeeper_tpu_test_escape_total"]["samples"]
    assert esc[0][1]["kind"] == 'K8s\\"Weird\\\\Kind'
    handler.batcher.stop()


# ----------------------------------------------- single-process trace path


def test_single_process_trace_decomposition_and_header():
    gt.TRACER.configure(1.0, slow_threshold_s=0)
    gt.TRACER.recorder.clear()
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.002))
    server = WebhookServer(handler, None, port=0)
    server.start()
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        conn.request("POST", "/v1/admit",
                     json.dumps(_review("t1", {"owner": "x"})),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Trace-Id") == tid, \
            "inbound traceparent trace id not honored"
        dump = gt.TRACER.recorder.dump()["planes"]["admission"]
        trace = next(t for t in dump["recent"] if t["trace_id"] == tid)
        stages = [s["stage"] for s in trace["spans"]]
        for want in ("frontend_parse", "batch_seal", "evaluate",
                     "serialize"):
            assert want in stages, f"stage {want} missing: {stages}"
        assert trace["status"] == "allow"
    finally:
        server.stop(drain_timeout=1.0)


def test_cache_hit_stage_replaces_evaluate():
    gt.TRACER.configure(1.0, slow_threshold_s=0)
    gt.TRACER.recorder.clear()
    client = _policy_client()
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    handler.handle(_review("same", {"owner": "x"}, uid="u1"))
    tr = gt.TRACER.start(gt.ADMISSION, force=True)
    handler.handle(_review("same", {"owner": "x"}, uid="u2"), trace=tr)
    tr.finish()
    stages = [s["stage"] for s in tr.to_dict()["spans"]]
    assert "cache_hit" in stages and "evaluate" not in stages
    handler.batcher.stop()


# ------------------------------------------------- backplane stats plumbing


def test_stats_accumulator_ships_stage_deltas():
    acc = _StatsAccumulator()
    acc.observe(0.001)
    acc.observe_stage("frontend_parse", 0.0002)
    acc.observe_stage("frontend_parse", 0.3)
    acc.observe_stage("some_other_stage", 0.001)
    out = acc.drain("w3")
    assert out["count"] == 1
    stages = out["stages"]
    assert stages["frontend_parse"]["count"] == 2
    assert abs(stages["frontend_parse"]["sum"] - 0.3002) < 1e-6
    assert sum(stages["frontend_parse"]["buckets"]) == 2
    assert stages["some_other_stage"]["count"] == 1
    # drained clean
    assert acc.drain("w3") is None


# --------------------------------------------------------- audit plane trace


def test_audit_sweep_trace_phases_and_histograms():
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    gt.TRACER.recorder.clear()
    kube = FakeKube()
    for gvk, namespaced in [(("", "v1", "Namespace"), False),
                            (("", "v1", "Pod"), True)]:
        kube.register_kind(gvk, namespaced=namespaced)
    kube.create({"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": "d"}})
    for i in range(4):
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "namespace": "d"}})
    client = _policy_client()
    mgr = AuditManager(kube, client, incremental=True,
                       gc_stale_statuses=False)
    mgr.audit_once()
    mgr.audit_once()  # one incremental sweep too
    mgr.stop()
    dump = gt.TRACER.recorder.dump()["planes"]
    assert "audit" in dump, "audit sweeps must always trace"
    stages_seen = set()
    for t in dump["audit"]["recent"]:
        stages_seen.update(s["stage"] for s in t["spans"])
    for want in ("list_delta_apply", "evaluate", "status_writes"):
        assert want in stages_seen
    statuses = {t["status"] for t in dump["audit"]["recent"]}
    assert {"full_resync", "incremental"} <= statuses
    text = gm.REGISTRY.render()
    assert 'gatekeeper_tpu_stage_duration_seconds_count' \
        '{engine="",plane="audit",stage="evaluate"}' in text


def test_failed_sweep_still_records_error_trace():
    """A sweep that blows up mid-evaluation must still land in the
    flight recorder with status=error — the failing sweeps are exactly
    the ones worth diagnosing after the fact."""
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    gt.TRACER.recorder.clear()
    client = _policy_client()

    def boom():
        raise RuntimeError("device on fire")

    client.audit = boom
    mgr = AuditManager(FakeKube(), client, audit_from_cache=True,
                       gc_stale_statuses=False)
    with pytest.raises(RuntimeError):
        mgr.audit_once()
    dump = gt.TRACER.recorder.dump()["planes"]["audit"]["recent"]
    assert dump and dump[-1]["status"] == "error"
    assert "device on fire" in dump[-1]["attrs"]["error"]


# ------------------------------------- full plane: subprocess frontends


def _get(conn_host, port, path):
    conn = http.client.HTTPConnection(conn_host, port, timeout=10)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_traced_request_through_subprocess_frontends():
    """The acceptance path: a Runtime with 2 pre-forked frontend
    PROCESSES at sample rate 1.0 — a traced request yields ONE trace
    whose stage spans are complete, ordered, and sum to ~wall clock;
    /metrics and /debug/* validate over real HTTP."""
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--trace-sample-rate", "1.0", "--trace-slow-threshold", "0"])
    rt = Runtime(args)
    rt.start()
    # load a real template so the traced request evaluates something
    # and /debug/templates has per-kind state to report
    rt.opa.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]}})
    rt.opa.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    tid = "aabbccddeeff00112233445566778899"
    try:
        deadline = time.monotonic() + 10
        while rt.backplane.connected < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rt.backplane.connected == 2
        mport = rt.metrics_server.server_address[1]
        hport = rt.health.port
        conn = http.client.HTTPConnection("127.0.0.1", rt.frontends.port,
                                          timeout=15)
        conn.request("POST", "/v1/admit?timeout=10s",
                     json.dumps(_review("traced")),
                     {"Content-Type": "application/json",
                      "traceparent": f"00-{tid}-00f067aa0ba902b7-01"})
        resp = conn.getresponse()
        resp.read()
        assert resp.getheader("X-Trace-Id") == tid
        # the engine records the trace at respond time; poll the dump
        trace = None
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            status, body = _get("127.0.0.1", mport, "/debug/traces")
            assert status == 200
            planes = json.loads(body).get("planes", {})
            for t in planes.get("admission", {}).get("recent", []):
                if t["trace_id"] == tid:
                    trace = t
                    break
            if trace:
                break
            time.sleep(0.1)
        assert trace is not None, "traced request never reached the " \
            "flight recorder"
        stages = [s["stage"] for s in trace["spans"]]
        # >= 5 named stages spanning frontend -> backplane -> engine ->
        # eval path
        for want in ("frontend_parse", "backplane_forward",
                     "batch_seal", "evaluate", "serialize", "respond"):
            assert want in stages, f"{want} missing from {stages}"
        assert len(stages) >= 5
        # complete + ordered: spans start in order, live inside the
        # trace window, and sum to ~wall clock (sequential stages;
        # small gaps for untimed glue are expected)
        starts = [s["start_s"] for s in trace["spans"]]
        assert starts == sorted(starts), "stage spans out of order"
        total = trace["duration_s"]
        span_sum = sum(s["duration_s"] for s in trace["spans"])
        assert all(0 <= s["start_s"] <= total + 1e-6
                   for s in trace["spans"])
        assert span_sum <= total * 1.10 + 1e-4
        assert span_sum >= total * 0.5, \
            f"spans cover too little of the trace: {span_sum} / {total}"
        # a second, uid-churned request serves from the decision cache
        # and still decomposes (cache_hit path)
        conn.request("POST", "/v1/admit?timeout=10s",
                     json.dumps(_review("traced", uid="uid-2")),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        tid2 = resp.getheader("X-Trace-Id")
        assert tid2 and tid2 != tid
        # /metrics: engine-side stages appear immediately; the
        # frontend-shipped stage deltas land within one S-frame
        # interval (2s)
        deadline = time.monotonic() + 8
        text = ""
        while time.monotonic() < deadline:
            status, body = _get("127.0.0.1", mport, "/metrics")
            assert status == 200
            text = body.decode()
            if ('stage="frontend_parse"' in text
                    and 'stage="evaluate"' in text):
                break
            time.sleep(0.2)
        for frag in ('plane="admission"', 'stage="evaluate"',
                     'stage="frontend_parse"', 'stage="respond"',
                     "gatekeeper_tpu_traces_total",
                     "process_resident_memory_bytes"):
            assert frag in text, f"{frag} missing from /metrics"
        _parse_exposition_strict(text)
        # /debug/templates on the metrics port, /debug/traces on the
        # health port (same registry), unknown endpoints 404
        status, body = _get("127.0.0.1", mport, "/debug/templates")
        assert status == 200
        tmpl = json.loads(body)
        assert "K8sNeedOwner" in tmpl["templates"]
        status, _ = _get("127.0.0.1", hport, "/debug/traces")
        assert status == 200
        status, body = _get("127.0.0.1", mport, "/debug/nope")
        assert status == 404
        assert "available" in json.loads(body)
    finally:
        rt.stop()
