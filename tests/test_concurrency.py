"""Concurrency + REST-path coverage (VERDICT r2 weak #7/#8).

The control plane is threaded everywhere (micro-batcher flusher, watch
fan-out, audit loop, cert refresh) but was only tested single-threaded
happy-path; and RestKubeClient had zero coverage (everything ran on
FakeKube). These tests drive:
  * RestKubeClient end-to-end against a stub apiserver (discovery, CRUD,
    conflict/apply, not-found, poll-watch event diffing);
  * MicroBatcher under concurrent submitters with per-request verdicts;
  * WatchManager add/remove/replace races across threads;
  * AuditManager sweeps overlapping constraint churn.
"""

from __future__ import annotations

import http.server
import json
import queue
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.kube import (
    Conflict,
    FakeKube,
    NotFound,
    RestKubeClient,
    WatchEvent,
)
from gatekeeper_tpu.control.watch import Registrar, WatchManager
from gatekeeper_tpu.control.webhook import MicroBatcher
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"


# ----------------------------------------------------- stub apiserver


class _StubApi(http.server.BaseHTTPRequestHandler):
    """Just enough apiserver: /api/v1 discovery + namespaced pod CRUD +
    streaming watch (?watch=1, newline-delimited JSON frames fed from a
    per-server event queue) so RestKubeClient's informer path is
    exercised against real chunked HTTP."""

    store: dict  # {(ns, name): obj}; assigned per-instance via class attr
    rv = [1]
    watch_events: "queue.Queue"  # frames the test script injects
    watch_open = [0]             # observability: open watch streams

    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _send(self, code: int, body):
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _split(self):
        path, _, query = self.path.partition("?")
        q = dict(p.split("=", 1) for p in query.split("&") if "=" in p)
        return path, q

    def _pod_path(self):
        # /api/v1/namespaces/<ns>/pods[/<name>]
        path, _q = self._split()
        parts = path.strip("/").split("/")
        if len(parts) >= 5 and parts[2] == "namespaces" and \
                parts[4] == "pods":
            name = parts[5] if len(parts) > 5 else None
            return parts[3], name
        if len(parts) >= 3 and parts[2] == "pods":
            return None, (parts[3] if len(parts) > 3 else None)
        return None, None

    def _serve_watch(self):
        """Chunked newline-delimited frames from the queue until the
        test posts the sentinel None (closes the stream)."""
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self.watch_open[0] += 1

        def chunk(data: bytes):
            self.wfile.write(f"{len(data):x}\r\n".encode())
            self.wfile.write(data + b"\r\n")
            self.wfile.flush()

        try:
            while True:
                ev = self.watch_events.get(timeout=30)
                if ev is None:
                    break
                chunk((json.dumps(ev) + "\n").encode())
            self.wfile.write(b"0\r\n\r\n")
        except (queue.Empty, BrokenPipeError, ConnectionError):
            pass
        finally:
            self.watch_open[0] -= 1

    def do_GET(self):
        path, q = self._split()
        if path == "/api/v1":
            self._send(200, {"resources": [
                {"name": "pods", "kind": "Pod", "namespaced": True},
                {"name": "pods/status", "kind": "Pod", "namespaced": True},
            ]})
            return
        if path == "/apis":
            self._send(200, {"groups": []})
            return
        if q.get("watch") == "1":
            self._serve_watch()
            return
        ns, name = self._pod_path()
        if name is not None:
            obj = self.store.get((ns, name))
            if obj is None:
                self._send(404, {"message": "not found"})
            else:
                self._send(200, obj)
            return
        items = [o for (o_ns, _), o in sorted(self.store.items())
                 if ns is None or o_ns == ns]
        self._send(200, {"kind": "PodList", "items": items,
                         "metadata": {"resourceVersion":
                                      str(self.rv[0])}})

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])))
        ns = (body.get("metadata") or {}).get("namespace") or ""
        name = (body.get("metadata") or {}).get("name")
        if (ns, name) in self.store:
            self._send(409, {"message": "exists"})
            return
        self.rv[0] += 1
        body.setdefault("metadata", {})["resourceVersion"] = str(self.rv[0])
        self.store[(ns, name)] = body
        self.watch_events.put({"type": "ADDED", "object": body})
        self._send(201, body)

    def do_PUT(self):
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])))
        ns, name = self._pod_path()
        cur = self.store.get((ns, name))
        if cur is None:
            self._send(404, {"message": "not found"})
            return
        sent_rv = (body.get("metadata") or {}).get("resourceVersion")
        if sent_rv != cur["metadata"]["resourceVersion"]:
            self._send(409, {"message": "conflict"})
            return
        self.rv[0] += 1
        body["metadata"]["resourceVersion"] = str(self.rv[0])
        self.store[(ns, name)] = body
        self.watch_events.put({"type": "MODIFIED", "object": body})
        self._send(200, body)

    def do_DELETE(self):
        ns, name = self._pod_path()
        gone = self.store.pop((ns, name), None)
        if gone is None:
            self._send(404, {"message": "not found"})
        else:
            self.watch_events.put({"type": "DELETED", "object": gone})
            self._send(200, {})


@pytest.fixture
def stub_api():
    handler = type("H", (_StubApi,), {"store": {}, "rv": [1],
                                      "watch_events": queue.Queue(),
                                      "watch_open": [0]})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    srv.daemon_threads = True  # watch handlers block on the frame queue
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    client = RestKubeClient(base_url=f"http://127.0.0.1:{srv.server_port}",
                            token="test-token")
    try:
        yield client, handler
    finally:
        for _ in range(4):  # unblock any open watch streams
            handler.watch_events.put(None)
        srv.shutdown()


POD_GVK = ("", "v1", "Pod")


def pod(name, ns="d", labels=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {}}


def test_rest_client_crud_and_discovery(stub_api):
    kube, handler = stub_api
    created = kube.create(pod("a"))
    assert created["metadata"]["resourceVersion"]
    assert kube.get(POD_GVK, "a", "d")["metadata"]["name"] == "a"
    with pytest.raises(NotFound):
        kube.get(POD_GVK, "missing", "d")
    with pytest.raises(Conflict):
        kube.create(pod("a"))
    # apply: create-conflict -> get + update with current resourceVersion
    updated = kube.apply(pod("a", labels={"x": "y"}))
    assert updated["metadata"]["labels"] == {"x": "y"}
    kube.create(pod("b"))
    names = sorted(o["metadata"]["name"] for o in kube.list(POD_GVK, "d"))
    assert names == ["a", "b"]
    # list() fills apiVersion/kind for unstructured consumers
    assert all(o["kind"] == "Pod" for o in kube.list(POD_GVK, "d"))
    kube.delete(POD_GVK, "b", "d")
    assert [o["metadata"]["name"] for o in kube.list(POD_GVK, "d")] == ["a"]
    # stale-resourceVersion update surfaces Conflict
    stale = kube.get(POD_GVK, "a", "d")
    kube.apply(pod("a", labels={"v": "2"}))
    with pytest.raises(Conflict):
        kube.update(stale)


def test_rest_client_watch_streams_mutations(stub_api):
    kube, handler = stub_api
    kube.create(pod("w1"))
    events: list[WatchEvent] = []
    got_initial = threading.Event()

    def cb(ev):
        events.append(ev)
        got_initial.set()

    cancel = kube.watch(POD_GVK, cb)
    try:
        assert got_initial.wait(5)
        assert events[0].type == "ADDED"
        assert events[0].object["metadata"]["name"] == "w1"
        kube.create(pod("w2"))
        kube.delete(POD_GVK, "w1", "d")
        deadline = time.time() + 8
        while time.time() < deadline:
            types = {(e.type, e.object["metadata"]["name"]) for e in events}
            if ("ADDED", "w2") in types and ("DELETED", "w1") in types:
                break
            time.sleep(0.2)
        types = {(e.type, e.object["metadata"]["name"]) for e in events}
        assert ("ADDED", "w2") in types and ("DELETED", "w1") in types
    finally:
        cancel()
        handler.watch_events.put(None)


# ------------------------------------------------- micro-batcher stress


def test_microbatcher_concurrent_submitters():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "c"}, "spec": {}})
    batcher = MicroBatcher(client, max_wait=0.002, max_batch=64)
    errs: list = []

    def review(i, labeled):
        labels = {"owner": "me"} if labeled else {}
        return {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": f"p{i}", "namespace": "d", "operation": "CREATE",
                "object": {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": f"p{i}", "namespace": "d",
                                        "labels": labels}}}

    def worker(w):
        try:
            for j in range(40):
                i = w * 100 + j
                labeled = (i % 3 == 0)
                results = batcher.submit(review(i, labeled))
                want = 0 if labeled else 1
                assert len(results) == want, (i, labeled, results)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batcher.stop()
    assert not errs, errs[:3]
    assert batcher.batched_requests == 8 * 40
    assert batcher.batches < 8 * 40  # batching actually happened


def test_microbatcher_deadline_skew_orders_batches():
    """Satellite: mixed 1s/5s/30s timeoutSeconds in one burst — tight-
    deadline requests seal into earlier batches (answered first) and
    NO request is answered after its propagated deadline.

    The burst is injected atomically under the batcher's lock: the
    deadline sort only orders what is queued at seal time, so a
    thread-per-request burst races the collector's full-batch seal and
    a loose request can slip into batch 1 before the tight ones are
    even enqueued. That race is inherent to concurrent arrival, not
    the ordering property under test."""
    from gatekeeper_tpu.control.webhook import _Pending

    flushed: list[list[int]] = []

    def evaluate(reviews):
        flushed.append([r["i"] for r in reviews])
        time.sleep(0.05)  # each flush costs a fixed slice of the budget
        return [[] for _ in reviews]

    batcher = MicroBatcher(None, max_wait=0.05, max_batch=4,
                           evaluate=evaluate)
    # 4 of each class, enqueued loose-first so only the deadline sort
    # (not arrival order) can produce the expected batching
    now = time.monotonic()
    budgets = [30.0] * 4 + [5.0] * 4 + [1.0] * 4
    pend = [_Pending({"i": i}, now + b) for i, b in enumerate(budgets)]
    try:
        with batcher._cv:
            batcher._pending += len(pend)
            batcher._queue.extend(pend)
            batcher._cv.notify()
        deadline_by_i = {i: p.deadline for i, p in enumerate(pend)}
        for i, p in enumerate(pend):
            assert p.done.wait(20), f"request {i} unanswered"
            assert p.error is None, f"request {i} failed: {p.error!r}"
            # answered before its propagated deadline
            assert time.monotonic() <= deadline_by_i[i], \
                f"request {i} answered after expiry"
    finally:
        batcher.stop()
    # deadline-ordered sealing: the 1s class seals (and therefore
    # flushes) first, the 30s class last; the stable sort keeps
    # arrival order within each equal-deadline class
    assert flushed == [[8, 9, 10, 11], [4, 5, 6, 7], [0, 1, 2, 3]], \
        f"tight deadlines were not sealed first: {flushed}"


# ----------------------------------------------- watch manager races


def test_watch_manager_add_remove_races():
    kube = FakeKube()
    gvks = [("", "v1", k) for k in
            ("Pod", "Service", "ConfigMap", "Secret")]
    for g in gvks:
        kube.register_kind(g)
        kube.create({"apiVersion": "v1", "kind": g[2],
                     "metadata": {"name": "seed", "namespace": "d"}})
    wm = WatchManager(kube)
    errs: list = []
    stop = threading.Event()

    def churn(seed):
        reg = Registrar(f"r{seed}", wm)
        try:
            k = 0
            while not stop.is_set():
                g = gvks[(seed + k) % len(gvks)]
                reg.add_watch(g)
                reg.replace_watches([gvks[(seed + k + 1) % len(gvks)]])
                reg.remove_watch(gvks[(seed + k + 1) % len(gvks)])
                k += 1
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def producer():
        i = 0
        try:
            while not stop.is_set():
                kube.create({"apiVersion": "v1", "kind": "Pod",
                             "metadata": {"name": f"p{i}",
                                          "namespace": "d"}})
                i += 1
                time.sleep(0.001)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=churn, args=(s,)) for s in range(6)]
    threads.append(threading.Thread(target=producer))
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errs, errs[:3]
    # every registrar released its refs: no leaked live watches with zero
    # registrars keeping their caches warm forever
    for gvk, rec in wm._records.items():
        assert rec.cancel is None or rec.registrars, gvk


# ------------------------------------------------- audit loop overlap


def test_audit_sweeps_overlap_constraint_churn():
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    kube = FakeKube()
    kube.register_kind(("constraints.gatekeeper.sh", "v1beta1", "K8sNeed"),
                       namespaced=False)
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneed"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeed"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneed
violation[{"msg": "always"}] { input.review.object.metadata.name }
"""}]},
    })
    for i in range(10):
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": f"n{i}"}})
    mgr = AuditManager(kube, client, interval=0.05)
    errs: list = []
    stop = threading.Event()

    def churn():
        i = 0
        try:
            while not stop.is_set():
                con = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                       "kind": "K8sNeed",
                       "metadata": {"name": f"c{i % 3}"}, "spec": {}}
                client.add_constraint(con)
                kube.apply(con)
                if i % 4 == 3:
                    client.remove_constraint(con)
                    try:
                        kube.delete(("constraints.gatekeeper.sh", "v1beta1",
                                     "K8sNeed"), f"c{i % 3}")
                    except Exception:
                        pass
                i += 1
                time.sleep(0.01)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    mgr.start()
    t = threading.Thread(target=churn)
    t.start()
    time.sleep(1.0)
    stop.set()
    t.join(5)
    mgr.stop()
    assert not errs, errs[:3]


def test_runtime_soak_under_concurrent_churn():
    """Control-plane soak: live webhook traffic over HTTP while
    templates/constraints/data churn and the audit loop sweeps — no
    exceptions, no deadlocks, and admission answers stay consistent
    with the currently-installed policy at quiescence."""
    import http.client
    import json as pyjson
    import threading
    import time

    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--audit-interval", "0.2",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8srequiredlabels"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sRequiredLabels"}}},
                 "targets": [{"target": "admission.k8s.gatekeeper.sh",
                              "rego": """
package k8srequiredlabels
violation[{"msg": msg}] {
  required := {k | k := input.parameters.labels[_]}
  provided := {k | input.review.object.metadata.labels[k]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("missing labels: %v", [missing])
}
"""}]},
    }
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "soak"},
        "spec": {"parameters": {"labels": ["owner"]}},
    }
    errors: list = []
    stop = threading.Event()

    def review(name, labels):
        o = {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": name}}
        if labels:
            o["metadata"]["labels"] = labels
        return {"apiVersion": "admission.k8s.io/v1beta1",
                "kind": "AdmissionReview",
                "request": {"uid": "u", "operation": "CREATE",
                            "kind": {"group": "", "version": "v1",
                                     "kind": "Namespace"},
                            "name": name,
                            "userInfo": {"username": "soak"},
                            "object": o}}

    def traffic(k):
        i = 0
        while not stop.is_set():
            try:
                conn = http.client.HTTPConnection("127.0.0.1",
                                                  rt.webhook.port,
                                                  timeout=10)
                labels = {"owner": "x"} if i % 2 else None
                conn.request("POST", "/v1/admit",
                             pyjson.dumps(review(f"t{k}-{i}", labels)),
                             {"Content-Type": "application/json"})
                resp = pyjson.loads(conn.getresponse().read())
                assert "response" in resp
                i += 1
            except Exception as e:  # pragma: no cover - fail the soak
                errors.append(e)
                return

    def churn():
        i = 0
        while not stop.is_set():
            try:
                if i % 7 == 0:
                    rt.kube.apply(template)
                if i % 3 == 0:
                    rt.kube.apply(constraint)
                elif i % 3 == 1:
                    try:
                        rt.kube.delete(("constraints.gatekeeper.sh",
                                        "v1beta1", "K8sRequiredLabels"),
                                       "soak")
                    except Exception:
                        pass
                rt.kube.create({"apiVersion": "v1", "kind": "Namespace",
                                "metadata": {"name": f"churn-{i}"}})
                rt.manager.drain()
                i += 1
                time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    rt.kube.create(template)
    rt.manager.drain()
    rt.kube.create(constraint)
    rt.manager.drain()
    threads = [threading.Thread(target=traffic, args=(k,))
               for k in range(4)] + [threading.Thread(target=churn)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=15)
        assert not t.is_alive(), "soak thread wedged"
    assert not errors, errors[:3]
    # quiescent consistency: reinstall the constraint; a bad namespace
    # must be denied again through the full HTTP path
    rt.kube.apply(template)
    rt.manager.drain()
    rt.kube.apply(constraint)
    rt.manager.drain()
    conn = http.client.HTTPConnection("127.0.0.1", rt.webhook.port,
                                      timeout=10)
    conn.request("POST", "/v1/admit", pyjson.dumps(review("final", None)),
                 {"Content-Type": "application/json"})
    out = pyjson.loads(conn.getresponse().read())
    assert out["response"]["allowed"] is False
    rt.stop()


def test_multi_worker_serving_plane_open_loop_burst():
    """Serving-plane e2e: 3 pre-forked frontend PROCESSES over one
    SO_REUSEPORT port forward an open-loop burst over the backplane to
    one in-process engine. Asserts: zero unanswered admissions, every
    verdict correct and carrying its request's uid, every answer lands
    before its propagated 2s deadline (the API server's give-up point),
    and cross-worker micro-batching actually happened."""
    import http.client as hc

    from gatekeeper_tpu.control.backplane import (
        BackplaneEngine,
        FrontendSupervisor,
        default_socket_path,
    )
    from gatekeeper_tpu.control.webhook import ValidationHandler

    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "c"}, "spec": {}})
    batcher = MicroBatcher(client, max_wait=0.003, max_batch=64)
    # cache off: every request must ride the full backplane+batcher path
    validation = ValidationHandler(client, kube=None, batcher=batcher,
                                   decision_cache_size=0)
    sock = default_socket_path() + ".mw"
    engine = BackplaneEngine(sock, validation=validation)
    engine.start()
    super_ = FrontendSupervisor(3, sock, port=0, addr="127.0.0.1")
    super_.start()
    n = 150
    results: dict[int, tuple] = {}
    errors: list = []
    lock = threading.Lock()

    def review(i, labeled):
        labels = {"owner": "me"} if labeled else {}
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": f"u{i}", "operation": "CREATE",
                            "kind": {"group": "", "version": "v1",
                                     "kind": "Pod"},
                            "name": f"p{i}", "namespace": "d",
                            "userInfo": {"username": "burst"},
                            "object": {"apiVersion": "v1", "kind": "Pod",
                                       "metadata": {
                                           "name": f"p{i}",
                                           "namespace": "d",
                                           "labels": labels}}}}

    def fire(i):
        t_send = time.monotonic()
        try:
            conn = hc.HTTPConnection("127.0.0.1", super_.port, timeout=10)
            conn.request("POST", "/v1/admit?timeout=2s",
                         json.dumps(review(i, i % 3 == 0)),
                         {"Content-Type": "application/json"})
            out = json.loads(conn.getresponse().read())
            conn.close()
            with lock:
                results[i] = (time.monotonic() - t_send,
                              out["response"])
        except Exception as e:  # noqa: BLE001 - any drop fails the test
            with lock:
                errors.append((i, e))

    try:
        # open loop: all arrivals scheduled up front, no waiting on
        # responses — the plane absorbs the whole burst at once
        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors, errors[:3]
        assert len(results) == n, "unanswered admissions"
        for i, (elapsed, resp) in results.items():
            assert resp["uid"] == f"u{i}"
            # deadline honored THROUGH the backplane: answered before
            # the API server's 2s give-up (either a real verdict or a
            # stance answer, never silence past the budget)
            assert elapsed < 2.0, f"request {i} answered after deadline"
            if "status" not in resp or resp["status"].get("code") == 403:
                assert resp["allowed"] is (i % 3 == 0), (i, resp)
        # requests from 3 separate frontend processes coalesced into
        # shared micro-batches on the one engine
        assert batcher.batched_requests >= n
        assert batcher.batches < batcher.batched_requests, \
            "no cross-worker batching happened"
    finally:
        super_.stop()
        engine.stop(drain_timeout=2.0)


def test_rest_client_streaming_watch(stub_api):
    """RestKubeClient.watch consumes a chunked ?watch=1 stream: initial
    list sync, then ADDED/MODIFIED/DELETED frames, BOOKMARK advancing
    the resourceVersion silently, and a 410 Gone frame forcing a
    backoff-relist that reconciles state changed behind the stream."""
    kube, handler = stub_api
    kube.create(pod("pre"))
    # the stub has no resourceVersion filtering: drop frames emitted
    # before the watch opened (a real apiserver would not replay them
    # past the list RV)
    while not handler.watch_events.empty():
        handler.watch_events.get()
    events: "queue.Queue" = queue.Queue()
    cancel = kube.watch(POD_GVK, events.put, send_initial=True)
    try:
        ev = events.get(timeout=10)
        assert ev.type == "ADDED"
        assert ev.object["metadata"]["name"] == "pre"

        # streamed frames (not poll diffs): inject through the queue
        handler.watch_events.put({
            "type": "ADDED",
            "object": pod("live-1") | {"metadata": {
                "name": "live-1", "namespace": "d",
                "resourceVersion": "50"}}})
        ev = events.get(timeout=10)
        assert (ev.type, ev.object["metadata"]["name"]) == \
            ("ADDED", "live-1")
        # the stream fills apiVersion/kind like list() does
        assert ev.object["kind"] == "Pod"

        handler.watch_events.put({
            "type": "BOOKMARK",
            "object": {"metadata": {"resourceVersion": "60"}}})
        handler.watch_events.put({
            "type": "MODIFIED",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "live-1", "namespace": "d",
                                    "resourceVersion": "61",
                                    "labels": {"x": "y"}}}})
        ev = events.get(timeout=10)
        assert (ev.type, ev.object["metadata"]["labels"]) == \
            ("MODIFIED", {"x": "y"})

        handler.watch_events.put({
            "type": "DELETED",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "live-1", "namespace": "d",
                                    "resourceVersion": "62"}}})
        ev = events.get(timeout=10)
        assert (ev.type, ev.object["metadata"]["name"]) == \
            ("DELETED", "live-1")

        # 410 Gone: the client must relist and surface the object that
        # appeared while its resourceVersion was expired
        kube.create(pod("appeared-during-gap"))
        handler.watch_events.put({
            "type": "ERROR",
            "object": {"kind": "Status", "code": 410,
                       "message": "too old resource version"}})
        ev = events.get(timeout=10)
        assert (ev.type, ev.object["metadata"]["name"]) == \
            ("ADDED", "appeared-during-gap")
    finally:
        cancel()
        handler.watch_events.put(None)


def test_rest_client_watch_reconnects_on_clean_close(stub_api):
    """A server-side timeout close (clean chunked EOF) must reconnect
    and keep streaming without a relist-diff storm."""
    kube, handler = stub_api
    events: "queue.Queue" = queue.Queue()
    cancel = kube.watch(POD_GVK, events.put, send_initial=False)
    try:
        deadline = time.time() + 10
        while handler.watch_open[0] < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert handler.watch_open[0] == 1
        handler.watch_events.put(None)  # server closes the stream
        # the client reconnects: a fresh stream opens and delivers
        deadline = time.time() + 10
        while handler.watch_open[0] < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert handler.watch_open[0] == 1, "no reconnect after close"
        handler.watch_events.put({
            "type": "ADDED",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "after-reconnect",
                                    "namespace": "d",
                                    "resourceVersion": "70"}}})
        ev = events.get(timeout=10)
        assert ev.object["metadata"]["name"] == "after-reconnect"
    finally:
        cancel()
        handler.watch_events.put(None)


def test_rest_client_list_pagination(stub_api):
    """list() follows continue tokens."""
    kube, handler = stub_api
    kube.LIST_PAGE_LIMIT = 2
    for i in range(5):
        kube.create(pod(f"p{i}"))
    # the stub ignores limit/continue (returns everything once), which
    # exercises the no-continue exit; a paging stub asserts the tokens
    pages = []

    class PagingStub:
        def __init__(self, items):
            self.items = items
            self.calls = []

        def __call__(self, method, path):
            self.calls.append(path)
            q = dict(p.split("=", 1)
                     for p in path.partition("?")[2].split("&")
                     if "=" in p)
            start = int(q.get("continue") or 0)
            limit = int(q["limit"])
            page = self.items[start:start + limit]
            meta = {"resourceVersion": "9"}
            if start + limit < len(self.items):
                meta["continue"] = str(start + limit)
            return {"items": page, "metadata": meta}

    stub = PagingStub([pod(f"x{i}") for i in range(5)])
    orig = kube._request
    kube._request = lambda m, p, body=None: stub(m, p)
    try:
        items, rv = kube._list_paged(POD_GVK)
    finally:
        kube._request = orig
    assert [o["metadata"]["name"] for o in items] == \
        [f"x{i}" for i in range(5)]
    assert rv == "9"
    assert len(stub.calls) == 3, stub.calls


def test_rest_client_kubeconfig(tmp_path):
    """Out-of-cluster auth from a kubeconfig file: server, inline CA
    data, and user token."""
    import base64
    import textwrap

    pytest.importorskip("cryptography")
    from gatekeeper_tpu.control.certs import _pem_cert, generate_ca

    _, ca = generate_ca()
    ca_b64 = base64.b64encode(_pem_cert(ca)).decode()
    cfg = tmp_path / "config"
    cfg.write_text(textwrap.dedent(f"""
        apiVersion: v1
        kind: Config
        current-context: test
        contexts:
        - name: test
          context:
            cluster: c1
            user: u1
        clusters:
        - name: c1
          cluster:
            server: https://10.9.8.7:6443
            certificate-authority-data: {ca_b64}
        users:
        - name: u1
          user:
            token: kubeconfig-token
    """))
    kube = RestKubeClient(kubeconfig=str(cfg))
    assert kube.base_url == "https://10.9.8.7:6443"
    assert kube.token == "kubeconfig-token"


def _minimal_kubeconfig(path, server, token):
    import textwrap

    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(f"""
        apiVersion: v1
        kind: Config
        current-context: test
        contexts:
        - name: test
          context:
            cluster: c1
            user: u1
        clusters:
        - name: c1
          cluster:
            server: {server}
        users:
        - name: u1
          user:
            token: {token}
    """))


def test_rest_client_config_precedence(tmp_path, monkeypatch):
    """In-cluster service account wins over the implicit ~/.kube/config
    default; an EXPLICIT kubeconfig (argument or $KUBECONFIG) wins over
    the in-cluster account unconditionally."""
    home = tmp_path / "home"
    _minimal_kubeconfig(home / ".kube" / "config",
                        "https://from-home:6443", "home-token")
    explicit = tmp_path / "explicit-config"
    _minimal_kubeconfig(explicit, "https://from-explicit:6443",
                        "explicit-token")
    sa = tmp_path / "sa"
    sa.mkdir()
    (sa / "token").write_text("sa-token")
    monkeypatch.setenv("HOME", str(home))
    monkeypatch.delenv("KUBECONFIG", raising=False)
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.11.12.13")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    monkeypatch.setattr(RestKubeClient, "SA_DIR", str(sa))

    # 1. in-cluster SA beats the implicit ~/.kube/config
    kube = RestKubeClient()
    assert kube.token == "sa-token"
    assert kube.base_url == "https://10.11.12.13:443"

    # 2. explicit kubeconfig argument beats the in-cluster account
    kube = RestKubeClient(kubeconfig=str(explicit))
    assert kube.token == "explicit-token"
    assert kube.base_url == "https://from-explicit:6443"

    # 3. $KUBECONFIG beats the in-cluster account too
    monkeypatch.setenv("KUBECONFIG", str(explicit))
    kube = RestKubeClient()
    assert kube.token == "explicit-token"
    assert kube.base_url == "https://from-explicit:6443"

    # 4. no in-cluster SA: the implicit ~/.kube/config applies again
    monkeypatch.delenv("KUBECONFIG", raising=False)
    monkeypatch.setattr(RestKubeClient, "SA_DIR", str(tmp_path / "absent"))
    kube = RestKubeClient()
    assert kube.token == "home-token"
    assert kube.base_url == "https://from-home:6443"
