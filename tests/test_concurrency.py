"""Concurrency + REST-path coverage (VERDICT r2 weak #7/#8).

The control plane is threaded everywhere (micro-batcher flusher, watch
fan-out, audit loop, cert refresh) but was only tested single-threaded
happy-path; and RestKubeClient had zero coverage (everything ran on
FakeKube). These tests drive:
  * RestKubeClient end-to-end against a stub apiserver (discovery, CRUD,
    conflict/apply, not-found, poll-watch event diffing);
  * MicroBatcher under concurrent submitters with per-request verdicts;
  * WatchManager add/remove/replace races across threads;
  * AuditManager sweeps overlapping constraint churn.
"""

from __future__ import annotations

import http.server
import json
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.kube import (
    Conflict,
    FakeKube,
    NotFound,
    RestKubeClient,
    WatchEvent,
)
from gatekeeper_tpu.control.watch import Registrar, WatchManager
from gatekeeper_tpu.control.webhook import MicroBatcher
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"


# ----------------------------------------------------- stub apiserver


class _StubApi(http.server.BaseHTTPRequestHandler):
    """Just enough apiserver: /api/v1 discovery + namespaced pod CRUD."""

    store: dict  # {(ns, name): obj}; assigned per-instance via class attr
    rv = [1]

    def log_message(self, *a):
        pass

    def _send(self, code: int, body):
        data = json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _pod_path(self):
        # /api/v1/namespaces/<ns>/pods[/<name>]
        parts = self.path.strip("/").split("/")
        if len(parts) >= 4 and parts[2] == "namespaces" and \
                parts[4] == "pods":
            name = parts[5] if len(parts) > 5 else None
            return parts[3], name
        if len(parts) >= 3 and parts[2] == "pods":
            return None, (parts[3] if len(parts) > 3 else None)
        return None, None

    def do_GET(self):
        if self.path == "/api/v1":
            self._send(200, {"resources": [
                {"name": "pods", "kind": "Pod", "namespaced": True},
                {"name": "pods/status", "kind": "Pod", "namespaced": True},
            ]})
            return
        if self.path == "/apis":
            self._send(200, {"groups": []})
            return
        ns, name = self._pod_path()
        if name is not None:
            obj = self.store.get((ns, name))
            if obj is None:
                self._send(404, {"message": "not found"})
            else:
                self._send(200, obj)
            return
        items = [o for (o_ns, _), o in sorted(self.store.items())
                 if ns is None or o_ns == ns]
        self._send(200, {"kind": "PodList", "items": items})

    def do_POST(self):
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])))
        ns = (body.get("metadata") or {}).get("namespace") or ""
        name = (body.get("metadata") or {}).get("name")
        if (ns, name) in self.store:
            self._send(409, {"message": "exists"})
            return
        self.rv[0] += 1
        body.setdefault("metadata", {})["resourceVersion"] = str(self.rv[0])
        self.store[(ns, name)] = body
        self._send(201, body)

    def do_PUT(self):
        body = json.loads(self.rfile.read(
            int(self.headers["Content-Length"])))
        ns, name = self._pod_path()
        cur = self.store.get((ns, name))
        if cur is None:
            self._send(404, {"message": "not found"})
            return
        sent_rv = (body.get("metadata") or {}).get("resourceVersion")
        if sent_rv != cur["metadata"]["resourceVersion"]:
            self._send(409, {"message": "conflict"})
            return
        self.rv[0] += 1
        body["metadata"]["resourceVersion"] = str(self.rv[0])
        self.store[(ns, name)] = body
        self._send(200, body)

    def do_DELETE(self):
        ns, name = self._pod_path()
        if self.store.pop((ns, name), None) is None:
            self._send(404, {"message": "not found"})
        else:
            self._send(200, {})


@pytest.fixture
def stub_api():
    handler = type("H", (_StubApi,), {"store": {}, "rv": [1]})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    client = RestKubeClient(base_url=f"http://127.0.0.1:{srv.server_port}",
                            token="test-token")
    try:
        yield client, handler
    finally:
        srv.shutdown()


POD_GVK = ("", "v1", "Pod")


def pod(name, ns="d", labels=None):
    meta = {"name": name, "namespace": ns}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta,
            "spec": {}}


def test_rest_client_crud_and_discovery(stub_api):
    kube, handler = stub_api
    created = kube.create(pod("a"))
    assert created["metadata"]["resourceVersion"]
    assert kube.get(POD_GVK, "a", "d")["metadata"]["name"] == "a"
    with pytest.raises(NotFound):
        kube.get(POD_GVK, "missing", "d")
    with pytest.raises(Conflict):
        kube.create(pod("a"))
    # apply: create-conflict -> get + update with current resourceVersion
    updated = kube.apply(pod("a", labels={"x": "y"}))
    assert updated["metadata"]["labels"] == {"x": "y"}
    kube.create(pod("b"))
    names = sorted(o["metadata"]["name"] for o in kube.list(POD_GVK, "d"))
    assert names == ["a", "b"]
    # list() fills apiVersion/kind for unstructured consumers
    assert all(o["kind"] == "Pod" for o in kube.list(POD_GVK, "d"))
    kube.delete(POD_GVK, "b", "d")
    assert [o["metadata"]["name"] for o in kube.list(POD_GVK, "d")] == ["a"]
    # stale-resourceVersion update surfaces Conflict
    stale = kube.get(POD_GVK, "a", "d")
    kube.apply(pod("a", labels={"v": "2"}))
    with pytest.raises(Conflict):
        kube.update(stale)


def test_rest_client_poll_watch_diffs(stub_api):
    kube, handler = stub_api
    kube.create(pod("w1"))
    events: list[WatchEvent] = []
    got_initial = threading.Event()

    def cb(ev):
        events.append(ev)
        got_initial.set()

    cancel = kube.watch(POD_GVK, cb)
    try:
        assert got_initial.wait(5)
        assert events[0].type == "ADDED"
        assert events[0].object["metadata"]["name"] == "w1"
        kube.create(pod("w2"))
        kube.delete(POD_GVK, "w1", "d")
        deadline = time.time() + 8
        while time.time() < deadline:
            types = {(e.type, e.object["metadata"]["name"]) for e in events}
            if ("ADDED", "w2") in types and ("DELETED", "w1") in types:
                break
            time.sleep(0.2)
        types = {(e.type, e.object["metadata"]["name"]) for e in events}
        assert ("ADDED", "w2") in types and ("DELETED", "w1") in types
    finally:
        cancel()


# ------------------------------------------------- micro-batcher stress


def test_microbatcher_concurrent_submitters():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "c"}, "spec": {}})
    batcher = MicroBatcher(client, max_wait=0.002, max_batch=64)
    errs: list = []

    def review(i, labeled):
        labels = {"owner": "me"} if labeled else {}
        return {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": f"p{i}", "namespace": "d", "operation": "CREATE",
                "object": {"apiVersion": "v1", "kind": "Pod",
                           "metadata": {"name": f"p{i}", "namespace": "d",
                                        "labels": labels}}}

    def worker(w):
        try:
            for j in range(40):
                i = w * 100 + j
                labeled = (i % 3 == 0)
                results = batcher.submit(review(i, labeled))
                want = 0 if labeled else 1
                assert len(results) == want, (i, labeled, results)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batcher.stop()
    assert not errs, errs[:3]
    assert batcher.batched_requests == 8 * 40
    assert batcher.batches < 8 * 40  # batching actually happened


# ----------------------------------------------- watch manager races


def test_watch_manager_add_remove_races():
    kube = FakeKube()
    gvks = [("", "v1", k) for k in
            ("Pod", "Service", "ConfigMap", "Secret")]
    for g in gvks:
        kube.register_kind(g)
        kube.create({"apiVersion": "v1", "kind": g[2],
                     "metadata": {"name": "seed", "namespace": "d"}})
    wm = WatchManager(kube)
    errs: list = []
    stop = threading.Event()

    def churn(seed):
        reg = Registrar(f"r{seed}", wm)
        try:
            k = 0
            while not stop.is_set():
                g = gvks[(seed + k) % len(gvks)]
                reg.add_watch(g)
                reg.replace_watches([gvks[(seed + k + 1) % len(gvks)]])
                reg.remove_watch(gvks[(seed + k + 1) % len(gvks)])
                k += 1
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    def producer():
        i = 0
        try:
            while not stop.is_set():
                kube.create({"apiVersion": "v1", "kind": "Pod",
                             "metadata": {"name": f"p{i}",
                                          "namespace": "d"}})
                i += 1
                time.sleep(0.001)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=churn, args=(s,)) for s in range(6)]
    threads.append(threading.Thread(target=producer))
    for t in threads:
        t.start()
    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(5)
    assert not errs, errs[:3]
    # every registrar released its refs: no leaked live watches with zero
    # registrars keeping their caches warm forever
    for gvk, rec in wm._records.items():
        assert rec.cancel is None or rec.registrars, gvk


# ------------------------------------------------- audit loop overlap


def test_audit_sweeps_overlap_constraint_churn():
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    kube = FakeKube()
    kube.register_kind(("constraints.gatekeeper.sh", "v1beta1", "K8sNeed"),
                       namespaced=False)
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneed"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeed"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneed
violation[{"msg": "always"}] { input.review.object.metadata.name }
"""}]},
    })
    for i in range(10):
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": f"n{i}"}})
    mgr = AuditManager(kube, client, interval=0.05)
    errs: list = []
    stop = threading.Event()

    def churn():
        i = 0
        try:
            while not stop.is_set():
                con = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
                       "kind": "K8sNeed",
                       "metadata": {"name": f"c{i % 3}"}, "spec": {}}
                client.add_constraint(con)
                kube.apply(con)
                if i % 4 == 3:
                    client.remove_constraint(con)
                    try:
                        kube.delete(("constraints.gatekeeper.sh", "v1beta1",
                                     "K8sNeed"), f"c{i % 3}")
                    except Exception:
                        pass
                i += 1
                time.sleep(0.01)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    mgr.start()
    t = threading.Thread(target=churn)
    t.start()
    time.sleep(1.0)
    stop.set()
    t.join(5)
    mgr.stop()
    assert not errs, errs[:3]


def test_runtime_soak_under_concurrent_churn():
    """Control-plane soak: live webhook traffic over HTTP while
    templates/constraints/data churn and the audit loop sweeps — no
    exceptions, no deadlocks, and admission answers stay consistent
    with the currently-installed policy at quiescence."""
    import http.client
    import json as pyjson
    import threading
    import time

    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--audit-interval", "0.2",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8srequiredlabels"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sRequiredLabels"}}},
                 "targets": [{"target": "admission.k8s.gatekeeper.sh",
                              "rego": """
package k8srequiredlabels
violation[{"msg": msg}] {
  required := {k | k := input.parameters.labels[_]}
  provided := {k | input.review.object.metadata.labels[k]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("missing labels: %v", [missing])
}
"""}]},
    }
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "soak"},
        "spec": {"parameters": {"labels": ["owner"]}},
    }
    errors: list = []
    stop = threading.Event()

    def review(name, labels):
        o = {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": name}}
        if labels:
            o["metadata"]["labels"] = labels
        return {"apiVersion": "admission.k8s.io/v1beta1",
                "kind": "AdmissionReview",
                "request": {"uid": "u", "operation": "CREATE",
                            "kind": {"group": "", "version": "v1",
                                     "kind": "Namespace"},
                            "name": name,
                            "userInfo": {"username": "soak"},
                            "object": o}}

    def traffic(k):
        i = 0
        while not stop.is_set():
            try:
                conn = http.client.HTTPConnection("127.0.0.1",
                                                  rt.webhook.port,
                                                  timeout=10)
                labels = {"owner": "x"} if i % 2 else None
                conn.request("POST", "/v1/admit",
                             pyjson.dumps(review(f"t{k}-{i}", labels)),
                             {"Content-Type": "application/json"})
                resp = pyjson.loads(conn.getresponse().read())
                assert "response" in resp
                i += 1
            except Exception as e:  # pragma: no cover - fail the soak
                errors.append(e)
                return

    def churn():
        i = 0
        while not stop.is_set():
            try:
                if i % 7 == 0:
                    rt.kube.apply(template)
                if i % 3 == 0:
                    rt.kube.apply(constraint)
                elif i % 3 == 1:
                    try:
                        rt.kube.delete(("constraints.gatekeeper.sh",
                                        "v1beta1", "K8sRequiredLabels"),
                                       "soak")
                    except Exception:
                        pass
                rt.kube.create({"apiVersion": "v1", "kind": "Namespace",
                                "metadata": {"name": f"churn-{i}"}})
                rt.manager.drain()
                i += 1
                time.sleep(0.01)
            except Exception as e:  # pragma: no cover
                errors.append(e)
                return

    rt.kube.create(template)
    rt.manager.drain()
    rt.kube.create(constraint)
    rt.manager.drain()
    threads = [threading.Thread(target=traffic, args=(k,))
               for k in range(4)] + [threading.Thread(target=churn)]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(timeout=15)
        assert not t.is_alive(), "soak thread wedged"
    assert not errors, errors[:3]
    # quiescent consistency: reinstall the constraint; a bad namespace
    # must be denied again through the full HTTP path
    rt.kube.apply(template)
    rt.manager.drain()
    rt.kube.apply(constraint)
    rt.manager.drain()
    conn = http.client.HTTPConnection("127.0.0.1", rt.webhook.port,
                                      timeout=10)
    conn.request("POST", "/v1/admit", pyjson.dumps(review("final", None)),
                 {"Content-Type": "application/json"})
    out = pyjson.loads(conn.getresponse().read())
    assert out["response"]["allowed"] is False
    rt.stop()
