"""Warm restart & HA suite (durable snapshots + leader election).

Covers the statestore tentpole end to end: snapshot save/load integrity
(checksum, schema version, staleness, atomic write-keeps-previous),
warm restart through the real Runtime (library + inventory + tracker
restored, first sweep incremental with ZERO re-encoded objects, readyz
gated until live re-validation), corrupted snapshots degrading to the
cold path (never a crash loop) under `state.snapshot` faults, encoded-
row adoption, watch RESUME from persisted resourceVersions (FakeKube
tombstone replay and the RestKubeClient streaming path against an HTTP
apiserver stub, including the 410-gap heal), Lease-based leader
election (single leader, graceful + crash failover, `kube.lease`
steal/expire faults, the GuardedKube not-leader write fence), byPod
status GC, and a kill -9 mid-sweep -> restore -> converge subprocess
round-trip.

Every test runs under a HARD SIGALRM timeout, same discipline as the
chaos suite.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.audit import (
    AuditManager,
    InventoryTracker,
    _auditable_gvks,
)
from gatekeeper_tpu.control.kube import (
    FakeKube,
    LeaseElector,
    RestKubeClient,
    WatchEvent,
)
from gatekeeper_tpu.control.main import Runtime, build_parser
from gatekeeper_tpu.control.resilience import GuardedKube, NotLeader
from gatekeeper_tpu.control.statestore import (
    SnapshotError,
    StateStore,
    restore_section,
)
from gatekeeper_tpu.utils.faults import FAULTS
from gatekeeper_tpu.utils.values import FrozenDict

TARGET = "admission.k8s.gatekeeper.sh"
LEASE_GVK = ("coordination.k8s.io", "v1", "Lease")
POD_GVK = ("", "v1", "Pod")

PER_TEST_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout_and_clean_faults():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        FAULTS.reset()


NEED_OWNER_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sneedowner"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
        "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
}

NEED_OWNER_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
    "spec": {},
}


def _pod(i, owner=False, ns="d"):
    labels = {"owner": "me"} if owner else {}
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"p{i}", "namespace": ns,
                         "labels": labels}}


def _seed_cluster(kube, n=20, violating=5):
    kube.create(NEED_OWNER_TEMPLATE)
    for i in range(n):
        kube.create(_pod(i, owner=i >= violating))


def _mk_runtime(kube, state_dir):
    args = build_parser().parse_args([
        "--fake-kube", "--operation", "audit",
        "--audit-incremental", "true",
        "--state-dir", state_dir, "--snapshot-interval", "0",
        "--health-addr", "0", "--metrics-backend", "none",
        "--disable-cert-rotation", "--audit-interval", "9999"])
    return Runtime(args, kube=kube)


def _metric_value(name, **labels):
    from gatekeeper_tpu.control.metrics import REGISTRY, _lv

    m = REGISTRY._metrics.get(name)
    if m is None:
        return 0.0
    return m.values.get(_lv(labels), 0.0)


# ------------------------------------------------------------- statestore


def test_statestore_roundtrip_json_and_blob(tmp_path):
    store = StateStore(str(tmp_path))
    assert store.save("vocab", {"strings": ["a", "b"]})
    assert store.load("vocab") == {"strings": ["a", "b"]}
    payload = {"tree": {"t": {"cluster": {"v1": {"Pod": {"x": {"k": 1}}}}}},
               "tracker": {"state": []}}
    assert store.save_blob("inventory", payload)
    assert store.load_blob("inventory") == payload
    assert store.age_s("vocab") is not None
    assert store.age_s("vocab") < 60


def test_statestore_blob_pickles_frozen_values(tmp_path):
    # FrozenDict payloads (encoded-rows metadata may carry them) must
    # round-trip the blob path
    store = StateStore(str(tmp_path))
    fd = FrozenDict({"a": (1, 2)})
    assert store.save_blob("rows", {"k": fd})
    out = store.load_blob("rows")
    assert out["k"] == fd


def test_statestore_corruption_detected(tmp_path):
    store = StateStore(str(tmp_path))
    store.save("library", {"templates": [1, 2, 3]})
    path = store.path("library")
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: len(data) - 5])
    with pytest.raises(SnapshotError):
        store.load("library")
    # the shared restore protocol maps it to the "fallback" outcome
    before = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                           outcome="fallback")
    assert restore_section(store, "library", lambda p: None) is False
    after = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                          outcome="fallback")
    assert after == before + 1


def test_statestore_schema_skew_and_staleness(tmp_path):
    store = StateStore(str(tmp_path))
    store.save("vocab", {"strings": []})
    raw = open(store.path("vocab"), "rb").read()
    head, _, body = raw.partition(b"\n")
    header = json.loads(head)
    header["schema"] = 999
    with open(store.path("vocab"), "wb") as f:
        f.write(json.dumps(header).encode() + b"\n" + body)
    with pytest.raises(SnapshotError):
        store.load("vocab")
    # staleness: a store with a tiny max age rejects an old snapshot
    store2 = StateStore(str(tmp_path), max_age_s=0.01)
    store2.save("vocab", {"strings": []})
    time.sleep(0.05)
    with pytest.raises(SnapshotError):
        store2.load("vocab")


def test_statestore_missing_is_not_fallback(tmp_path):
    store = StateStore(str(tmp_path))
    before = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                           outcome="missing")
    assert restore_section(store, "nothing", lambda p: None) is False
    after = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                          outcome="missing")
    assert after == before + 1


def test_fault_io_error_on_save_keeps_previous(tmp_path):
    store = StateStore(str(tmp_path))
    assert store.save("library", {"v": 1})
    FAULTS.inject("state.snapshot", mode="io-error", count=1)
    assert store.save("library", {"v": 2}) is False
    # previous snapshot intact: atomic write never clobbers on failure
    assert store.load("library") == {"v": 1}


def test_fault_corrupt_via_spec_syntax(tmp_path):
    # the production arming path: --fault-injection spec syntax
    FAULTS.configure("state.snapshot:corrupt#1")
    store = StateStore(str(tmp_path))
    assert store.save("library", {"v": 1})  # save lands, then corrupts
    with pytest.raises(SnapshotError):
        store.load("library")
    assert FAULTS.fired("state.snapshot") == 1


def test_fault_truncate_blob_falls_back(tmp_path):
    store = StateStore(str(tmp_path))
    FAULTS.inject("state.snapshot", mode="truncate", count=1,
                  match={"op": "save"})
    store.save_blob("inventory", {"tree": {}, "tracker": {"x": list(range(1000))}})
    assert restore_section(store, "inventory", lambda p: None,
                           blob=True) is False


# ----------------------------------------------------------- warm restart


def test_warm_restart_end_to_end(tmp_path):
    kube = FakeKube()
    state_dir = str(tmp_path / "state")
    rt = _mk_runtime(kube, state_dir)
    _seed_cluster(kube, n=20, violating=5)
    rt.start()
    rt.manager.drain()
    kube.create(NEED_OWNER_CONSTRAINT)
    rt.manager.drain()
    results = rt.audit.audit_once()
    assert len(results) == 5
    rt.stop()  # SIGTERM drain: snapshots written here

    assert os.path.exists(os.path.join(state_dir, "library.snapshot.json"))
    assert os.path.exists(os.path.join(state_dir,
                                       "inventory.snapshot.blob"))

    # "new process": fresh Runtime over the SAME cluster + state dir
    rt2 = _mk_runtime(kube, state_dir)
    try:
        # library restored from the snapshot, before any watch delivery
        assert rt2.opa.template_kinds() == ["K8sNeedOwner"]
        # tracker restored: state map seeded, watches resumed
        assert rt2.audit.tracker is not None
        # readyz gate: restored state not yet re-validated
        assert rt2.audit.restore_ready() is False
        calls_before = len(kube.calls)
        res2 = rt2.audit.audit_once()
        assert rt2.audit.restore_ready() is True
        # first warm sweep is INCREMENTAL (no forced full re-encode)
        # and re-encodes NOTHING on an unchanged cluster
        assert rt2.audit.last_sweep_stats["sweep"] == "incremental"
        assert rt2.audit.last_sweep_stats["dirty"] == 0
        assert len(res2) == 5
        # no full cluster re-list of the tracked inventory: the resumed
        # watches carried the state (constraint/status lists excepted)
        inventory_lists = [c for c in kube.calls[calls_before:]
                           if c[0] == "list" and c[1] == POD_GVK
                           and c[2] is None]
        assert inventory_lists == []
    finally:
        if rt2.audit.tracker is not None:
            rt2.audit.tracker.stop()


def test_warm_restart_applies_downtime_delta(tmp_path):
    kube = FakeKube()
    state_dir = str(tmp_path / "state")
    rt = _mk_runtime(kube, state_dir)
    _seed_cluster(kube, n=12, violating=3)
    rt.start()
    rt.manager.drain()
    kube.create(NEED_OWNER_CONSTRAINT)
    rt.manager.drain()
    rt.audit.audit_once()
    rt.stop()

    # mutations while "down": one new violator, one delete, one fix
    kube.create(_pod(100, owner=False))
    kube.delete(POD_GVK, "p0", "d")          # was violating
    fixed = kube.get(POD_GVK, "p1", "d")
    fixed["metadata"]["labels"] = {"owner": "me"}
    kube.update(fixed)                        # was violating, now fixed

    rt2 = _mk_runtime(kube, state_dir)
    try:
        res = rt2.audit.audit_once()
        stats = rt2.audit.last_sweep_stats
        assert stats["sweep"] == "incremental"
        # exactly the downtime delta re-encoded: add + delete + update
        assert stats["dirty"] == 3
        names = sorted((r.resource.get("metadata") or {}).get("name")
                       for r in res)
        assert names == ["p100", "p2"]
    finally:
        if rt2.audit.tracker is not None:
            rt2.audit.tracker.stop()


def test_corrupt_snapshot_cold_fallback_no_crash(tmp_path):
    kube = FakeKube()
    state_dir = str(tmp_path / "state")
    rt = _mk_runtime(kube, state_dir)
    _seed_cluster(kube, n=10, violating=2)
    rt.start()
    rt.manager.drain()
    kube.create(NEED_OWNER_CONSTRAINT)
    rt.manager.drain()
    rt.audit.audit_once()
    rt.stop()

    # corrupt BOTH the inventory blob and the library body
    for name in ("inventory.snapshot.blob", "library.snapshot.json"):
        path = os.path.join(state_dir, name)
        data = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(data[: max(1, len(data) // 2)])

    before = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                           outcome="fallback")
    rt2 = _mk_runtime(kube, state_dir)  # must not raise: cold path
    try:
        after = _metric_value("gatekeeper_tpu_snapshot_restore_total",
                              outcome="fallback")
        assert after >= before + 2
        # cold path: no tracker restored, readiness trivially open
        assert rt2.audit.tracker is None
        assert rt2.audit.restore_ready() is True
        # the cold first sweep still converges (full resync); start the
        # controllers only — the audit loop would race our manual sweep
        rt2.manager.start()
        rt2.manager.drain()
        res = rt2.audit.audit_once()
        assert rt2.audit.last_sweep_stats["sweep"] == "full_resync"
        assert len(res) == 2
    finally:
        rt2.manager.stop()
        if rt2.audit.tracker is not None:
            rt2.audit.tracker.stop()


def test_encoded_rows_snapshot_and_adoption(tmp_path):
    # device-path feature tensors snapshot -> restore -> adoption on
    # the first warm audit (candidate set unchanged)
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    def mk():
        drv = TpuDriver()
        drv.async_warm = False
        drv._use_device_for_batch = lambda n: True  # force device path
        client = Backend(drv).new_client([K8sValidationTarget()])
        return drv, client

    drv, client = mk()
    client.add_template(NEED_OWNER_TEMPLATE)
    client.add_constraint(NEED_OWNER_CONSTRAINT)
    for i in range(32):
        client.add_data(_pod(i, owner=i % 2 == 0))
    want = len(client.audit().results())
    assert want == 16
    rows = drv.encoded_rows_snapshot()
    assert rows and "K8sNeedOwner" in rows
    store = StateStore(str(tmp_path))
    assert store.save_blob("rows", rows)
    assert store.save("vocab", drv.vocab_snapshot())

    drv2, client2 = mk()
    drv2.vocab_restore(store.load("vocab"))
    client2.add_template(NEED_OWNER_TEMPLATE)
    client2.add_constraint(NEED_OWNER_CONSTRAINT)
    tree = drv.inventory_snapshot()
    drv2.inventory_restore(tree)
    drv2.encoded_rows_restore(store.load_blob("rows"))
    assert len(client2.audit().results()) == want
    assert drv2.restored_rows_adopted >= 1


def test_encoded_rows_refused_after_inventory_delta(tmp_path):
    # any inventory write between restore and the first audit makes the
    # stashed rows suspect: adoption must refuse and re-extract
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    def mk():
        drv = TpuDriver()
        drv.async_warm = False
        drv._use_device_for_batch = lambda n: True
        client = Backend(drv).new_client([K8sValidationTarget()])
        return drv, client

    drv, client = mk()
    client.add_template(NEED_OWNER_TEMPLATE)
    client.add_constraint(NEED_OWNER_CONSTRAINT)
    for i in range(16):
        client.add_data(_pod(i, owner=i % 2 == 0))
    client.audit()
    rows = drv.encoded_rows_snapshot()
    tree = drv.inventory_snapshot()
    vocab = drv.vocab_snapshot()

    drv2, client2 = mk()
    drv2.vocab_restore(vocab)
    client2.add_template(NEED_OWNER_TEMPLATE)
    client2.add_constraint(NEED_OWNER_CONSTRAINT)
    drv2.inventory_restore(tree)
    drv2.encoded_rows_restore(rows)
    client2.add_data(_pod(99, owner=False))  # delta AFTER restore
    res = client2.audit().results()
    assert len(res) == 9  # 8 original violators + p99
    assert drv2.restored_rows_adopted == 0


# ----------------------------------------------------------- watch resume


def test_fakekube_resume_no_added_storm():
    kube = FakeKube()
    kube.register_kind(POD_GVK)
    for i in range(10):
        kube.create(_pod(i))
    rv = kube._rv
    # churn after the checkpoint: 2 modified, 1 deleted, 1 added
    p = kube.get(POD_GVK, "p1", "d")
    p["metadata"]["labels"] = {"owner": "x"}
    kube.update(p)
    p = kube.get(POD_GVK, "p2", "d")
    p["metadata"]["labels"] = {"owner": "y"}
    kube.update(p)
    kube.delete(POD_GVK, "p3", "d")
    kube.create(_pod(42))

    events = []
    gaps = []
    cancel = kube.watch(POD_GVK, events.append, send_initial=False,
                        resource_version=str(rv), on_gap=lambda: gaps.append(1))
    cancel()
    assert gaps == []
    by_type = {}
    for e in events:
        by_type.setdefault(e.type, []).append(
            e.object["metadata"]["name"])
    assert "ADDED" not in by_type  # no duplicate ADDED storm
    assert sorted(by_type.get("MODIFIED", [])) == ["p1", "p2", "p42"]
    assert by_type.get("DELETED") == ["p3"]


def test_fakekube_resume_too_old_heals_via_relist():
    kube = FakeKube()
    kube.register_kind(POD_GVK)
    for i in range(5):
        kube.create(_pod(i))
    old_rv = "1"
    kube.compact()  # history gone: old RVs must take the 410 path
    events = []
    gaps = []
    cancel = kube.watch(POD_GVK, events.append, send_initial=False,
                        resource_version=old_rv, on_gap=lambda: gaps.append(1))
    cancel()
    assert len(gaps) == 1  # subscriber told to reconcile deletes
    assert sorted(e.type for e in events) == ["ADDED"] * 5


def test_tracker_restart_resume_and_410_heal():
    """Tracker snapshot -> cluster churns (incl. deletes) -> restore:
    the resumed watches carry the delta; with compacted history the
    gap resync heals the same state."""
    for compact in (False, True):
        kube = FakeKube()
        kube.register_kind(POD_GVK)
        kube.register_kind(("", "v1", "Namespace"), namespaced=False)
        for i in range(10):
            kube.create(_pod(i, owner=True))
        drv = RegoDriver()
        from gatekeeper_tpu.target import K8sValidationTarget
        opa = Backend(drv).new_client([K8sValidationTarget()])
        tr = InventoryTracker(kube, opa)
        tr.full_resync(_auditable_gvks(kube))
        snap = tr.snapshot()
        tr.stop()

        kube.delete(POD_GVK, "p0", "d")
        kube.create(_pod(77))
        if compact:
            kube.compact()

        drv2 = RegoDriver()
        opa2 = Backend(drv2).new_client([K8sValidationTarget()])
        tr2 = InventoryTracker(kube, opa2)
        tr2.restore(snap)
        stats = tr2.apply_pending()
        assert tr2.validated.is_set()
        assert stats["total"] == 10  # 10 - 1 deleted + 1 added
        keys = {k[2] for k in tr2._state if k[0] == POD_GVK}
        assert "p0" not in keys and "p77" in keys
        tr2.stop()


class _StubApi(BaseHTTPRequestHandler):
    """Minimal apiserver: discovery + pod list + one-shot watch."""

    def log_message(self, *a):
        pass

    def do_GET(self):
        srv = self.server
        srv.requests.append(self.path)
        if self.path.startswith("/api/v1/pods") and "watch=1" in self.path:
            srv.watch_count += 1
            if srv.gone_first and srv.watch_count == 1:
                frame = {"type": "ERROR",
                         "object": {"code": 410, "message": "too old"}}
            else:
                frame = {"type": "MODIFIED",
                         "object": {"metadata": {"name": "w1",
                                                 "resourceVersion": "50"}}}
            body = (json.dumps(frame) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/api/v1/pods"):
            body = json.dumps({
                "kind": "PodList",
                "metadata": {"resourceVersion": "42"},
                "items": [{"metadata": {"name": "l1",
                                        "resourceVersion": "40"}}],
            }).encode()
        elif self.path == "/api/v1":
            body = json.dumps({"resources": [
                {"name": "pods", "kind": "Pod", "namespaced": True,
                 "verbs": ["list", "watch"]}]}).encode()
        else:
            body = b"{}"
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _stub_server(gone_first=False):
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _StubApi)
    srv.daemon_threads = True
    srv.requests = []
    srv.watch_count = 0
    srv.gone_first = gone_first
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_rest_watch_resumes_from_persisted_rv():
    srv = _stub_server()
    client = RestKubeClient(base_url=f"http://127.0.0.1:{srv.server_address[1]}",
                            token="t")
    events = []
    got = threading.Event()

    def cb(e):
        events.append(e)
        got.set()

    cancel = client.watch(POD_GVK, cb, send_initial=False,
                          resource_version="37")
    try:
        assert got.wait(10)
    finally:
        cancel()
        srv.shutdown()
        srv.server_close()
    watch_reqs = [r for r in srv.requests if "watch=1" in r]
    assert watch_reqs and "resourceVersion=37" in watch_reqs[0]
    # resume mode: NO initial paged list before the stream opened
    first_watch = srv.requests.index(watch_reqs[0])
    assert not any("limit=" in r for r in srv.requests[:first_watch])
    assert events[0].type == "MODIFIED"
    assert events[0].object["metadata"]["name"] == "w1"


def test_rest_watch_410_heals_with_gap_signal():
    srv = _stub_server(gone_first=True)
    client = RestKubeClient(base_url=f"http://127.0.0.1:{srv.server_address[1]}",
                            token="t")
    events = []
    gaps = []
    healed = threading.Event()

    def cb(e):
        events.append(e)
        if e.type == "ADDED":
            healed.set()

    cancel = client.watch(POD_GVK, cb, send_initial=False,
                          resource_version="5", on_gap=lambda: gaps.append(1))
    try:
        assert healed.wait(10)
    finally:
        cancel()
        srv.shutdown()
        srv.server_close()
    assert len(gaps) == 1  # caller told to reconcile gap deletes
    # the 410 triggered a relist (paged list request seen)...
    assert any("limit=" in r for r in srv.requests)
    # ...whose diff re-emitted the live object as ADDED
    added = [e for e in events if e.type == "ADDED"]
    assert added and added[0].object["metadata"]["name"] == "l1"


# -------------------------------------------------------- leader election


def _lease_kube():
    kube = FakeKube()
    kube.register_kind(LEASE_GVK)
    return kube


def test_single_leader_and_graceful_failover():
    kube = _lease_kube()
    e1 = LeaseElector(kube, identity="pod-a", lease_duration=0.6,
                      namespace="gk")
    e2 = LeaseElector(kube, identity="pod-b", lease_duration=0.6,
                      namespace="gk")
    e1.start()
    assert e1.wait_leader(5)
    e2.start()
    time.sleep(0.5)
    assert not e2.is_leader  # exactly one leader while both live
    t0 = time.time()
    e1.stop()  # graceful: releases the lease
    assert e2.wait_leader(5)
    # graceful failover is fast — far under a full lease duration x2
    assert time.time() - t0 < 3.0
    e2.stop()
    lease = kube.get(LEASE_GVK, e2.lease_name, "gk")
    assert lease["spec"]["holderIdentity"] == ""  # released on shutdown


def test_crash_failover_within_lease_duration():
    kube = _lease_kube()
    e1 = LeaseElector(kube, identity="pod-a", lease_duration=0.5,
                      namespace="gk")
    e2 = LeaseElector(kube, identity="pod-b", lease_duration=0.5,
                      namespace="gk")
    e1.start()
    try:
        assert e1.wait_leader(5)
        e2.start()
        time.sleep(0.2)
        e1.stop(release=False)  # crash: lease NOT released
        t0 = time.time()
        assert e2.wait_leader(5)
        # takeover needed the lease to lapse, but within ~2 durations
        assert time.time() - t0 < 2.5
    finally:
        # leaked elector loops would consume other tests' armed faults
        e1.stop(release=False)
        e2.stop()


def test_lease_steal_fault_deposes_then_recovers():
    kube = _lease_kube()
    e1 = LeaseElector(kube, identity="pod-a", lease_duration=0.5,
                      namespace="gk")
    e1.start()
    assert e1.wait_leader(5)
    before = e1.transitions
    FAULTS.inject("kube.lease", mode="steal", count=1,
                  match={"identity": "pod-a"})
    # deposed by the thief, then (the thief never renews) re-acquired
    # after its lease lapses: two transitions, polled via the counter
    # because the not-leader window can be shorter than a poll interval
    t0 = time.time()
    while e1.transitions < before + 2 and time.time() - t0 < 10:
        time.sleep(0.05)
    assert e1.transitions >= before + 2
    assert e1.wait_leader(5)
    assert FAULTS.fired("kube.lease") == 1
    e1.stop()


def test_lease_expire_fault_drops_leadership():
    kube = _lease_kube()
    e1 = LeaseElector(kube, identity="pod-a", lease_duration=0.5,
                      namespace="gk")
    e1.start()
    assert e1.wait_leader(5)
    before = e1.transitions
    FAULTS.inject("kube.lease", mode="expire", count=1,
                  match={"identity": "pod-a"})
    t0 = time.time()
    while e1.transitions < before + 2 and time.time() - t0 < 10:
        time.sleep(0.05)
    # lost on the lapse, re-acquired on a later tick
    assert e1.transitions >= before + 2
    assert e1.wait_leader(5)
    e1.stop()


def test_not_leader_write_fence():
    kube = FakeKube()
    kube.register_kind(POD_GVK)
    leading = {"v": False}
    guard = GuardedKube(kube, write_gate=lambda: leading["v"])
    with pytest.raises(NotLeader):
        guard.create(_pod(1))
    assert kube.list(POD_GVK) == []  # no API call went through
    # reads and watches pass the fence untouched
    assert guard.list(POD_GVK) == []
    leading["v"] = True
    guard.create(_pod(1))
    assert len(kube.list(POD_GVK)) == 1
    # guarded status writers swallow the fence as a no-op
    from gatekeeper_tpu.control.resilience import guarded_status_update

    leading["v"] = False
    obj = kube.list(POD_GVK)[0]
    assert guarded_status_update(guard, obj, lambda o: None) is False


def test_audit_loop_gated_on_leadership():
    kube = FakeKube()
    kube.register_kind(POD_GVK)
    from gatekeeper_tpu.target import K8sValidationTarget

    opa = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    leading = {"v": False}
    am = AuditManager(kube, opa, interval=0.05,
                      leader_check=lambda: leading["v"],
                      gc_stale_statuses=False)
    sweeps = []
    orig = am.audit_once
    am.audit_once = lambda: (sweeps.append(time.time()), orig())[1]
    am.start()
    try:
        time.sleep(0.4)
        assert sweeps == []  # follower never swept
        assert am.healthy()  # but stays live
        leading["v"] = True
        t0 = time.time()
        while not sweeps and time.time() - t0 < 10:
            time.sleep(0.05)
        assert sweeps, "promoted leader never swept"
        # promotion is prompt: the follower polls at a sub-lease cadence
        assert sweeps[0] - t0 < 5
    finally:
        am.stop()


# ------------------------------------------------------------- byPod GC


def test_stale_by_pod_statuses_pruned(tmp_path):
    kube = FakeKube()
    rt = _mk_runtime(kube, str(tmp_path / "s"))
    _seed_cluster(kube, n=4, violating=1)
    # live replica pods (gatekeeper-labeled) in our namespace
    kube.create({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "gatekeeper-audit-live",
                              "namespace": "gatekeeper-system",
                              "labels": {"gatekeeper.sh/system": "yes"}}})
    rt.start()
    rt.manager.drain()
    kube.create(NEED_OWNER_CONSTRAINT)
    rt.manager.drain()
    # a replaced pod's stale byPod entry on the constraint status
    gvk = ("constraints.gatekeeper.sh", "v1beta1", "K8sNeedOwner")
    obj = kube.get(gvk, "need-owner")
    status = obj.setdefault("status", {})
    by_pod = status.setdefault("byPod", [])
    by_pod.append({"id": "gatekeeper-audit-REPLACED", "enforced": True})
    kube.update(obj, subresource="status")
    rt.audit.audit_once()
    cur = kube.get(gvk, "need-owner")
    ids = [e.get("id") for e in (cur.get("status") or {}).get("byPod", [])]
    assert "gatekeeper-audit-REPLACED" not in ids
    rt.stop()


# ------------------------------------------------------ kill -9 round-trip


_CHILD_SCRIPT = r"""
import json, os, sys, time
sys.path.insert(0, os.environ["REPO_DIR"])
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.audit import (AuditManager, InventoryTracker,
                                          _auditable_gvks)
from gatekeeper_tpu.control.kube import FakeKube
from gatekeeper_tpu.control.statestore import StateStore, restore_section
from gatekeeper_tpu.target import K8sValidationTarget

STATE = os.environ["STATE_DIR"]
PHASE = os.environ["PHASE"]
TARGET = "admission.k8s.gatekeeper.sh"

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate", "metadata": {"name": "k8sneedowner"},
    "spec": {"crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
             "targets": [{"target": TARGET, "rego":
                          "package k8sneedowner\n"
                          "violation[{\"msg\": \"no owner\"}] "
                          "{ not input.review.object.metadata.labels.owner }"}]}}
CONSTRAINT = {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
              "kind": "K8sNeedOwner", "metadata": {"name": "no"},
              "spec": {}}

def seed_kube():
    # deterministic cluster: the "apiserver" survives the kill because
    # both phases rebuild it identically (FakeKube RVs are sequential)
    kube = FakeKube()
    kube.register_kind(("", "v1", "Pod"))
    for i in range(60):
        labels = {} if i % 3 == 0 else {"owner": "me"}
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "namespace": "d",
                                  "labels": labels}})
    return kube

kube = seed_kube()
drv = RegoDriver()
client = Backend(drv).new_client([K8sValidationTarget()])
store = StateStore(STATE)

if PHASE == "1":
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    am = AuditManager(kube, client, incremental=True,
                      gc_stale_statuses=False)
    am.tracker = InventoryTracker(kube, client)
    am.tracker.full_resync(_auditable_gvks(kube))
    store.save_blob("inventory", {"tree": drv.inventory_snapshot() or {},
                                  "tracker": am.tracker.snapshot()})
    store.save("library", client.snapshot_library())
    print("SNAPSHOTTED", flush=True)
    # now sweep forever; the parent kill -9s us mid-sweep
    while True:
        am.tracker.apply_pending()
        client.audit()
        store.save_blob("inventory",
                        {"tree": drv.inventory_snapshot() or {},
                         "tracker": am.tracker.snapshot()})
        print("SWEPT", flush=True)
else:
    ok_lib = restore_section(store, "library", client.restore_library)
    am = AuditManager(kube, client, incremental=True,
                      gc_stale_statuses=False)
    def apply_inv(snap):
        drv.inventory_restore(snap.get("tree") or {})
        am.restore_state(snap.get("tracker") or {})
    ok_inv = restore_section(store, "inventory", apply_inv, blob=True)
    if not ok_lib:
        client.add_template(TEMPLATE)
        client.add_constraint(CONSTRAINT)
    if am.tracker is None:  # cold fallback still converges
        am.tracker = InventoryTracker(kube, client)
        am.tracker.full_resync(_auditable_gvks(kube))
    else:
        am.tracker.apply_pending()
        assert am.tracker.validated.is_set()
    n = len(client.audit().results())
    print(json.dumps({"restored": bool(ok_inv), "violations": n}),
         flush=True)
    assert n == 20, n
    print("CONVERGED", flush=True)
"""


def test_kill9_mid_sweep_then_restore_converges(tmp_path):
    state_dir = str(tmp_path / "state")
    script = tmp_path / "child.py"
    script.write_text(_CHILD_SCRIPT)
    env = dict(os.environ)
    env.update({"STATE_DIR": state_dir, "PHASE": "1",
                "REPO_DIR": os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "JAX_PLATFORMS": "cpu"})
    p1 = subprocess.Popen([sys.executable, str(script)], env=env,
                          stdout=subprocess.PIPE, text=True)
    try:
        # wait for the first snapshot + at least one sweep, then KILL -9
        deadline = time.time() + 60
        swept = False
        for line in p1.stdout:
            if "SWEPT" in line:
                swept = True
                break
            if time.time() > deadline:
                break
        assert swept, "child never completed a sweep"
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=10)
    finally:
        if p1.poll() is None:
            p1.kill()

    env["PHASE"] = "2"
    p2 = subprocess.run([sys.executable, str(script)], env=env,
                        capture_output=True, text=True, timeout=90)
    assert p2.returncode == 0, p2.stdout + p2.stderr
    assert "CONVERGED" in p2.stdout
    # the atomically-written snapshot survived the SIGKILL: phase 2
    # warm-restored (rename is all-or-nothing; a torn write would have
    # shown up as restored=false via the checksum fallback)
    out = json.loads([ln for ln in p2.stdout.splitlines()
                      if ln.startswith("{")][0])
    assert out["restored"] is True
    assert out["violations"] == 20
