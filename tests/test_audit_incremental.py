"""Incremental delta audit (ISSUE 1 tentpole).

The correctness invariant: an incremental sweep (persistent encoded
inventory + dirty-row patching + results delta cache) must produce
identical violation sets to a from-scratch full sweep over the same
cluster state — asserted differentially under randomized churn
(creates / updates / deletes, vocabulary-growing label values, and
namespace-label flips that change namespaceSelector outcomes), with the
full-sweep reference running on the independent interpreter engine.

Mechanism pins: steady sweeps issue ZERO constraint-status PATCHes and
re-extract nothing; the watch-gap (410 Gone) fallback re-list-diffs;
delete-then-recreate under the same name but a new uid is applied.
"""

from __future__ import annotations

import random

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.audit import AuditManager, InventoryTracker
from gatekeeper_tpu.control.kube import FakeKube, KubeError
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.parallel.workload import REQUIRED_LABELS_TEMPLATE
from gatekeeper_tpu.target import K8sValidationTarget

CONSTRAINT_GVK = ("constraints.gatekeeper.sh", "v1beta1",
                  "K8sRequiredLabels")

CONSTRAINTS = [
    {  # every Namespace needs a regex-conforming owner label
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "ns-owner", "uid": "c-1"},
        "spec": {
            "match": {"kinds": [{"apiGroups": [""],
                                 "kinds": ["Namespace"]}]},
            "parameters": {"labels": [
                {"key": "owner",
                 "allowedRegex": "^[a-z]+[.]corp[.]example$"}]},
        },
    },
    {  # Pods in env=prod namespaces need a team label
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "prod-team", "uid": "c-2"},
        "spec": {
            "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
                      "namespaceSelector":
                          {"matchLabels": {"env": "prod"}}},
            "parameters": {"labels": [{"key": "team"}]},
        },
    },
]


def _ns(name, labels=None, uid=None):
    o = {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": name}}
    if labels is not None:
        o["metadata"]["labels"] = labels
    if uid is not None:
        o["metadata"]["uid"] = uid
    return o


def _pod(name, namespace, labels=None, uid=None):
    o = {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": name, "namespace": namespace}}
    if labels is not None:
        o["metadata"]["labels"] = labels
    if uid is not None:
        o["metadata"]["uid"] = uid
    return o


def _cluster():
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    for i in range(4):
        kube.create(_ns(f"ns-{i}",
                        {"env": "prod" if i % 2 else "dev",
                         "owner": "alpha.corp.example"}, uid=f"ns-u{i}"))
    for i in range(40):
        labels = {}
        if i % 3 == 0:
            labels["team"] = "payments"
        kube.create(_pod(f"p-{i}", f"ns-{i % 4}", labels, uid=f"p-u{i}"))
    return kube


def _manager(kube, driver, full_resync_every):
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    for c in CONSTRAINTS:
        client.add_constraint(c)
        kube.apply(dict(c))
    return client, AuditManager(kube, client, incremental=True,
                                full_resync_every=full_resync_every)


def _key(results):
    return sorted(
        ((r.constraint.get("metadata") or {}).get("name", ""), r.msg,
         (r.resource or {}).get("kind", ""),
         ((r.resource or {}).get("metadata") or {}).get("namespace") or "",
         ((r.resource or {}).get("metadata") or {}).get("name") or "",
         r.enforcement_action)
        for r in results)


def _apply_churn(kube, rng, round_):
    """Randomized creates/updates/deletes, including vocabulary-growing
    label values and namespace env flips (namespaceSelector outcomes)."""
    ops = []
    for _ in range(8):
        op = rng.choice(["update", "update", "create", "delete",
                         "ns-flip", "owner-churn"])
        ops.append(op)
        if op == "update":
            i = rng.randrange(40)
            labels = {}
            if rng.random() < 0.5:
                labels["team"] = f"team-{round_}-{i}"  # new vocab
            try:
                cur = kube.get(("", "v1", "Pod"), f"p-{i}", f"ns-{i % 4}")
            except KubeError:
                continue
            cur["metadata"]["labels"] = labels
            kube.update(cur)
        elif op == "create":
            name = f"extra-{round_}-{rng.randrange(1000)}"
            try:
                kube.create(_pod(name, f"ns-{rng.randrange(4)}",
                                 uid=f"u-{name}"))
            except KubeError:
                pass
        elif op == "delete":
            i = rng.randrange(40)
            try:
                kube.delete(("", "v1", "Pod"), f"p-{i}", f"ns-{i % 4}")
            except KubeError:
                pass
        elif op == "ns-flip":
            i = rng.randrange(4)
            cur = kube.get(("", "v1", "Namespace"), f"ns-{i}")
            labels = cur["metadata"].setdefault("labels", {})
            labels["env"] = "dev" if labels.get("env") == "prod" \
                else "prod"
            kube.update(cur)
        else:  # owner-churn: regex-relevant value growth
            i = rng.randrange(4)
            cur = kube.get(("", "v1", "Namespace"), f"ns-{i}")
            labels = cur["metadata"].setdefault("labels", {})
            labels["owner"] = rng.choice(
                ["beta.corp.example", f"BAD-{round_}", "x.corp.example"])
            kube.update(cur)
    return ops


def test_differential_incremental_vs_full_under_churn():
    """Every churn round: the incremental sweep (TpuDriver, patched
    caches, never resyncing) must equal a from-scratch full re-encode
    sweep (independent interpreter engine, resyncing every sweep)."""
    kube = _cluster()
    # 0 = periodic re-encode disabled (the first sweep still encodes
    # from scratch): the incremental side must never fall back
    _ci, inc = _manager(kube, TpuDriver(), full_resync_every=0)
    _cf, full = _manager(kube, RegoDriver(), full_resync_every=1)
    assert _key(inc.audit_once()) == _key(full.audit_once())
    rng = random.Random(42)
    for round_ in range(6):
        ops = _apply_churn(kube, rng, round_)
        got, want = _key(inc.audit_once()), _key(full.audit_once())
        assert got == want, f"round {round_} diverged after {ops}"
        assert inc.last_sweep_stats["sweep"] == "incremental"
    assert want, "differential went vacuous (no violations at the end)"
    inc.stop()
    full.stop()


def test_steady_sweep_is_delta_and_writes_nothing():
    """Acceptance: a sweep with zero changes performs ZERO status
    PATCHes (fake kube call log) and re-extracts nothing — the results
    delta cache answers and the encoded inventory stays resident."""
    kube = _cluster()
    drv = TpuDriver()
    _client, mgr = _manager(kube, drv, full_resync_every=10 ** 9)
    mgr.audit_once()
    first = mgr.audit_once()  # fingerprints settle

    import gatekeeper_tpu.ir.driver as drvmod
    calls = {"extract": 0}
    orig = drvmod.extract_batch
    drvmod.extract_batch = lambda *a, **k: (
        calls.__setitem__("extract", calls["extract"] + 1), orig(*a, **k)
    )[1]
    try:
        n0 = len(kube.calls)
        out = mgr.audit_once()
        new_calls = kube.calls[n0:]
    finally:
        drvmod.extract_batch = orig
    assert _key(out) == _key(first)
    status_writes = [c for c in new_calls
                     if c[0] == "update" and c[3] == "status"]
    assert status_writes == [], status_writes
    assert calls["extract"] == 0, "steady sweep re-extracted features"
    assert drv.last_audit_path.startswith("delta("), drv.last_audit_path
    assert mgr.last_sweep_stats["dirty"] == 0

    # one object changes -> O(changed constraints) writes: only the
    # ns-owner constraint's violation set changes
    cur = kube.get(("", "v1", "Namespace"), "ns-0")
    cur["metadata"]["labels"] = {"env": "dev"}  # owner label gone
    kube.update(cur)
    n0 = len(kube.calls)
    mgr.audit_once()
    writes = [c for c in kube.calls[n0:]
              if c[0] == "update" and c[3] == "status"]
    assert [c[2] for c in writes] == [("", "ns-owner")]
    mgr.stop()


def test_full_resync_backstop_heals_divergence():
    """--audit-full-resync-every: the from-scratch re-encode must repair
    lost updates AND lost deletes (watch events that never arrived),
    while leaving inventory data it does not own untouched (the config
    controller co-owns the tree — full resync must not wipe it)."""
    kube = _cluster()
    client, mgr = _manager(kube, TpuDriver(), full_resync_every=2)
    mgr.audit_once()  # sweep 0: full resync
    # inventory data owned by another writer (config-synced kind)
    client.add_data({"apiVersion": "v1", "kind": "Endpoints",
                     "metadata": {"name": "foreign", "namespace": "ns-0"}})
    # divergence: one update and one delete whose events are LOST
    cur = kube.get(("", "v1", "Namespace"), "ns-1")
    cur["metadata"]["labels"] = {"env": "prod"}  # owner label dropped
    kube.update(cur)
    kube.delete(("", "v1", "Pod"), "p-1", "ns-1")
    with mgr.tracker._lock:
        mgr.tracker._dirty.clear()
    r1 = _key(mgr.audit_once())  # sweep 1: incremental, still stale
    assert mgr.last_sweep_stats["sweep"] == "incremental"
    assert not any(name == "ns-1" for (_c, _m, _k, _n, name, _e) in r1)
    r2 = _key(mgr.audit_once())  # sweep 2: full resync heals both
    assert mgr.last_sweep_stats["sweep"] == "full_resync"
    assert any(c == "ns-owner" and name == "ns-1"
               for (c, _m, _k, _n, name, _e) in r2)
    key = ((("", "v1", "Pod")), "ns-1", "p-1")
    assert key not in mgr.tracker._state
    # the foreign object survived the resync (no inventory wipe)
    assert client.driver.get_data(
        ("external", "admission.k8s.gatekeeper.sh", "namespace", "ns-0",
         "v1", "Endpoints", "foreign")) is not None
    mgr.stop()


class _WatchlessKube(FakeKube):
    """Streams always fail (a server whose watch RVs are expired: every
    subscription dies with 410 Gone) — the tracker must fall back to a
    per-sweep resourceVersion-diff re-list."""

    def watch(self, gvk, callback, send_initial=True):
        raise KubeError("watch: HTTP 410 Gone", 410)


def test_watch_gap_falls_back_to_relist_diff():
    kube = _WatchlessKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    kube.create(_ns("ns-0", {"env": "prod", "owner": "a.corp.example"},
                    uid="n0"))
    kube.create(_pod("p-0", "ns-0", uid="u0"))
    _client, mgr = _manager(kube, TpuDriver(), full_resync_every=10 ** 9)
    r0 = _key(mgr.audit_once())
    assert mgr.tracker._poll, "no GVK degraded to the re-list path"
    assert any(name == "p-0" for (_c, _m, _k, _ns_, name, _e) in r0)
    # churn is only observable through the re-list diff
    cur = kube.get(("", "v1", "Pod"), "p-0", "ns-0")
    cur["metadata"]["labels"] = {"team": "x"}
    kube.update(cur)
    kube.create(_pod("p-1", "ns-0", uid="u1"))
    r1 = _key(mgr.audit_once())
    assert mgr.last_sweep_stats["dirty"] == 2
    assert not any(name == "p-0" for (_c, _m, _k, _ns_, name, _e) in r1)
    assert any(name == "p-1" for (_c, _m, _k, _ns_, name, _e) in r1)
    kube.delete(("", "v1", "Pod"), "p-1", "ns-0")
    r2 = _key(mgr.audit_once())
    assert not any(name == "p-1" for (_c, _m, _k, _ns_, name, _e) in r2)
    mgr.stop()


def test_note_gap_triggers_one_shot_resync():
    """note_gap(gvk): the operator/watch-layer signal for a stream that
    died beyond the client's own recovery — the next sweep re-list-diffs
    that GVK once, picking up changes whose events were lost."""
    kube = _cluster()
    _client, mgr = _manager(kube, TpuDriver(), full_resync_every=10 ** 9)
    mgr.audit_once()
    # make ns-0 prod so p-0's team label is load-bearing
    cur = kube.get(("", "v1", "Namespace"), "ns-0")
    cur["metadata"]["labels"]["env"] = "prod"
    kube.update(cur)
    r = _key(mgr.audit_once())
    assert not any(c == "prod-team" and name == "p-0"
                   for (c, _m, _k, _n, name, _e) in r)
    # p-0 loses its team label, but the event is LOST (dead stream)
    cur = kube.get(("", "v1", "Pod"), "p-0", "ns-0")
    cur["metadata"]["labels"] = {}
    kube.update(cur)
    with mgr.tracker._lock:
        mgr.tracker._dirty.clear()  # simulate the lost delivery
    r = _key(mgr.audit_once())  # stale: the change was never seen
    assert not any(c == "prod-team" and name == "p-0"
                   for (c, _m, _k, _n, name, _e) in r)
    mgr.tracker.note_gap(("", "v1", "Pod"))
    r = _key(mgr.audit_once())  # one-shot resync heals it
    assert any(c == "prod-team" and name == "p-0"
               for (c, _m, _k, _n, name, _e) in r)
    assert mgr.last_sweep_stats["dirty"] == 1
    mgr.stop()


def test_resync_supersedes_stale_pending_events():
    """A stale MODIFIED event pending for an object whose DELETED event
    was lost must not resurrect it: the resync re-list supersedes the
    pre-list event backlog (informer relist semantics)."""
    kube = _cluster()
    _client, mgr = _manager(kube, TpuDriver(), full_resync_every=10 ** 9)
    mgr.audit_once()
    cur = kube.get(("", "v1", "Pod"), "p-2", "ns-2")
    cur["metadata"]["labels"] = {"x": "y"}
    kube.update(cur)
    kube.delete(("", "v1", "Pod"), "p-2", "ns-2")
    key = (("", "v1", "Pod"), "ns-2", "p-2")
    with mgr.tracker._lock:
        # simulate the DELETED event being lost mid-gap: only the stale
        # MODIFIED remains pending
        mgr.tracker._dirty[key] = ("MODIFIED", cur)
    mgr.tracker.note_gap(("", "v1", "Pod"))
    r = _key(mgr.audit_once())
    assert key not in mgr.tracker._state
    assert not any(name == "p-2" for (_c, _m, _k, _n, name, _e) in r)
    mgr.stop()


def test_delete_then_recreate_same_name_new_uid():
    """A delete + recreate under the same name but a new uid (collapsed
    into one watch gap) must apply the NEW object's state."""
    kube = _cluster()
    _client, mgr = _manager(kube, TpuDriver(), full_resync_every=10 ** 9)
    mgr.audit_once()
    key = ((("", "v1", "Pod")), "ns-0", "p-0")
    assert mgr.tracker._state[key][0] == "p-u0"
    # p-0 (i%3==0) carries a team label; the recreate drops it, so in
    # prod namespaces the prod-team violation must appear
    kube.delete(("", "v1", "Pod"), "p-0", "ns-0")
    kube.create(_pod("p-0", "ns-0", uid="p-u0-reborn"))
    r = _key(mgr.audit_once())
    assert mgr.tracker._state[key][0] == "p-u0-reborn"
    # ns-0 is env=dev in _cluster (i%2==0 -> dev): flip it to prod to
    # make the recreated pod's missing team label observable
    cur = kube.get(("", "v1", "Namespace"), "ns-0")
    cur["metadata"]["labels"]["env"] = "prod"
    kube.update(cur)
    r = _key(mgr.audit_once())
    assert any(c == "prod-team" and name == "p-0"
               for (c, _m, _k, _ns_, name, _e) in r)
    mgr.stop()


def test_strtab_snapshot_append_only():
    """The invariant the encoded-inventory cache leans on: interning
    never reassigns ids across growth."""
    from gatekeeper_tpu.ops.strtab import StringTable

    t = StringTable()
    ids = {s: t.intern(s) for s in ("a", "b", "c")}
    snap = t.snapshot()
    t.intern_many(["d", "e", "a"])
    assert t.grown_since(snap) == 2
    for s, i in ids.items():
        assert t.intern(s) == i and t.string(i) == s
