"""Full-library device compilation: coverage, bit-equality, taxonomy.

PR 10's contract: every shipped kind — both libraries, including the
cross-object join templates — evaluates through a device program, with
the interpreter demoted to a quarantine-only escape hatch. This suite
holds that with three instruments:

  * coverage: every shipped kind compiles (dense or join), and the
    checked-in `compiled_coverage.json` ratchet can only move up;
  * bit-equality: a library-wide differential sweep over a churned
    synthetic inventory — verdicts AND messages must equal the
    interpreter driver's for every kind, with the eval-path counters
    proving the device/join paths actually served;
  * taxonomy: an interpreter-bound kind records a STABLE Uncompilable
    reason code (bounded metric label set, asserted on codes not prose)
    through driver state, /debug/templates, and the
    gatekeeper_tpu_compile_fallback_total metric.

The extended-form corpus (bench_configs.EXTENDED_FORM_TEMPLATES) pins
the newly compiled upstream-canonical shapes: param key-set
comprehensions, non-var comprehension heads, multi-literal filter
bodies, derived unary builtins, `some`-decl + 2-arg-identical joins,
and inline-generator joins. Conftest pins GATEKEEPER_TPU_ASYNC_COMPILE=0
so dispatch is deterministic (device programs compile inline — forced
device, no host-warming rounds).
"""

import copy
import json
import random
import re
from pathlib import Path

import pytest

import bench_configs
from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.metrics import REGISTRY
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.ir.compile import REASON_CODES, Uncompilable
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget

LIBS = {
    "general": bench_configs.GENERAL_CONSTRAINTS,
    "pod-security-policy": bench_configs.PSP_CONSTRAINTS,
}


def mk_client(driver):
    return Backend(driver).new_client([K8sValidationTarget()])


def load_library(client, lib: str) -> list:
    kinds = []
    for name in policies.names():
        if name.startswith(lib + "/"):
            t = policies.load(name)
            client.add_template(t)
            kinds.append(t["spec"]["crd"]["spec"]["names"]["kind"])
    for kind, cname, params in LIBS[lib]:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {})})
    return sorted(kinds)


def coverage_of(drv, kinds):
    device = [k for k in kinds
              if drv.compiled_for(k) is not None
              or drv.join_for(k) is not None]
    return {"device_compiled_kinds": len(device),
            "total_kinds": len(kinds),
            "interpreter_kinds": sorted(set(kinds) - set(device))}


def lib_objects(lib: str, n: int):
    if lib == "general":
        objs = bench_configs.synth_mixed_objects(n, seed=7)
    else:
        objs = bench_configs.synth_pods_psp(n, seed=7)
    return objs


def result_key(r):
    return (r.msg, r.constraint["metadata"]["name"],
            r.constraint["kind"],
            (r.resource or {}).get("metadata", {}).get("name"),
            r.enforcement_action)


def churn(objs, rng):
    """~3% replacements (field flips) + a couple of removals, applied
    identically to every driver under comparison."""
    replaced = []
    for i in rng.sample(range(len(objs)), max(2, len(objs) // 33)):
        o = copy.deepcopy(objs[i])
        o["metadata"].setdefault("labels", {})["churned"] = "yes"
        spec = o.get("spec", {})
        for c in spec.get("containers", []) or []:
            c["image"] = "docker.io/churned:latest"
        replaced.append(o)
    removed = [objs[i] for i in rng.sample(range(len(objs)), 2)]
    return replaced, removed


# ------------------------------------------------------------- coverage


@pytest.mark.parametrize("lib", sorted(LIBS))
def test_library_device_coverage(lib):
    """Every shipped kind compiles to a device program (dense or join);
    no fallback reason is recorded for any of them."""
    drv = TpuDriver()
    client = mk_client(drv)
    kinds = load_library(client, lib)
    cov = coverage_of(drv, kinds)
    assert cov["interpreter_kinds"] == [], \
        f"{lib}: interpreter-bound kinds {cov['interpreter_kinds']} " \
        f"(reasons: {drv.fallback_reasons()})"
    assert cov["device_compiled_kinds"] == cov["total_kinds"]
    assert drv.fallback_reasons() == {}


def test_coverage_ratchet():
    """compiled_coverage.json is a two-way ratchet: regressing a kind to
    the interpreter fails, and raising coverage must update the file in
    the same PR (so the recorded floor always matches reality)."""
    recorded = json.loads(
        (Path(__file__).resolve().parent.parent / "compiled_coverage.json")
        .read_text())
    for lib in sorted(LIBS):
        drv = TpuDriver()
        client = mk_client(drv)
        kinds = load_library(client, lib)
        cov = coverage_of(drv, kinds)
        want = recorded[lib]
        assert cov == want, (
            f"{lib}: device coverage moved — measured {cov}, ratchet "
            f"records {want}. A REGRESSION (kind newly on the "
            "interpreter) must be fixed; RAISED coverage must update "
            "compiled_coverage.json in this same PR.")


# ------------------------------------------------- differential sweeps


@pytest.mark.parametrize("lib,n", [("general", 360),
                                   ("pod-security-policy", 240)])
def test_library_differential_sweep(lib, n):
    """Library-wide bit-equality: audit the full library over a churned
    synthetic inventory with the device path forced, and compare every
    verdict AND message against the interpreter driver — including the
    join kinds. The eval-path counters must show no kind served from
    the interpreter fallback."""
    rng = random.Random(5)
    objs = lib_objects(lib, n)
    dev = TpuDriver()
    # force the device path: the cost model would otherwise keep a
    # test-sized sweep on the host codegen path (legitimate in
    # production, but this test exists to prove the DEVICE programs)
    dev._use_device_for_batch = lambda pairs: True
    drivers = {"interp": RegoDriver(), "device": dev}
    clients = {}
    for name, drv in drivers.items():
        client = mk_client(drv)
        kinds = load_library(client, lib)
        for o in objs:
            client.add_data(o)
        clients[name] = client

    def results(client):
        return sorted(result_key(r) for r in client.audit().results())

    first = {name: results(c) for name, c in clients.items()}
    assert first["interp"] == first["device"]
    assert first["interp"], f"{lib}: vacuous sweep (no violations)"

    # churn both inventories identically, re-audit, compare again (the
    # delta path and the join-table invalidation must stay bit-equal)
    replaced, removed = churn(objs, rng)
    for client in clients.values():
        for o in replaced:
            client.add_data(copy.deepcopy(o))
        for o in removed:
            client.remove_data(copy.deepcopy(o))
    second = {name: results(c) for name, c in clients.items()}
    assert second["interp"] == second["device"]

    # forced-device proof: no library kind ever served via the
    # interpreter fallback path
    interp_served = sorted({k for (k, p) in dev._eval_counts
                            if p == "interp" and k in kinds})
    assert interp_served == [], \
        f"{lib}: kinds served from the interpreter: {interp_served}"


XOBJS = [
    {"apiVersion": "v1", "kind": "Pod",
     "metadata": {"name": f"p{i}", "namespace": f"ns{i % 5}",
                  "labels": ({"owner": "a", "app": "b", "team": "c"}
                             if i % 4 else {"owner": "a"})},
     "spec": {"containers": [
         {"name": "main",
          "image": ("docker.io/evil7:latest" if i % 11 == 0 else
                    "Docker.IO/app:v1" if i % 7 == 0 else
                    "gcr.io/corp/app:v1"),
          **({} if i % 3 == 0 else
             {"securityContext": {"runAsNonRoot": i % 2 == 0}})}]}}
    for i in range(80)
] + [
    {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
     "metadata": {"name": f"ing{i}", "namespace": f"ns{i % 3}",
                  "uid": f"uid-ing{i}"},
     "spec": {"rules": [{"host": f"h{i % 6}.example.com"}]}}
    for i in range(16)
] + [
    {"apiVersion": "v1", "kind": "Service",
     "metadata": {"name": f"svc{i}", "namespace": f"ns{i % 3}"},
     "spec": {"selector": {"app": f"app{i % 5}"}}}
    for i in range(12)
]

XREVIEWS = [
    {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
     "metadata": {"name": "new", "namespace": "ns9"},
     "spec": {"rules": [{"host": "h0.example.com"}]}},
    {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
     "metadata": {"name": "ing1", "namespace": "ns1", "uid": "uid-ing1"},
     "spec": {"rules": [{"host": "h-solo.example.com"}]}},
    {"apiVersion": "v1", "kind": "Service",
     "metadata": {"name": "svc1", "namespace": "ns1"},
     "spec": {"selector": {"app": "app1"}}},
    {"apiVersion": "v1", "kind": "Pod",
     "metadata": {"name": "naked", "namespace": "ns0",
                  "labels": {"owner": "a"}},
     "spec": {"containers": [{"name": "m",
                              "image": "docker.io/evil7:latest"}]}},
]


@pytest.mark.parametrize(
    "kind", [k for k, _, _ in bench_configs.EXTENDED_FORM_TEMPLATES])
def test_extended_form_differential(kind):
    """Each newly compiled upstream-canonical form is bit-equal to the
    interpreter across audit AND admission, and actually lands on the
    device (dense) or join path — not the interpreter fallback."""
    tmpl, params = next((t, p) for k, t, p
                        in bench_configs.EXTENDED_FORM_TEMPLATES
                        if k == kind)
    outs = {}
    for name, drv_cls in (("interp", RegoDriver), ("device", TpuDriver)):
        drv = drv_cls()
        client = mk_client(drv)
        client.add_template(tmpl)
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": "x"},
            "spec": ({"parameters": params} if params else {})})
        for o in XOBJS:
            client.add_data(copy.deepcopy(o))
        out = [sorted(result_key(r) for r in client.audit().results())]
        for rv in XREVIEWS:
            out.append(sorted(
                r.msg for r in client.review(
                    AugmentedUnstructured(copy.deepcopy(rv))).results()))
        outs[name] = out
        if drv_cls is TpuDriver:
            assert (drv.compiled_for(kind) is not None
                    or drv.join_for(kind) is not None), \
                f"{kind} interpreter-bound: {drv.fallback_reasons()}"
    assert outs["interp"] == outs["device"]
    assert any(any(x) for x in outs["interp"]), f"{kind}: vacuous scenario"


def test_multiclause_identity_differential():
    """An identity fn with TWO clauses (ns/name OR uid) — the exclusion
    must hold when EITHER clause identifies the review's own stored
    copy, on both the host probe and the device membership path."""
    rego = """
package xuniquehostmulti

identical(obj, review) {
  obj.metadata.namespace == review.object.metadata.namespace
  obj.metadata.name == review.object.metadata.name
}

identical(obj, review) {
  obj.metadata.uid == review.uid
}

violation[{"msg": msg}] {
  input.review.kind.kind == "Ingress"
  host := input.review.object.spec.rules[_].host
  other := data.inventory.namespace[ns][apiv]["Ingress"][name]
  other.spec.rules[_].host == host
  not identical(other, input.review)
  msg := sprintf("host conflict <%v>", [host])
}
"""
    tmpl = bench_configs._xtemplate("XUniqueHostMulti", rego)
    outs = {}
    for drv_cls in (RegoDriver, TpuDriver):
        drv = drv_cls()
        client = mk_client(drv)
        client.add_template(tmpl)
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "XUniqueHostMulti", "metadata": {"name": "m"},
            "spec": {}})
        for o in XOBJS:
            client.add_data(copy.deepcopy(o))
        out = [sorted(result_key(r) for r in client.audit().results())]
        # own copy via ns/name; own copy via uid only (renamed); true
        # conflict
        for rv in [
            {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
             "metadata": {"name": "ing2", "namespace": "ns2",
                          "uid": "uid-ing2"},
             "spec": {"rules": [{"host": "solo-h.example.com"}]}},
            {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
             "metadata": {"name": "renamed", "namespace": "nsX",
                          "uid": "uid-ing3"},
             "spec": {"rules": [{"host": "h3.example.com"}]}},
            {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
             "metadata": {"name": "clash", "namespace": "nsY",
                          "uid": "uid-clash"},
             "spec": {"rules": [{"host": "h0.example.com"}]}},
        ]:
            res = client.review(AugmentedUnstructured(rv)).results()
            out.append(sorted(r.msg for r in res))
        outs[drv_cls.__name__] = out
        if drv_cls is TpuDriver:
            jc = drv.join_for("XUniqueHostMulti")
            assert jc is not None
            assert len(jc.prog.clauses[0].rev_ident) == 2
    assert outs["RegoDriver"] == outs["TpuDriver"]
    # the scenario must be non-vacuous in both directions
    assert outs["RegoDriver"][3], "true conflict must fire"


# -------------------------------------------------------------- taxonomy


def test_fallback_reason_taxonomy():
    """An interpreter-bound kind records a STABLE reason code — in
    fallback_reasons(), /debug/templates, and the bounded-label
    gatekeeper_tpu_compile_fallback_total metric."""
    drv = TpuDriver()
    client = mk_client(drv)
    # review-pure kind outside the subset: dense reason is actionable
    client.add_template(bench_configs._xtemplate("XUnsupportedCall", """
package xunsupportedcall

violation[{"msg": msg}] {
  x := object.get(input.review.object, "spec", {})
  x.hostNetwork
  msg := "no host network"
}
"""))
    # data-reading kind outside the join shape: join reason wins
    client.add_template(bench_configs._xtemplate("XNegatedGenerator", """
package xnegatedgenerator

violation[{"msg": msg}] {
  not data.inventory.cluster["v1"]["Namespace"][input.review.object.metadata.namespace]
  msg := "namespace not synced"
}
"""))
    reasons = drv.fallback_reasons()
    assert reasons["XUnsupportedCall"]["reason"] == "call"
    assert reasons["XUnsupportedCall"]["dense"]["code"] == "call"
    assert reasons["XNegatedGenerator"]["reason"] == "join-generator"
    for ent in reasons.values():
        assert ent["reason"] in REASON_CODES
        assert ent["dense"]["code"] in REASON_CODES
        if ent["join"] is not None:
            assert ent["join"]["code"] in REASON_CODES
    # /debug/templates carries the same record per kind
    debug = drv.templates_debug()["templates"]
    assert debug["XUnsupportedCall"]["state"] == "interpreter"
    assert debug["XUnsupportedCall"]["fallback"]["reason"] == "call"
    assert debug["XNegatedGenerator"]["fallback"]["join"]["code"] == \
        "join-generator"
    # device-compiled kinds carry no fallback record
    client.add_template(policies.load("general/httpsonly"))
    assert drv.templates_debug()["templates"]["K8sHttpsOnly"][
        "fallback"] is None
    # the metric labels on the bounded code set
    text = REGISTRY.render()
    rows = re.findall(
        r'gatekeeper_tpu_compile_fallback_total\{([^}]*)\} (\d+)', text)
    got = {}
    for labels, val in rows:
        kind = re.search(r'kind="([^"]*)"', labels).group(1)
        reason = re.search(r'reason="([^"]*)"', labels).group(1)
        got[kind] = reason
        assert reason in REASON_CODES
    assert got.get("XUnsupportedCall") == "call"
    assert got.get("XNegatedGenerator") == "join-generator"


def test_multiline_raise_sites_carry_real_codes():
    """The two historically multi-line raise sites must report their
    dedicated codes, not the 'internal' drift guard: a parameterized
    join template → join-input, a non-emptiness set count → count."""
    drv = TpuDriver()
    client = mk_client(drv)
    client.add_template(bench_configs._xtemplate("XParamJoin", """
package xparamjoin

violation[{"msg": msg}] {
  input.parameters.enabled == true
  other := data.inventory.namespace[ns][apiv]["Ingress"][name]
  other.spec.rules[_].host == input.review.object.spec.rules[_].host
  msg := "conflict"
}
"""))
    client.add_template(bench_configs._xtemplate("XNonEmptyCount", """
package xnonemptycount

violation[{"msg": msg}] {
  provided := {k | input.review.object.metadata.labels[k]}
  count(provided) > 1
  msg := "too many labels"
}
"""))
    reasons = drv.fallback_reasons()
    assert reasons["XParamJoin"]["reason"] == "join-input"
    assert reasons["XParamJoin"]["join"]["code"] == "join-input"
    assert reasons["XNonEmptyCount"]["reason"] == "count"
    assert reasons["XNonEmptyCount"]["dense"]["code"] == "count"
    assert not any(e["reason"] == "internal" for e in reasons.values())


def test_unknown_reason_code_folds_to_internal():
    """Taxonomy drift (a raise site with a stray code) must not widen
    the metric label set — it folds into the stable 'internal' code."""
    e = Uncompilable("no-such-code", "something odd")
    assert e.code == "internal"
    assert "no-such-code" in e.detail
    e2 = Uncompilable("guard", "prose")
    assert e2.code == "guard" and str(e2) == "guard: prose"


def test_template_update_clears_fallback():
    """Re-ingesting a kind with a now-compilable body drops its
    fallback record (and the debug state flips to compiled)."""
    drv = TpuDriver()
    client = mk_client(drv)
    bad = bench_configs._xtemplate("XFlips", """
package xflips

violation[{"msg": msg}] {
  x := object.get(input.review.object, "spec", {})
  x.bad
  msg := "bad"
}
""")
    client.add_template(bad)
    assert "XFlips" in drv.fallback_reasons()
    good = bench_configs._xtemplate("XFlips", """
package xflips

violation[{"msg": msg}] {
  input.review.object.spec.bad == true
  msg := "bad"
}
""")
    client.add_template(good)
    assert "XFlips" not in drv.fallback_reasons()
    assert drv.compiled_for("XFlips") is not None


# ------------------------------------------------- match-table widening


def test_match_table_vectorized_rows_bit_equal():
    """The numpy-vectorized string-family row construction must be
    bit-equal to the per-string host path (which remains the fallback
    for oversize-string windows)."""
    import numpy as np

    from gatekeeper_tpu.ops.strtab import MatchTables, StringTable

    def build(vector: bool):
        t = StringTable()
        m = MatchTables(t)
        if not vector:
            m.MAX_VECTOR_STRLEN = 0  # force the per-string path
        for i in range(4000):
            t.intern(f"reg-{i % 37}.example.com/app-{i}:v{i % 5}")
        t.intern("")            # empty string
        t.intern("x" * 600)     # oversize row (vetoes vectorization)
        for i in range(7):
            m.row("startswith", f"reg-{i}.example.com/")
            m.row("endswith", f":v{i % 5}")
            m.row("contains", f"app-{i * 13}")
            m.row("eq", f"reg-1.example.com/app-{i}:v0")
            m.row("glob", f"reg-{i}.*:v1")
        return m.materialize()

    a, b = build(True), build(False)
    assert a.shape == b.shape
    assert (a == b).all()
    assert a.any(), "vacuous: no pattern matched anything"
