"""Manifest-driven end-to-end scenario suite.

The analog of the reference's bats e2e table (test/bats/test.bats: 12
admission/audit/sync scenarios against deployed manifests): every
scenario here drives the REAL entrypoint (control.main.Runtime with the
in-memory apiserver) using the agilebank demo's YAML manifests
(demo/agilebank/**, the counterpart of demo/agilebank/ + the dryrun
walkthrough), with admission requests over real HTTP against the
webhook server.
"""

import http.client
import json
from pathlib import Path

import pytest
import yaml

from gatekeeper_tpu.control.main import Runtime, build_parser

DEMO = Path(__file__).resolve().parent.parent / "demo" / "agilebank"
TEMPLATE_GVK = ("templates.gatekeeper.sh", "v1beta1", "ConstraintTemplate")
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"


def load(rel: str) -> dict:
    return yaml.safe_load((DEMO / rel).read_text())


def load_dir(rel: str) -> list[dict]:
    return [yaml.safe_load(p.read_text())
            for p in sorted((DEMO / rel).glob("*.yaml"))]


def admission_review(obj, operation="CREATE", username="alice", old=None):
    group, _, version = (obj.get("apiVersion") or "").rpartition("/")
    req = {
        "uid": "uid-e2e",
        "kind": {"group": group, "version": version, "kind": obj["kind"]},
        "operation": operation,
        "name": obj["metadata"]["name"],
        "userInfo": {"username": username},
        "object": obj if operation != "DELETE" else None,
    }
    if old is not None:
        req["oldObject"] = old
    ns_ = obj["metadata"].get("namespace")
    if ns_:
        req["namespace"] = ns_
    return {"apiVersion": "admission.k8s.io/v1beta1",
            "kind": "AdmissionReview", "request": req}


@pytest.fixture(scope="module")
def rt():
    """One Runtime for the whole scenario table, like one deployed
    cluster for the whole bats run."""
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--exempt-namespace", "gatekeeper-system",
    ])
    runtime = Runtime(args)
    runtime.args.metrics_backend = "none"
    runtime.kube.register_kind(("networking.k8s.io", "v1", "Ingress"),
                               namespaced=True)
    # the namespaces the demo manifests deploy into — a real cluster
    # always has the Namespace object (the audit, like the reference,
    # skips objects whose namespace cannot be fetched)
    for ns_name in ("gatekeeper-system", "payments", "production",
                    "staging"):
        runtime.kube.create({"apiVersion": "v1", "kind": "Namespace",
                             "metadata": {"name": ns_name}})
    runtime.start()
    yield runtime
    runtime.stop()


def post(rt, path: str, payload: dict) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", rt.webhook.port,
                                      timeout=10)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    return json.loads(conn.getresponse().read())


def admit(rt, obj, **kw) -> dict:
    return post(rt, "/v1/admit", admission_review(obj, **kw))["response"]


# --- the scenario table (ordered; module-scoped runtime carries state) --


def test_01_templates_apply_and_crds_established(rt):
    for tpl in load_dir("templates"):
        rt.kube.create(tpl)
    rt.manager.drain()
    for tpl in load_dir("templates"):
        kind = tpl["spec"]["crd"]["spec"]["names"]["kind"]
        crd = rt.kube.get(
            ("apiextensions.k8s.io", "v1beta1",
             "CustomResourceDefinition"),
            f"{kind.lower()}.{CONSTRAINT_GROUP}")
        assert crd["spec"]["names"]["kind"] == kind
        stored = rt.kube.get(TEMPLATE_GVK, tpl["metadata"]["name"])
        assert stored["status"]["created"] is True
        assert rt.opa.knows_kind(kind)


def test_02_constraints_apply_and_enforce(rt):
    for c in load_dir("constraints") + [load("dryrun/unique_ingress_host.yaml")]:
        rt.kube.create(c)
    rt.manager.drain()
    stored = rt.kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                         "all-must-have-owner")
    assert stored["status"]["byPod"][0]["enforced"] is True


def test_03_sync_config_populates_inventory(rt):
    rt.kube.create(load("sync.yaml"))
    rt.kube.create(load("existing_resources/payments_service.yaml"))
    rt.kube.create(load("dryrun/existing_ingress.yaml"))
    rt.manager.drain()
    dump = json.loads(rt.opa.dump())
    inv = dump["data"]["external"]["admission.k8s.gatekeeper.sh"]
    assert "payments" in inv["namespace"]["production"]["v1"]["Service"]
    assert "checkout" in \
        inv["namespace"]["payments"]["networking.k8s.io/v1"]["Ingress"]


def test_04_namespace_label_webhook_serving(rt):
    bad = {"apiVersion": "v1", "kind": "Namespace",
           "metadata": {"name": "sneaky",
                        "labels": {"admission.gatekeeper.sh/ignore":
                                   "yes-please"}}}
    out = post(rt, "/v1/admitlabel", admission_review(bad))
    assert out["response"]["allowed"] is False
    exempt = {"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": "gatekeeper-system",
                           "labels": {"admission.gatekeeper.sh/ignore":
                                      "no-self-managing"}}}
    out = post(rt, "/v1/admitlabel", admission_review(exempt))
    assert out["response"]["allowed"] is True


def test_05_required_labels_denies_bad_namespace(rt):
    resp = admit(rt, load("bad_resources/namespace.yaml"))
    assert resp["allowed"] is False
    assert "owner" in resp["status"]["reason"]
    resp = admit(rt, load("good_resources/namespace.yaml"))
    assert resp["allowed"] is True


def test_06_container_limits(rt):
    assert admit(rt, load("bad_resources/opa_no_limits.yaml"))["allowed"] \
        is False
    assert admit(rt,
                 load("bad_resources/opa_limits_too_high.yaml"))["allowed"] \
        is False
    assert admit(rt, load("good_resources/opa.yaml"))["allowed"] is True


def test_07_allowed_repos_in_production(rt):
    resp = admit(rt, load("bad_resources/opa_wrong_repo.yaml"))
    assert resp["allowed"] is False
    assert "repo" in resp["status"]["reason"]


def test_08_unique_service_selector_join(rt):
    resp = admit(rt, load("bad_resources/duplicate_service.yaml"))
    assert resp["allowed"] is False
    assert "same selector" in resp["status"]["reason"]
    distinct = {"apiVersion": "v1", "kind": "Service",
                "metadata": {"name": "ledger", "namespace": "production"},
                "spec": {"selector": {"app": "ledger"},
                         "ports": [{"port": 80}]}}
    assert admit(rt, distinct)["allowed"] is True


def test_09_dryrun_constraint_allows_but_audits(rt):
    conflicting = load("dryrun/conflicting_ingress.yaml")
    # dryrun: admission must NOT deny the conflicting ingress
    assert admit(rt, conflicting)["allowed"] is True
    # ... but the audit must report it once it exists in the cluster
    rt.kube.create(conflicting)
    rt.manager.drain()
    rt.audit.audit_once()
    stored = rt.kube.get((CONSTRAINT_GROUP, "v1beta1",
                          "K8sUniqueIngressHost"), "unique-ingress-host")
    viol = stored["status"].get("violations") or []
    assert any(v["enforcementAction"] == "dryrun" for v in viol)
    assert {v["name"] for v in viol} >= {"checkout", "checkout-v2"}


def test_10_audit_reports_required_label_violations(rt):
    rt.kube.create({"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "ownerless"}})
    rt.manager.drain()
    rt.audit.audit_once()
    stored = rt.kube.get((CONSTRAINT_GROUP, "v1beta1",
                          "K8sRequiredLabels"), "all-must-have-owner")
    viol = stored["status"].get("violations") or []
    assert any(v["name"] == "ownerless" for v in viol)
    assert stored["status"]["totalViolations"] >= 1
    assert any("owner" in v["message"] for v in viol)


def test_11_remediated_resources_pass(rt):
    fixed = load("bad_resources/namespace.yaml")
    fixed["metadata"]["labels"] = {"owner": "treasury.agilebank.demo"}
    assert admit(rt, fixed)["allowed"] is True


def test_12_deleting_constraint_stops_enforcement(rt):
    rt.kube.delete((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                   "all-must-have-owner")
    rt.manager.drain()
    assert admit(rt, load("bad_resources/namespace.yaml"))["allowed"] \
        is True


def test_13_health_endpoints(rt):
    """healthz/readyz on --health-addr (reference main.go:205-212)."""
    assert rt.health is not None, "--health-addr must serve"
    conn = http.client.HTTPConnection("127.0.0.1", rt.health.port,
                                      timeout=10)
    conn.request("GET", "/healthz")
    resp = conn.getresponse()
    assert resp.status == 200 and resp.read() == b"ok"
    conn.request("GET", "/readyz")
    resp = conn.getresponse()
    assert resp.status == 200
    conn.request("GET", "/nosuch")
    assert conn.getresponse().status == 404


def test_14_example_fixtures_end_to_end():
    """The example/ content dir (reference example/{templates,
    constraints,resources}): template + namespaceSelector constraint +
    resources drive admission and discovery audit on a fresh runtime."""
    ex = Path(__file__).resolve().parent.parent / "example"
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
    ])
    runtime = Runtime(args)
    runtime.args.metrics_backend = "none"
    runtime.start()
    try:
        kube = runtime.kube
        kube.create(yaml.safe_load(
            (ex / "templates/required-labels.yaml").read_text()))
        runtime.manager.drain()
        kube.create(yaml.safe_load(
            (ex / "constraints/pods-in-prod-namespaces.yaml").read_text()))
        runtime.manager.drain()
        kube.create(yaml.safe_load(
            (ex / "resources/prod-namespace.yaml").read_text()))
        bad_pod = yaml.safe_load((ex / "resources/bad-pod.yaml").read_text())
        out = runtime.webhook.validation.handle(admission_review(bad_pod))
        assert out["response"]["allowed"] is False
        assert "owner" in out["response"]["status"]["reason"]
        # a pod in a namespace the selector does not match sails through
        kube.create({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "dev-sandbox"}})
        free_pod = json.loads(json.dumps(bad_pod))
        free_pod["metadata"]["namespace"] = "dev-sandbox"
        out = runtime.webhook.validation.handle(admission_review(free_pod))
        assert out["response"]["allowed"] is True
        # discovery audit resolves the selector from the live cluster
        kube.create(bad_pod)
        runtime.audit.audit_once()
        stored = kube.get((CONSTRAINT_GROUP, "v1beta1",
                           "K8sRequiredLabelsList"),
                          "prod-pods-must-have-owner")
        viol = stored["status"].get("violations") or []
        assert any(v["name"] == "checkout-worker" for v in viol), viol
    finally:
        runtime.stop()
