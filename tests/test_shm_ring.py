"""Shared-memory admission backplane (ISSUE 14 tentpole): ring
allocator semantics, zero-copy descriptor frames, lifecycle under
crashes, the inline fallback under exhaustion, bulk/streaming ingest,
and the vectored `_send_frame`.

Covers the acceptance contract directly:
  * zero per-review payload copies across the backplane on the happy
    path — asserted by spying on every frame's byte count (descriptor
    Q/'r' frames stay tens of bytes while the reviews are KBs);
  * cross-process zero-copy — a write-then-mutate canary proves the
    reader's memoryview IS the writer's mapping, not a copy;
  * inline-payload fallback under ring exhaustion, verdicts still
    correct;
  * frontend SIGKILL with descriptors in flight — the engine detaches
    and keeps serving, the supervisor sweeps the dead child's segments
    and the respawned frontend gets a fresh ring;
  * engine kill + reconnect re-handshakes the ring (descriptors only
    flow after a fresh A-frame ack).

Every test runs under a hard SIGALRM timeout (repo convention).
"""

from __future__ import annotations

import http.client
import json
import signal
import struct
import subprocess
import sys
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control import shm
from gatekeeper_tpu.control.backplane import (
    BackplaneClient,
    BackplaneEngine,
    BackplaneError,
    FrontendServer,
    FrontendSupervisor,
    default_socket_path,
)
from gatekeeper_tpu.control.webhook import (
    AdmissionDeadline,
    AdmissionShed,
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
)
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"
PER_TEST_TIMEOUT_S = 120

pytestmark = pytest.mark.skipif(not shm.supported(),
                                reason="no shared_memory support")


@pytest.fixture(autouse=True)
def _hard_timeout():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _policy_client():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    return client


def _review(name, labels=None, uid=None, pad=0):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": "d"}}
    if labels:
        obj["metadata"]["labels"] = labels
    if pad:
        obj["metadata"]["annotations"] = {"pad": "x" * pad}
    return {"apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": uid or f"uid-{name}",
                        "operation": "CREATE",
                        "kind": {"group": "", "version": "v1",
                                 "kind": "Pod"},
                        "name": name, "namespace": "d",
                        "userInfo": {"username": "ring"},
                        "object": obj}}


def _body(name, labels=None, uid=None, pad=0):
    return json.dumps(_review(name, labels, uid, pad)).encode()


# ------------------------------------------------------- ring allocator


def test_ring_append_release_wraparound_integrity():
    """Records allocate FIFO, release out of order, reclaim in FIFO
    order, and wrap at the end without ever straddling it — payload
    bytes survive bit-exact through many laps."""
    seg = shm.create("gk-test-ring-unit", 4096)
    try:
        w = shm.RingWriter(seg)
        r = shm.RingReader(seg)
        import random
        rng = random.Random(7)
        outstanding = []
        for i in range(400):
            data = bytes([i % 251]) * rng.randrange(1, 700)
            off = w.append(data)
            while off is None:
                # exhausted: release oldest records until FIFO space
                # frees up (one record may not be enough near a wrap
                # gap); an empty ring must never refuse
                assert outstanding, "empty ring refused an alloc"
                o_off, o_data = outstanding.pop(0)
                assert bytes(r.view(o_off, len(o_data))) == o_data
                r.release(o_off)
                off = w.append(data)
            outstanding.append((off, data))
            # release a random prefix sometimes (out-of-order consume
            # happens at the record level, reclaim stays FIFO)
            while outstanding and rng.random() < 0.4:
                o_off, o_data = outstanding.pop(0)
                assert bytes(r.view(o_off, len(o_data))) == o_data
                r.release(o_off)
        for o_off, o_data in outstanding:
            assert bytes(r.view(o_off, len(o_data))) == o_data
            r.release(o_off)
        assert w.used_fraction() == 0.0
        r.close()
        w.close()
    finally:
        shm.unlink("gk-test-ring-unit")


def test_ring_watermark_oversize_and_cancel():
    seg = shm.create("gk-test-ring-wm", 4096)
    try:
        w = shm.RingWriter(seg)
        # oversized single item refuses (max_item fraction of the ring)
        assert w.append(b"z" * 2000) is None
        assert w.fallbacks == 1
        # fill past the watermark: allocs succeed until headroom runs
        # out, then None without blocking
        offs = []
        while True:
            off = w.append(b"a" * 500)
            if off is None:
                break
            offs.append(off)
        assert offs, "nothing allocated before exhaustion"
        assert w.used_fraction() > 0.5
        # cancel frees the slots without a reader
        for off in offs:
            w.cancel(off)
        assert w.append(b"b" * 500) is not None
    finally:
        shm.unlink("gk-test-ring-wm")


def test_cross_process_zero_copy_canary():
    """The reader's memoryview IS the writer's mapping: a child
    process writes a canary into the segment, the parent slices a view
    once, then the child mutates one byte — the parent's EXISTING view
    reflects it. A copy anywhere between the processes fails this."""
    seg = shm.create("gk-test-ring-canary", 4096)
    try:
        child = subprocess.Popen(
            [sys.executable, "-c", """
import sys
from multiprocessing import shared_memory
seg = shared_memory.SharedMemory(name="gk-test-ring-canary")
seg.buf[100:108] = b"CANARY00"
print("READY", flush=True)
sys.stdin.readline()
seg.buf[100] = ord("X")
print("DONE", flush=True)
seg.close()
"""],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        try:
            assert child.stdout.readline().strip() == "READY"
            view = memoryview(seg.buf)[100:108]
            assert bytes(view) == b"CANARY00"
            child.stdin.write("\n")
            child.stdin.flush()
            assert child.stdout.readline().strip() == "DONE"
            # the SAME view object sees the child's byte: shared
            # mapping, no intermediate copy
            assert bytes(view) == b"XANARY00"
            view.release()
        finally:
            child.kill()
            child.wait(timeout=10)
    finally:
        shm.unlink("gk-test-ring-canary")


# ------------------------------------------- descriptor-only happy path


def _ring_plane(ring_mb=1.0, prefix="gk-test-plane", max_wait=0.001):
    client = _policy_client()
    validation = ValidationHandler(
        client, kube=None,
        batcher=MicroBatcher(client, max_wait=max_wait))
    sock = default_socket_path() + ".ring"
    engine = BackplaneEngine(sock, validation=validation,
                             ns_label=NamespaceLabelHandler(()))
    engine.start()
    bc = BackplaneClient(sock, worker_id="ringtest", ring_mb=ring_mb,
                         ring_prefix=prefix)
    return engine, bc, validation


def _await_ring_ack(bc, timeout=5.0):
    bc.ensure_connected()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if bc._ring_ok.is_set():
            return
        time.sleep(0.01)
    raise AssertionError("engine never acked the ring handshake")


def test_descriptor_only_frames_zero_payload_on_socket(monkeypatch):
    """THE acceptance assertion: on the happy path a multi-KB review
    crosses the backplane as a ~40-byte Q descriptor and its response
    as a ~20-byte 'r' descriptor — zero payload bytes on the socket in
    either direction."""
    import gatekeeper_tpu.control.backplane as bp

    engine, bc, _ = _ring_plane()
    frames: list = []
    orig = bp._send_frame

    def spy(sock, lock, *parts):
        frames.append((bytes(parts[0][:1]),
                       4 + sum(len(p) for p in parts)))
        return orig(sock, lock, *parts)

    monkeypatch.setattr(bp, "_send_frame", spy)
    try:
        _await_ring_ack(bc)
        frames.clear()
        body = _body("big", pad=8000)
        assert len(body) > 8000
        status, out = bc.call("/v1/admit", body, 5.0,
                              time.monotonic() + 5)
        assert status == 200
        env = json.loads(bytes(out))
        assert env["response"]["allowed"] is False
        if hasattr(out, "release"):
            out.release()
        q_frames = [n for k, n in frames if k == b"Q"]
        r_frames = [n for k, n in frames if k == b"r"]
        assert q_frames and max(q_frames) < 256, \
            f"payload leaked onto the socket: Q frames {q_frames}"
        assert r_frames and max(r_frames) < 64, \
            f"response leaked onto the socket: r frames {r_frames}"
        # and the plain-R path carried no payload either
        assert not any(k == b"R" and n > 64 for k, n in frames)
    finally:
        bc.close()
        engine.stop(drain_timeout=1.0)


def test_ring_exhaustion_falls_back_inline_verdicts_correct():
    """When the ring has no space the accept path must NOT block: the
    review rides an inline frame and the verdict is identical."""
    engine, bc, _ = _ring_plane(ring_mb=0.01)  # 10 KB ring
    try:
        _await_ring_ack(bc)
        # occupy the ring directly (simulates a burst the engine has
        # not parsed yet), beyond the watermark
        held = []
        while True:
            off = bc._rings.req.append(b"x" * 600)
            if off is None:
                break
            held.append(off)
        fallbacks_before = bc._rings.req.fallbacks
        status, out = bc.call("/v1/admit", _body("noowner"), 5.0,
                              time.monotonic() + 5)
        assert status == 200
        assert json.loads(bytes(out))["response"]["allowed"] is False
        assert bc._rings.req.fallbacks > fallbacks_before
        # free the simulated backlog: the next call rides the ring
        for off in held:
            bc._rings.req.cancel(off)
        allocs_before = bc._rings.req.allocs
        status, out = bc.call("/v1/admit", _body("owned",
                                                 {"owner": "x"}),
                              5.0, time.monotonic() + 5)
        assert status == 200
        assert json.loads(bytes(out))["response"]["allowed"] is True
        assert bc._rings.req.allocs == allocs_before + 1
    finally:
        bc.close()
        engine.stop(drain_timeout=1.0)


def test_engine_kill_fails_inflight_and_ring_rehandshakes():
    """Chaos with the ring enabled: engine abort mid-flight fails the
    waiter (stance answer upstream), the ring un-acks, and a fresh
    engine re-attaches on reconnect — descriptors flow again."""
    engine, bc, validation = _ring_plane()
    sock = engine.socket_path
    try:
        _await_ring_ack(bc)
        stall = threading.Event()
        release = threading.Event()

        def evaluate(reviews):
            stall.set()
            release.wait(10)
            return [[] for _ in reviews]

        validation.batcher._evaluate = evaluate
        errs: list = []

        def call():
            try:
                bc.call("/v1/admit", _body("inflight"), 5.0,
                        time.monotonic() + 5)
            except BackplaneError as e:
                errs.append(e)

        t = threading.Thread(target=call)
        t.start()
        assert stall.wait(5), "request never reached the engine"
        engine.abort()
        t.join(timeout=10)
        release.set()
        assert errs, "in-flight descriptor did not fail on engine loss"
        assert not bc._ring_ok.is_set(), "ring stayed acked past drop"
        # outstanding request-ring slots were failed: ring is clean
        assert bc._rings.req.used_fraction() == 0.0
        # fresh engine on the same socket: reconnect re-handshakes
        client2 = _policy_client()
        engine2 = BackplaneEngine(
            sock, validation=ValidationHandler(
                client2, kube=None,
                batcher=MicroBatcher(client2, max_wait=0.001)),
            ns_label=NamespaceLabelHandler(()))
        engine2.start()
        try:
            deadline = time.monotonic() + 10
            status = None
            while time.monotonic() < deadline:
                try:
                    status, out = bc.call("/v1/admit",
                                          _body("after",
                                                {"owner": "x"}),
                                          5.0, time.monotonic() + 5)
                    break
                except BackplaneError:
                    time.sleep(0.1)
            assert status == 200
            _await_ring_ack(bc)
            allocs = bc._rings.req.allocs
            status, out = bc.call("/v1/admit", _body("ringy"),
                                  5.0, time.monotonic() + 5)
            assert status == 200
            assert bc._rings.req.allocs == allocs + 1, \
                "descriptor path did not resume after re-handshake"
        finally:
            engine2.stop(drain_timeout=1.0)
    finally:
        bc.close()
        engine.stop(drain_timeout=1.0)


# ---------------------------------------- supervisor lifecycle (SIGKILL)


def test_frontend_sigkill_fresh_ring_and_sweep():
    """kill -9 a frontend holding descriptors in flight: the engine
    detaches that ring and keeps serving, the supervisor sweeps the
    dead child's segments and the respawn gets a FRESH ring; shutdown
    leaves no /dev/shm leak."""
    client = _policy_client()
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    sock = default_socket_path() + ".sk"
    engine = BackplaneEngine(sock, validation=validation,
                             ns_label=NamespaceLabelHandler(()))
    engine.start()
    fronts = FrontendSupervisor(1, sock, port=0, addr="127.0.0.1",
                                ready_timeout=60.0, shm_ring_mb=1.0)
    import os
    ring_q = f"/dev/shm/{fronts._ring_prefix(0)}-q"

    def post(path, review, timeout=10):
        conn = http.client.HTTPConnection("127.0.0.1", fronts.port,
                                          timeout=timeout)
        try:
            conn.request("POST", path, json.dumps(review),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())
        finally:
            conn.close()

    try:
        fronts.start()
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not os.path.exists(ring_q):
            time.sleep(0.05)
        assert os.path.exists(ring_q), "frontend never created its ring"
        status, out = post("/v1/admit", _review("warm", {"owner": "x"}))
        assert status == 200 and out["response"]["allowed"] is True

        # hold an evaluation so a descriptor is in flight at kill time
        stall = threading.Event()
        release = threading.Event()
        real_eval = validation.batcher._evaluate

        def evaluate(reviews):
            stall.set()
            release.wait(5)
            return real_eval(reviews)

        validation.batcher._evaluate = evaluate
        t = threading.Thread(
            target=lambda: _swallow(post, "/v1/admit",
                                    _review("mid-kill")))
        t.start()
        assert stall.wait(5), "in-flight request never reached engine"
        victim = fronts._procs[0]
        victim.kill()  # SIGKILL: no unlink, no drain
        victim.wait(timeout=10)
        release.set()
        validation.batcher._evaluate = real_eval
        t.join(timeout=10)

        # engine survived the dead frontend; supervisor respawns with a
        # freshly swept ring and the plane serves again
        deadline = time.monotonic() + 30
        ok = False
        while time.monotonic() < deadline:
            try:
                status, out = post("/v1/admit",
                                   _review("after-respawn"),
                                   timeout=5)
                if status == 200 \
                        and out["response"]["allowed"] is False:
                    ok = True
                    break
            except (OSError, http.client.HTTPException, ValueError):
                pass
            time.sleep(0.2)
        assert ok, "plane did not recover after frontend SIGKILL"
        assert engine.alive()
    finally:
        fronts.stop()
        engine.stop(drain_timeout=1.0)
    # the supervisor swept the segments on stop: no /dev/shm leak
    assert not os.path.exists(ring_q), "ring segment leaked"


def _swallow(fn, *args):
    try:
        fn(*args)
    except Exception:
        pass


# --------------------------------------------- bulk / streaming ingest


def test_submit_many_shed_and_deadline_semantics():
    stall = threading.Event()

    def evaluate(reviews):
        stall.wait(2.0)
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=0.001, evaluate=evaluate,
                     max_queue=2)
    try:
        outs = b.submit_many([{"r": i} for i in range(4)],
                             deadline=time.monotonic() + 0.3)
        # 2 entries queued (then expired against the stalled flusher),
        # 2 shed at enqueue by the bound
        sheds = [o for o in outs if isinstance(o, AdmissionShed)]
        deads = [o for o in outs if isinstance(o, AdmissionDeadline)]
        assert len(sheds) == 2 and len(deads) == 2
        stall.set()
        time.sleep(0.1)
        outs = b.submit_many([{"ok": 1}, {"ok": 2}],
                             deadline=time.monotonic() + 5)
        assert outs == [[], []]
    finally:
        stall.set()
        b.stop()


def test_handle_bulk_orders_verdicts_and_stances():
    client = _policy_client()
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    reviews = [
        _review("bad0"),
        _review("ok1", {"owner": "me"}),
        {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
         "request": {"uid": "sa", "userInfo": {
             "username": "system:serviceaccount:gatekeeper-system:"
                         "gatekeeper-admin"}}},
        _review("bad3"),
    ]
    outs = validation.handle_bulk(reviews, time.monotonic() + 10)
    assert [o["response"]["allowed"] for o in outs] == \
        [False, True, True, False]
    assert [o["response"]["uid"] for o in outs] == \
        ["uid-bad0", "uid-ok1", "sa", "uid-bad3"]
    assert "no owner label" in outs[0]["response"]["status"]["reason"]
    validation.batcher.stop()


def test_backplane_bulk_frame_roundtrip_and_not_ready():
    engine, bc, _ = _ring_plane()
    try:
        payloads = [_body(f"blk{i}",
                          {"owner": "x"} if i % 2 else None,
                          uid=f"blk-{i}")
                    for i in range(7)]
        outs = bc.review_bulk(payloads, timeout_s=10.0)
        assert len(outs) == 7
        envs = [json.loads(o) for o in outs]
        assert [e["response"]["allowed"] for e in envs] == \
            [False, True, False, True, False, True, False]
        assert [e["response"]["uid"] for e in envs] == \
            [f"blk-{i}" for i in range(7)]
        # a not-ready engine refuses bulk frames like Q frames
        engine.ready_check = lambda: False
        with pytest.raises(BackplaneError):
            bc.review_bulk(payloads[:1], timeout_s=5.0)
        engine.ready_check = None
    finally:
        bc.close()
        engine.stop(drain_timeout=1.0)


def test_backplane_bulk_over_iov_max_payloads():
    """A >500-review B frame exceeds sendmsg's IOV_MAX iovec cap in
    both directions (request parts AND the enveloped reply) —
    _send_frame must flatten, not surface EMSGSIZE as connection
    loss."""
    engine, bc, _ = _ring_plane()
    try:
        payloads = [_body(f"iov{i}", {"owner": "x"}, uid=f"iov-{i}")
                    for i in range(600)]
        outs = bc.review_bulk(payloads, timeout_s=30.0)
        assert len(outs) == 600
        assert all(json.loads(o)["response"]["allowed"] is True
                   for o in outs)
        assert json.loads(outs[599])["response"]["uid"] == "iov-599"
    finally:
        bc.close()
        engine.stop(drain_timeout=1.0)


def test_http_respond_ring_slice_on_tls_like_socket():
    """ssl.SSLSocket.sendmsg raises NotImplementedError (not
    AttributeError): the ring-slice response path must fall back to a
    plain concat send and still release the slot."""
    from gatekeeper_tpu.control.webhook import FastHTTPServer

    seg = shm.create("gk-test-tls-resp", 4096)
    try:
        w = shm.RingWriter(seg)
        r = shm.RingReader(seg)
        off = w.append(b'{"ok":true}')
        payload = shm.RingSlice(r, off, 11)

        sent = []

        class TlsLikeConn:
            def sendmsg(self, bufs):
                raise NotImplementedError(
                    "sendmsg not allowed on instances of SSLSocket")

            def sendall(self, data):
                sent.append(bytes(data))

        FastHTTPServer._respond(TlsLikeConn(), 200, payload)
        body = b"".join(sent)
        assert body.endswith(b'{"ok":true}')
        assert b"Content-Length: 11" in body
        assert payload._released, "slot not released after TLS send"
        # released back to the allocator: the slot is reusable
        w2 = w.append(b"x" * 800)
        assert w2 is not None
        r.close()
        w.close()
    finally:
        shm.unlink("gk-test-tls-resp")


# ----------------------------------------------- _send_frame micro-bench


def test_send_frame_vectored_roundtrip_and_microbench():
    """The satellite fix: _send_frame must deliver multi-part frames
    byte-identically via sendmsg (no header+payload concat copy).
    Round-trips parts of every size class and micro-benches against
    the old concat implementation (informational print — CI boxes are
    too noisy to gate a ratio)."""
    import socket as socket_mod

    from gatekeeper_tpu.control.backplane import (
        _recv_exact,
        _send_frame,
    )

    a, b = socket_mod.socketpair()
    lock = threading.Lock()
    try:
        cases = [
            (b"Q", b"x" * 3, b"", b"tail"),
            (b"R", b"y" * 70000),            # > default socket buffer
            (memoryview(b"Z" * 1000),),
            (b"S",),
        ]
        got = []

        def reader():
            for _ in cases:
                (n,) = struct.unpack("!I", _recv_exact(b, 4))
                got.append(_recv_exact(b, n))

        t = threading.Thread(target=reader)
        t.start()
        for parts in cases:
            _send_frame(a, lock, *parts)
        t.join(timeout=10)
        assert got == [b"".join(bytes(p) for p in parts)
                       for parts in cases]

        # micro-bench: new vectored send vs the old concat send
        payload = b"p" * 4096
        n_iter = 2000

        def drain(total):
            left = total
            while left > 0:
                left -= len(b.recv(65536))

        d = threading.Thread(target=drain,
                             args=(n_iter * (4 + 1 + len(payload)),))
        d.start()
        t0 = time.perf_counter()
        for _ in range(n_iter):
            _send_frame(a, lock, b"Q", payload)
        t_new = time.perf_counter() - t0
        d.join(timeout=30)

        def old_send(sock, lck, *parts):
            pl = b"".join(parts)
            msg = struct.pack("!I", len(pl)) + pl
            with lck:
                sock.sendall(msg)

        d = threading.Thread(target=drain,
                             args=(n_iter * (4 + 1 + len(payload)),))
        d.start()
        t0 = time.perf_counter()
        for _ in range(n_iter):
            old_send(a, lock, b"Q", payload)
        t_old = time.perf_counter() - t0
        d.join(timeout=30)
        print(f"\n_send_frame 4KB x{n_iter}: vectored "
              f"{t_new * 1e6 / n_iter:.1f}us vs concat "
              f"{t_old * 1e6 / n_iter:.1f}us per frame")
    finally:
        a.close()
        b.close()
