"""Rego formatter round-trip: format(parse(src)) must re-parse to the
same AST (modulo source positions and wildcard numbering) for every
reference library template and every repo policy — the `opa fmt`
contract (vendor/.../format/format.go)."""

from dataclasses import fields, is_dataclass
from pathlib import Path

import pytest

from gatekeeper_tpu.rego import ast as A
from gatekeeper_tpu.rego.format import format_module
from gatekeeper_tpu.rego.parser import parse_module

REFERENCE = Path("/root/reference/library")
REF_SRCS = sorted(REFERENCE.glob("*/*/src.rego")) \
    if REFERENCE.exists() else []


def canon(node, wcmap):
    """Structural normal form: drop line numbers, rename wildcards in
    first-seen order (the parser numbers them globally)."""
    if isinstance(node, A.Var) and node.name.startswith("$wc"):
        if node.name not in wcmap:
            wcmap[node.name] = f"$wc{len(wcmap)}"
        return ("Var", wcmap[node.name])
    if is_dataclass(node):
        out = [type(node).__name__]
        for f in fields(node):
            if f.name in ("line", "source_name"):
                continue
            out.append((f.name, canon(getattr(node, f.name), wcmap)))
        return tuple(out)
    if isinstance(node, tuple):
        return tuple(canon(x, wcmap) for x in node)
    return node


def roundtrip(src: str) -> None:
    m1 = parse_module(src)
    text = format_module(m1)
    m2 = parse_module(text)
    c1, c2 = canon(m1, {}), canon(m2, {})
    assert c1 == c2, f"round-trip drift:\n{text}"
    # idempotence: formatting formatted source is a fixed point
    assert format_module(m2) == text


@pytest.mark.parametrize(
    "path", REF_SRCS, ids=[str(p.parent.name) for p in REF_SRCS])
def test_roundtrip_reference_library(path):
    roundtrip(path.read_text())


def test_roundtrip_repo_policies():
    from gatekeeper_tpu import policies
    for name in policies.names():
        t = policies.load(name)
        for target in t["spec"]["targets"]:
            roundtrip(target["rego"])
            for lib in target.get("libs") or []:
                roundtrip(lib)


def test_format_shapes():
    src = '''
package demo

default allow = false

allow {
  input.review.kind.kind == "Pod"
  not denied
}

denied {
  some ns
  x := data.inventory.namespace[ns][_]["Pod"][name]
  count({p | p := x.spec.containers[_].name}) > 1
  y = [u | u := x.spec.volumes[_]; u.hostPath]
  m := {k: v | v := x.metadata.labels[k]}
  z := (1 + 2) * 3
  x.spec.replicas >= -1
  arr := []
  s := set()
  obj := {"a": 1}
  f(x) with input as {"review": {}}
}

f(v) = out {
  out := v
}

items[name] {
  name := input.review.object.metadata.name
}

pairs[k] = v {
  v := input.review.object.metadata.labels[k]
}
'''
    roundtrip(src)
