"""gklint + locktrace test suite (ISSUE 15).

Fixture corpus: for every checker, a seeded-violation snippet that must
trip it and a clean twin that must stay silent — the analyzer's own
regression net. Plus the two-way baseline-ratchet semantics, the
allow-comment escape hatch, a clean-tree gate over the real repo, the
README stage-table sync, and the runtime lockset tracer (a real A->B /
B->A inversion across two threads must be detected).
"""

from __future__ import annotations

import json
import shutil
import threading

import pytest

from tools.gklint.core import Project, load_baseline, ratchet, \
    run_checkers, write_baseline
from tools.gklint.__main__ import locktrace_gate
from gatekeeper_tpu.utils import locktrace

REPO = __file__.rsplit("/tests/", 1)[0]


# --------------------------------------------------------- fixture rig

SKELETON = {
    # the declared no-block entry points must exist in a fixture
    # project or block_zone reports them missing
    "gatekeeper_tpu/control/backplane.py": """\
class BackplaneEngine:
    def _read_loop(self, conn, wlock):
        conn.recv(4)
""",
    "gatekeeper_tpu/control/webhook.py": """\
class MicroBatcher:
    def _loop(self):
        pass


class FastHTTPServer:
    def _serve_connection(self, conn):
        conn.recv(4)
""",
    "gatekeeper_tpu/control/metrics.py": """\
def run_saturation_probes():
    pass
""",
    "gatekeeper_tpu/control/adaptive.py": """\
class AdaptiveController:
    def _loop(self):
        pass
""",
}


def _project(tmp_path, files: dict) -> Project:
    merged = dict(SKELETON)
    merged.update(files)
    for rel, text in merged.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    shutil.copy(f"{REPO}/gatekeeper_tpu/control/stages.py",
                tmp_path / "gatekeeper_tpu/control/stages.py")
    return Project(str(tmp_path))


def _codes(findings, checker):
    return sorted(f.code for f in findings if f.checker == checker)


# ------------------------------------------------------------ checkers

def test_block_zone_trips_on_reachable_sleep_and_clean_twin(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/webhook.py": """\
import time


class MicroBatcher:
    def _loop(self):
        self._seal()

    def _seal(self):
        time.sleep(0.01)


class CleanBatcher:
    def _loop(self):
        self._seal()

    def _seal(self):
        time.sleep(0.01)


class FastHTTPServer:
    def _serve_connection(self, conn):
        conn.recv(4)
"""})
    found = [f for f in run_checkers(proj, {"block_zone"})
             if f.checker == "block_zone"]
    # only MicroBatcher._loop is a declared entry: CleanBatcher's
    # identical sleep is NOT reachable from any no-block zone
    assert len(found) == 1
    assert found[0].code == "sleep:time.sleep"
    assert "MicroBatcher._loop" in found[0].message


def test_block_zone_traverses_call_graph_multi_hop(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/backplane.py": """\
class BackplaneEngine:
    def _read_loop(self, conn, wlock):
        self._hop1()

    def _hop1(self):
        self._hop2()

    def _hop2(self):
        import subprocess
        subprocess.run(["true"])
        self.kube.get(("", "v1", "Namespace"), "x")
"""})
    found = [f for f in run_checkers(proj, {"block_zone"})]
    cats = {f.code.split(":")[0] for f in found}
    assert "subprocess" in cats and "kube" in cats


def test_block_zone_allow_comment_prunes_edge(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/backplane.py": """\
class BackplaneEngine:
    def _read_loop(self, conn, wlock):
        # gklint: allow(block-zone) reason=guarded by fast=True raise
        self._hop()

    def _hop(self):
        import time
        time.sleep(1)
"""})
    assert not [f for f in run_checkers(proj, {"block_zone"})]


def test_gauge_teardown_trips_and_clean_twin(tmp_path):
    body = """\
from . import metrics


class Leaky:
    def start(self):
        metrics.report_queue_depth("admission", 5, engine="1")
        metrics.register_saturation_probe("leaky", lambda: None)


class Clean:
    def start(self):
        metrics.report_queue_depth("admission", 5, engine="1")
        metrics.register_saturation_probe("clean", lambda: None)

    def stop(self):
        metrics.report_queue_depth("admission", 0, engine="1")
        metrics.unregister_saturation_probe("clean")


class CleanViaFinally:
    def _run(self):
        try:
            metrics.report_duty_cycle(0.7)
        finally:
            metrics.report_duty_cycle(0.0)
"""
    proj = _project(tmp_path,
                    {"gatekeeper_tpu/control/engine.py": body})
    found = [f for f in run_checkers(proj, {"gauge_teardown"})]
    scopes = {f.scope for f in found}
    assert scopes == {"Leaky"}
    assert sorted(f.code for f in found) == ["probe:leaky",
                                             "report_queue_depth"]


def test_clock_discipline_trips_and_clean_twin(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/x.py": """\
import time


def bad():
    t0 = time.time()
    work()
    return time.time() - t0


def bad_deadline(timeout):
    deadline = time.time() + timeout
    while time.time() < deadline:
        pass


def clean():
    t0 = time.monotonic()
    work()
    return time.monotonic() - t0


def clean_stamp():
    return {"ts": time.time()}  # storage, not arithmetic


def allowed():
    # gklint: allow(clock) reason=persisted epoch from another process
    return time.time() - 12345.0
"""})
    found = [f for f in run_checkers(proj, {"clock_discipline"})]
    assert sorted({f.scope for f in found}) == ["bad", "bad_deadline"]


def test_metrics_hygiene_trips_and_clean_twin(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/metrics.py": """\
def run_saturation_probes():
    pass


REASONS = ("a", "b")


def bad_counter():
    REGISTRY.counter_add("my_requests", "h")


def bad_histogram():
    REGISTRY.observe("my_latency_ms", "h", 1.0)


def bad_interpolated(kind):
    REGISTRY.counter_add("x_total", "h", kind=f"kind-{kind}")


def bad_unbounded(reason):
    REGISTRY.counter_add("y_total", "h", reason=reason)


def clean(reason):
    if reason not in REASONS:
        reason = "other"
    REGISTRY.counter_add("z_total", "h", reason=reason)
    REGISTRY.observe("z_seconds", "h", 1.0)
"""})
    found = [f for f in run_checkers(proj, {"metrics_hygiene"})]
    assert _codes(found, "metrics_hygiene") == [
        "counter-name:my_requests", "histogram-name:my_latency_ms",
        "interpolated-label:kind", "unbounded-label:reason"]


def test_jit_discipline_trips_and_clean_twin(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/ir/evaljax.py": """\
import jax
from .aot import AotJit


def bad(fn):
    return jax.jit(fn)


def clean(fn, store):
    return AotJit(fn, store=store, fingerprint="f", tag="t")


def allowed(fn):
    # gklint: allow(jit) reason=degrade path exercised without a store
    return jax.jit(fn)
""",
        "gatekeeper_tpu/ir/aot.py": """\
import jax


class AotJit:
    def __init__(self, fn, **kw):
        self._jit = jax.jit(fn)  # aot.py is the one sanctioned wrapper
"""})
    found = [f for f in run_checkers(proj, {"jit_discipline"})
             if f.checker == "jit_discipline"]
    assert len(found) == 1
    assert found[0].scope == "bad"


def test_stage_registry_trips_and_clean_twin(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/x.py": """\
def bad(tr):
    with tr.span("not_a_stage"):
        pass


def dynamic(tr, name):
    tr.add_phase(name, 0.1)


def clean(tr):
    with tr.span("encode"):
        pass


def allowed(tr, name):
    # gklint: allow(stage) reason=names bounded upstream
    tr.add_phase(name, 0.1)
"""})
    found = [f for f in run_checkers(proj, {"jit_discipline"})
             if f.checker == "stage_registry"]
    assert _codes(found, "stage_registry") == [
        "dynamic-stage:add_phase", "unregistered-stage:not_a_stage"]


def test_allow_comment_without_reason_is_a_finding(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/x.py": """\
import time


def f():
    # gklint: allow(clock)
    return time.time() - 1.0
"""})
    found = run_checkers(proj)
    assert any(f.checker == "allow" for f in found)
    # and the reasonless allow did NOT suppress the clock finding
    assert any(f.checker == "clock_discipline" for f in found)


# ------------------------------------------------------------- ratchet

def test_baseline_ratchet_two_way(tmp_path):
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/x.py": """\
import time


def bad():
    t0 = time.time()
    return time.time() - t0
"""})
    findings = run_checkers(proj)
    assert findings
    base = tmp_path / "gklint_baseline.json"
    write_baseline(str(base), findings)
    # exact match: clean both ways
    new, stale = ratchet(findings, load_baseline(str(base)))
    assert not new and not stale
    # a NEW finding (not in baseline) fails
    new, stale = ratchet(findings, {})
    assert new and not stale
    # a STALE suppression (baseline outlives the fix) fails --check
    new, stale = ratchet([], load_baseline(str(base)))
    assert not new and stale


def test_baseline_count_ratchet(tmp_path):
    """Same key, more occurrences than baselined -> the excess is new."""
    proj = _project(tmp_path, {
        "gatekeeper_tpu/control/x.py": """\
import time


def bad():
    a = time.time() - 1.0
    b = time.time() - 2.0
    return a + b
"""})
    findings = [f for f in run_checkers(proj)
                if f.checker == "clock_discipline"]
    assert len(findings) == 2
    key = findings[0].key()
    assert findings[1].key() == key
    new, stale = ratchet(findings, {key: 1})
    assert len(new) == 1 and not stale


# ----------------------------------------------------------- real tree

def test_real_tree_is_clean_against_baseline():
    """The committed tree must pass the same gate CI runs: no new
    findings vs gklint_baseline.json and no stale suppressions."""
    project = Project(REPO)
    findings = run_checkers(project)
    baseline = load_baseline(f"{REPO}/gklint_baseline.json")
    new, stale = ratchet(findings, baseline)
    assert not new, "\n".join(new)
    assert not stale, "\n".join(stale)


def test_stage_table_in_readme_matches_registry():
    """The README stage table renders from control/stages.py — a stage
    added to the registry must land in the docs in the same PR."""
    from gatekeeper_tpu.control.stages import STAGES, stages_markdown

    readme = open(f"{REPO}/README.md", encoding="utf-8").read()
    table = stages_markdown()
    assert table in readme, (
        "README.md stage table is stale — paste the output of "
        "`python -m tools.gklint --stages-md` into the Static "
        "analysis section")
    for name in STAGES:
        assert f"`{name}`" in readme


# ----------------------------------------------------------- locktrace

def test_locktrace_detects_cross_thread_inversion():
    """A real A->B / B->A acquisition inversion across two threads
    (sequenced so the test itself cannot deadlock) must be detected."""
    t = locktrace.LockTracer()
    lock_a = t.lock()
    lock_b = t.lock()

    def t1():
        with lock_a:
            with lock_b:
                pass

    def t2():
        with lock_b:
            with lock_a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()
    kinds = {f["kind"] for f in t.report()}
    assert "inversion" in kinds or "cycle" in kinds
    inv = [f for f in t.report() if f["kind"] in ("inversion", "cycle")]
    assert any(lock_a.site in f["sites"] and lock_b.site in f["sites"]
               for f in inv)


def test_locktrace_consistent_order_is_clean():
    t = locktrace.LockTracer()
    lock_a = t.lock()
    lock_b = t.lock()

    def ordered():
        with lock_a:
            with lock_b:
                pass

    for _ in range(3):
        th = threading.Thread(target=ordered)
        th.start()
        th.join()
    assert t.report() == []


def test_locktrace_three_party_cycle():
    """A->B, B->C, C->A — no single edge is a 2-party inversion until
    the last, but report()'s cycle search must name all three."""
    t = locktrace.LockTracer()
    # separate lines on purpose: a lock's graph node is its ALLOCATION
    # SITE, and three locks born on one line would collapse into one
    la = t.lock()
    lb = t.lock()
    lc = t.lock()

    def seq(first, second):
        with first:
            with second:
                pass

    for pair in ((la, lb), (lb, lc), (lc, la)):
        th = threading.Thread(target=seq, args=pair)
        th.start()
        th.join()
    report = t.report()
    assert any(f["kind"] in ("cycle", "inversion") for f in report)
    cyc = [f for f in report if f["kind"] == "cycle"]
    if cyc:
        assert len(cyc[0]["sites"]) == 3


def test_locktrace_held_across_blocking_and_gate(tmp_path, capsys):
    t = locktrace.LockTracer()
    lock_a = t.lock()
    with lock_a:
        t.note_blocking("time.sleep", "here:1")
    report = t.report()
    assert report and report[0]["kind"] == "held_across_blocking"
    # the CI gate treats held-across-blocking as advisory...
    dump = tmp_path / "locktrace.jsonl"
    t.dump(str(dump))
    assert locktrace_gate(str(dump)) == 0
    # ...but fails on a cycle/inversion in the same dump
    with open(dump, "a") as f:
        f.write(json.dumps({"kind": "inversion",
                            "detail": "a -> b vs b -> a"}) + "\n")
    assert locktrace_gate(str(dump)) == 1
    capsys.readouterr()


def test_locktrace_install_wraps_threading_and_condition():
    """install(force=True) patches the factories; Condition.wait over
    a traced RLock keeps the per-thread lockset honest (the private
    _release_save protocol), so waiting does not fabricate edges."""
    if locktrace.tracer() is not None:
        # an ARMED suite run (GATEKEEPER_TPU_LOCKTRACE=1) already owns
        # the global install; uninstalling here would silently untrace
        # every suite collected after this one
        pytest.skip("global lockset tracer already armed for this run")
    tr = locktrace.install(force=True)
    try:
        lk = threading.Lock()
        assert lk.__class__.__name__ == "_TracedLock"
        cond = threading.Condition()
        other = threading.Lock()

        def waiter():
            with cond:
                cond.wait(timeout=0.05)

        th = threading.Thread(target=waiter)
        th.start()
        th.join()
        # while nothing was held, an unrelated acquisition after the
        # wait must not have recorded edges from the condition lock.
        # (filter to THIS file's lock sites: the global install also
        # traces unrelated background threads' locks)
        with other:
            pass
        mine = [f for f in tr.report()
                if any(__file__ in s for s in f.get("sites", ()))]
        assert mine == []
    finally:
        locktrace.uninstall()
