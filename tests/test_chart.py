"""Helm chart (chart/gatekeeper-tpu) render + sanity.

No helm binary is baked into the image, so the test renders the chart
with a minimal substituter covering exactly the template constructs the
chart uses ({{ .Values.* }}, {{ toYaml .Values.x | indent N }}, and
non-nested {{- if .Values.x }}...{{- end }} truthy guards) and then
runs the same structural checks CI applies to the flat manifest —
rendered output and flat manifest must describe the same objects.
"""

import re
from pathlib import Path

import yaml

CHART = Path(__file__).resolve().parent.parent / "chart" / "gatekeeper-tpu"
FLAT = Path(__file__).resolve().parent.parent / "deploy" / \
    "gatekeeper-tpu.yaml"


def render(values: dict) -> str:
    tpl = (CHART / "templates" / "gatekeeper-tpu.yaml").read_text()

    def lookup(path: str):
        node = values
        for seg in path.split("."):
            node = node[seg]
        return node

    def sub_value(m):
        return str(lookup(m.group(1)))

    def sub_toyaml(m):
        node = lookup(m.group(1))
        ind = int(m.group(2))
        text = yaml.safe_dump(node, default_flow_style=False).rstrip()
        return "\n".join(" " * ind + ln for ln in text.splitlines())

    def sub_if(m):
        return m.group(2) if lookup(m.group(1)) else ""

    # non-nested truthy guards: the block renders iff the value is
    # truthy (Helm semantics for the scalars this chart guards on)
    out = re.sub(r"\{\{-\s*if\s+\.Values\.([\w.]+)\s*\}\}\n(.*?)"
                 r"\{\{-\s*end\s*\}\}\n", sub_if, tpl, flags=re.S)
    out = re.sub(r"\{\{\s*toYaml\s+\.Values\.([\w.]+)\s*\|\s*indent\s+"
                 r"(\d+)\s*\}\}", sub_toyaml, out)
    out = re.sub(r"\{\{\s*\.Values\.([\w.]+)\s*\}\}", sub_value, out)
    assert "{{" not in out, "unrendered template construct"
    return out


def default_values() -> dict:
    return yaml.safe_load((CHART / "values.yaml").read_text())


def test_chart_renders_and_matches_flat_manifest_shape():
    docs = [d for d in yaml.safe_load_all(render(default_values()))
            if d is not None]
    flat = [d for d in yaml.safe_load_all(FLAT.read_text())
            if d is not None]
    kinds = sorted((d["kind"], d["metadata"]["name"]) for d in docs)
    flat_kinds = sorted((d["kind"], d["metadata"]["name"]) for d in flat)
    assert kinds == flat_kinds, "chart and flat manifest diverged"
    assert len(docs) >= 12


def test_chart_values_reach_rendered_objects():
    vals = default_values()
    vals["replicas"] = 3
    vals["auditInterval"] = 123
    vals["auditShards"] = 4
    vals["logLevel"] = "DEBUG"
    vals["image"]["release"] = "v9.9"
    vals["resources"]["limits"]["memory"] = "4Gi"
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    deps = {d["metadata"]["name"]: d for d in docs
            if d["kind"] == "Deployment"}
    webhook = deps["gatekeeper-controller-manager"]
    audit = deps["gatekeeper-audit"]
    assert webhook["spec"]["replicas"] == 3
    assert audit["spec"]["replicas"] == 1  # audit stays a singleton
    ac = audit["spec"]["template"]["spec"]["containers"][0]
    assert ac["image"] == "gatekeeper-tpu:v9.9"
    assert "--audit-interval=123" in ac["args"]
    assert "--audit-shards=4" in ac["args"]
    assert "--log-level=DEBUG" in ac["args"]
    assert any("--constraint-violations-limit=20" == a for a in ac["args"])
    assert ac["resources"]["limits"]["memory"] == "4Gi"


def test_chart_webhook_fail_open_preserved():
    docs = [d for d in yaml.safe_load_all(render(default_values()))
            if d is not None]
    vwh = [d for d in docs
           if d["kind"] == "ValidatingWebhookConfiguration"]
    assert vwh, "no ValidatingWebhookConfiguration in the chart"
    policies = {w["name"]: w.get("failurePolicy")
                for w in vwh[0]["webhooks"]}
    # reference stance: validation fails open; the ignore-label guard
    # fails closed (protects the exemption label itself)
    assert policies["validation.gatekeeper.sh"] == "Ignore"
    assert policies["check-ignore-label.gatekeeper.sh"] == "Fail"


def test_chart_streaming_and_preview_values_reach_deployments():
    vals = default_values()
    vals["streamAudit"]["windowMs"] = 40
    vals["preview"]["auditPort"] = 9444
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    deps = {d["metadata"]["name"]: d for d in docs
            if d["kind"] == "Deployment"}
    ac = deps["gatekeeper-audit"]["spec"]["template"]["spec"][
        "containers"][0]
    # streaming implies the incremental watch-fed inventory
    assert "--audit-incremental=True" in ac["args"]
    assert "--stream-audit=True" in ac["args"]
    assert "--stream-window-ms=40" in ac["args"]
    assert "--stream-max-batch=512" in ac["args"]
    # the audit pod's dedicated preview listener + its containerPort
    assert "--preview-endpoint=True" in ac["args"]
    assert "--preview-port=9444" in ac["args"]
    assert any(p.get("name") == "preview"
               and p["containerPort"] == 9444 for p in ac["ports"])
    wc = deps["gatekeeper-controller-manager"]["spec"]["template"][
        "spec"]["containers"][0]
    assert "--preview-endpoint=True" in wc["args"]
    # the documented disable value must render a VALID Deployment:
    # auditPort=0 must not emit containerPort: 0 (rejected by the API)
    vals["preview"]["auditPort"] = 0
    # disabling streaming must NOT drag the incremental inventory down
    # with it — the knobs are independent (auditIncremental)
    vals["streamAudit"]["enabled"] = False
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    ac = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-audit"][
        "spec"]["template"]["spec"]["containers"][0]
    assert "--preview-port=0" in ac["args"]
    assert all(p.get("name") != "preview" for p in ac["ports"])
    assert "--stream-audit=False" in ac["args"]
    assert "--audit-incremental=True" in ac["args"]


def test_chart_ring_and_ingest_values_reach_webhook_deployment():
    vals = default_values()
    vals["admission"]["shmRingMb"] = 16
    vals["ingest"]["port"] = 51000
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    wc = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-controller-manager"][
        "spec"]["template"]["spec"]["containers"][0]
    assert "--admission-shm-ring-mb=16" in wc["args"]
    assert "--ingest-grpc" in wc["args"]
    assert "--ingest-port=51000" in wc["args"]
    assert any(p.get("name") == "grpc-ingest"
               and p["containerPort"] == 51000 for p in wc["ports"])
    # disabling the ingest endpoint drops BOTH the flags and the port
    # (no invalid containerPort, no dangling --ingest-grpc)
    vals["ingest"]["enabled"] = False
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    wc = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-controller-manager"][
        "spec"]["template"]["spec"]["containers"][0]
    assert "--ingest-grpc" not in wc["args"]
    assert all(p.get("name") != "grpc-ingest" for p in wc["ports"])
    # rings stay on independently of the ingest endpoint
    assert "--admission-shm-ring-mb=16" in wc["args"]


def test_chart_adaptive_control_values_reach_webhook_deployment():
    # default ships the kill switch: knobs hold their baselines
    docs = [d for d in yaml.safe_load_all(render(default_values()))
            if d is not None]
    wc = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-controller-manager"][
        "spec"]["template"]["spec"]["containers"][0]
    assert "--adaptive-control=False" in wc["args"]
    assert "--adaptive-interval=1" in wc["args"]
    assert "--adaptive-hysteresis=10" in wc["args"]
    # arming the controller is a values flip, not a template edit
    vals = default_values()
    vals["adaptive"]["enabled"] = True
    vals["adaptive"]["intervalSeconds"] = 2
    vals["adaptive"]["hysteresisSeconds"] = 30
    docs = [d for d in yaml.safe_load_all(render(vals)) if d is not None]
    wc = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-controller-manager"][
        "spec"]["template"]["spec"]["containers"][0]
    assert "--adaptive-control=True" in wc["args"]
    assert "--adaptive-interval=2" in wc["args"]
    assert "--adaptive-hysteresis=30" in wc["args"]
    # the audit pod runs no admission batcher: the controller flag
    # stays off its container (it would only watch)
    ac = {d["metadata"]["name"]: d for d in docs
          if d["kind"] == "Deployment"}["gatekeeper-audit"][
        "spec"]["template"]["spec"]["containers"][0]
    assert not any(a.startswith("--adaptive") for a in ac["args"])
