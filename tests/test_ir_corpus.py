"""Device-path conformance over the reference's own test corpus.

For every library template that compiles to the device path, harvest the
input documents its src_test.rego builds (evaluating the test files'
helper functions with our interpreter), then check the core invariant on
each: the device filter must fire for every input where the interpreter
finds violations (never under-fire; over-fire is allowed — the host
re-check is authoritative). Also asserts end-to-end client audit parity
(TpuDriver vs RegoDriver) over the harvested objects.

Reference corpus: /root/reference/library/**/src_test.rego (SURVEY.md §4
tier 1 — the same suites the interpreter conformance tests run).
"""

from __future__ import annotations

import functools
import glob
from dataclasses import replace as dc_replace
from pathlib import Path

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.rego import ast as A
from gatekeeper_tpu.rego.interp import Interpreter, UNDEF
from gatekeeper_tpu.rego.parser import parse_module
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.values import thaw, freeze

from .conftest import REFERENCE, requires_reference

TARGET = "admission.k8s.gatekeeper.sh"

LIB_DIRS = sorted(
    str(Path(p).parent.relative_to(REFERENCE))
    for p in glob.glob(str(REFERENCE / "library/*/*/src_test.rego"))
) if REFERENCE.exists() else []

# cross-object templates: compiled by the inventory-join compiler
# (ir/join.py) instead of the elementwise device compiler
JOIN_COMPILED = {
    "library/general/uniqueingresshost",
    "library/general/uniqueserviceselector",
}


def _kind_for(pkg_name: str) -> str:
    return "T" + pkg_name.capitalize()


def harvest_inputs(src: str, test_src: str, pkg: tuple = None) -> list[dict]:
    """Evaluate each test rule's `... with input as X` document.
    Cached per (src, test_src): several suites replay the same corpus."""
    return [doc for doc, _ in _harvest_cached(src, test_src)]


def harvest_cases(src: str, test_src: str) -> list[tuple[dict, dict]]:
    """(input document, data.inventory with-value or None) pairs."""
    return list(_harvest_cached(src, test_src))


@functools.lru_cache(maxsize=64)
def _harvest_cached(src: str, test_src: str) -> tuple:
    src_mod = parse_module(src)
    test_mod = parse_module(test_src)
    docs = []
    harvest_rules = []
    n = 0
    for r in test_mod.rules:
        if not r.name.startswith("test_"):
            continue
        for i, lit in enumerate(r.body):
            wv = None
            iv = None
            for w in lit.withs:
                if tuple(w.target) == ("input",):
                    wv = w.value
                elif tuple(w.target) == ("data", "inventory"):
                    iv = w.value
            if wv is None:
                continue
            n += 1
            head = A.ObjectLit((
                (A.Scalar("input"), wv),
                (A.Scalar("inventory"),
                 iv if iv is not None else A.Scalar(None)),
            ))
            harvest_rules.append(A.Rule(
                name=f"__harvest_{n}", kind="complete", value=head,
                body=tuple(dc_replace(l, withs=()) for l in r.body[:i]),
            ))
            break
    hmod = dc_replace(test_mod, rules=test_mod.rules + tuple(harvest_rules))
    interp = Interpreter({"src": src_mod, "test": hmod})
    for r in harvest_rules:
        try:
            v = interp.eval_rule(src_mod.package, r.name)
        except Exception:
            continue
        if v is UNDEF:
            continue
        case = thaw(freeze(v))
        doc = case.get("input")
        if isinstance(doc, dict) and "review" in doc:
            docs.append((_complete_review(doc), case.get("inventory")))
    return tuple(docs)


def _complete_review(doc: dict) -> dict:
    """Fill in review.kind from the object's apiVersion/kind when the test
    fixture omits it. The live system always populates it — the webhook
    from the AdmissionRequest, the audit path when wrapping Unstructured
    objects (reference pkg/target/target.go:91-127) — so fixtures relying
    on input.review.kind (e.g. httpsonly's group/kind guard) only exercise
    their violating path with it present."""
    review = doc.get("review")
    if not isinstance(review, dict) or "kind" in review:
        return doc
    obj = review.get("object")
    if not isinstance(obj, dict) or "kind" not in obj:
        return doc
    api = obj.get("apiVersion") or ""
    group, _, version = api.rpartition("/")
    review["kind"] = {"group": group, "version": version or api,
                      "kind": obj["kind"]}
    return doc


def _template_for(dirpath: str) -> tuple[dict, str]:
    src = (REFERENCE / dirpath / "src.rego").read_text()
    pkg_name = parse_module(src).package[-1]
    kind = _kind_for(pkg_name)
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": TARGET, "rego": src}],
        },
    }
    return template, kind


@requires_reference
@pytest.mark.parametrize("dirpath", LIB_DIRS)
def test_device_never_underfires_on_reference_corpus(dirpath):
    template, kind = _template_for(dirpath)
    test_src = (REFERENCE / dirpath / "src_test.rego").read_text()
    src = (REFERENCE / dirpath / "src.rego").read_text()
    docs = harvest_inputs(src, test_src, None)
    assert docs, f"no inputs harvested from {dirpath}"

    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(template)
    if dirpath in JOIN_COMPILED:
        # join path: no elementwise program, but the kind must compile
        # through ir/join.py (parity is covered by the audit test below)
        assert kind in drv.compiled_kinds()
        assert drv._join_progs.get(kind) is not None
        assert drv.join_for(kind) is not None
        return
    assert kind in drv.compiled_kinds(), f"{kind} did not compile"
    ct = drv.compiled_for(kind)
    assert ct is not None, f"{kind} failed device lowering"

    under = []
    over = 0
    fired_cases = 0
    for i, doc in enumerate(docs):
        review = doc.get("review") or {}
        params = doc.get("parameters")
        constraint = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": f"c{i}"},
            "spec": ({"parameters": params} if params is not None else {}),
        }
        interp_results = drv._eval_template_violations(
            TARGET, constraint, review, "deny", {}, None)
        fires = drv.eval_compiled(ct, kind, [review], [constraint])
        if interp_results:
            fired_cases += 1
            if not fires[0, 0]:
                under.append((i, [r.msg for r in interp_results]))
        elif fires[0, 0]:
            over += 1
    assert not under, (
        f"{dirpath}: device filter under-fired on {len(under)}/{len(docs)} "
        f"harvested inputs: {under[:3]}"
    )
    # sanity: the corpus must actually exercise the violating path
    assert fired_cases > 0, f"{dirpath}: no violating inputs harvested"


@requires_reference
@pytest.mark.parametrize("dirpath", LIB_DIRS)
def test_client_audit_parity_on_reference_corpus(dirpath):
    """End-to-end: audit over the harvested review objects must produce
    identical result multisets through both drivers."""
    template, kind = _template_for(dirpath)
    test_src = (REFERENCE / dirpath / "src_test.rego").read_text()
    src = (REFERENCE / dirpath / "src.rego").read_text()
    docs = harvest_inputs(src, test_src, None)
    # distinct parameterizations become distinct constraints; objects with
    # metadata.name become inventory
    outs = []
    for drv_cls in (RegoDriver, TpuDriver):
        drv = drv_cls()
        client = Backend(drv).new_client([K8sValidationTarget()])
        client.add_template(template)
        seen_params = []
        objs = []
        for i, doc in enumerate(docs):
            params = doc.get("parameters")
            if params not in seen_params:
                seen_params.append(params)
            obj = (doc.get("review") or {}).get("object")
            if isinstance(obj, dict):
                o = dict(obj)
                o.setdefault("apiVersion", "v1")
                o.setdefault("kind", "Pod")
                meta = dict(o.get("metadata") or {})
                meta["name"] = f"obj-{i}"
                meta.setdefault("namespace", "default")
                o["metadata"] = meta
                objs.append(o)
        for j, params in enumerate(seen_params):
            client.add_constraint({
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": kind, "metadata": {"name": f"c{j}"},
                "spec": ({"parameters": params} if params is not None
                         else {}),
            })
        for o in objs:
            client.add_data(o)
        outs.append(sorted(
            (r.msg, r.constraint["metadata"]["name"],
             (r.resource or {}).get("metadata", {}).get("name"))
            for r in client.audit().results()))
    assert outs[0] == outs[1], (
        f"{dirpath}: audit mismatch\ninterp only: "
        f"{[x for x in outs[0] if x not in outs[1]][:5]}\ndevice only: "
        f"{[x for x in outs[1] if x not in outs[0]][:5]}"
    )
