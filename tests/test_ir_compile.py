"""Differential tests: compiled (TpuDriver) vs interpreter (RegoDriver).

The compiled filter + host materialization must produce exactly the same
result multiset as the interpreter driver for compilable templates — on
randomized object/constraint populations covering the edge shapes the
compiler reasons about (missing fields, null labels, empty lists, DELETE
reviews, dryrun actions, regex params, prefix params).
"""

import random

import pytest
import yaml

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget

from .conftest import REFERENCE, requires_reference


def mk_client(driver):
    return Backend(driver).new_client([K8sValidationTarget()])


def load_ref_template(path):
    return yaml.safe_load((REFERENCE / path).read_text())


def result_key(r):
    return (
        r.msg,
        r.constraint["metadata"]["name"],
        (r.resource or {}).get("metadata", {}).get("name"),
        r.enforcement_action,
    )


def assert_same_results(res_a, res_b):
    a = sorted(result_key(r) for r in res_a)
    b = sorted(result_key(r) for r in res_b)
    assert a == b


def run_both(template, constraints, objects):
    out = []
    for drv_cls in (RegoDriver, TpuDriver):
        drv = drv_cls()
        client = mk_client(drv)
        client.add_template(template)
        for c in constraints:
            client.add_constraint(c)
        for o in objects:
            client.add_data(o)
        out.append((drv, client))
    (drv_a, client_a), (drv_b, client_b) = out
    if isinstance(template, dict):
        kind = template["spec"]["crd"]["spec"]["names"]["kind"]
        assert kind in drv_b.compiled_kinds(), f"{kind} did not compile"
    assert_same_results(client_a.audit().results(), client_b.audit().results())
    # review path parity on each object too
    for o in objects[: 20]:
        assert_same_results(
            client_a.review(AugmentedUnstructured(o)).results(),
            client_b.review(AugmentedUnstructured(o)).results(),
        )


# ----------------------------------------------------------- requiredlabels


NS_LABEL_POOL = ["owner", "team", "env", "cost-center", "tier"]
VAL_POOL = ["me.agilebank.demo", "you.agilebank.demo", "###", "", "web",
            "prod", "a" * 40]


def random_namespace(rng, i):
    labels = None
    if rng.random() < 0.8:
        labels = {
            k: rng.choice(VAL_POOL)
            for k in rng.sample(NS_LABEL_POOL, rng.randint(0, 4))
        }
        if rng.random() < 0.1:
            labels = {}
    o = {"apiVersion": "v1", "kind": "Namespace",
         "metadata": {"name": f"ns-{i}"}}
    if labels is not None:
        o["metadata"]["labels"] = labels
    return o


def requiredlabels_constraint(rng, i):
    labels = []
    for k in rng.sample(NS_LABEL_POOL, rng.randint(1, 3)):
        entry = {"key": k}
        roll = rng.random()
        if roll < 0.4:
            entry["allowedRegex"] = rng.choice(
                ["^[a-zA-Z]+.agilebank.demo$", "^prod$", "", "^[a-z]+$"])
        elif roll < 0.5:
            entry["allowedRegex"] = ""
        labels.append(entry)
    spec = {"parameters": {"labels": labels}}
    if rng.random() < 0.3:
        spec["parameters"]["message"] = f"custom message {i}"
    if rng.random() < 0.3:
        spec["enforcementAction"] = "dryrun"
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": f"req-{i}"},
        "spec": spec,
    }


@requires_reference
def test_requiredlabels_differential():
    template = load_ref_template("library/general/requiredlabels/template.yaml")
    rng = random.Random(7)
    constraints = [requiredlabels_constraint(rng, i) for i in range(12)]
    objects = [random_namespace(rng, i) for i in range(60)]
    run_both(template, constraints, objects)


# ------------------------------------------------------------- allowedrepos


def random_pod(rng, i):
    def container(j):
        c = {"name": f"c{j}"}
        if rng.random() < 0.95:
            c["image"] = rng.choice([
                "gcr.io/safe/app:v1", "docker.io/evil/app", "openpolicyagent/opa",
                "gcr.io/other/thing", "", "quay.io/x/y:2",
            ])
        return c

    spec = {}
    if rng.random() < 0.9:
        spec["containers"] = [container(j) for j in range(rng.randint(0, 4))]
    if rng.random() < 0.4:
        spec["initContainers"] = [container(j) for j in range(rng.randint(0, 2))]
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": f"pod-{i}", "namespace": "default"},
            "spec": spec}


def allowedrepos_constraint(rng, i):
    repos = rng.sample(["gcr.io/", "quay.io/", "docker.io/", "openpolicyagent"],
                       rng.randint(0, 3))
    return {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sAllowedRepos",
        "metadata": {"name": f"repos-{i}"},
        "spec": {"parameters": {"repos": repos}},
    }


@requires_reference
def test_allowedrepos_differential():
    template = load_ref_template("library/general/allowedrepos/template.yaml")
    rng = random.Random(11)
    constraints = [allowedrepos_constraint(rng, i) for i in range(8)]
    objects = [random_pod(rng, i) for i in range(50)]
    run_both(template, constraints, objects)


# --------------------------------------------------------------- httpsonly


def random_ingress(rng, i):
    o = {
        "apiVersion": rng.choice(["extensions/v1beta1",
                                  "networking.k8s.io/v1", "v1"]),
        "kind": rng.choice(["Ingress", "Service"]),
        "metadata": {"name": f"ing-{i}", "namespace": "default"},
    }
    if rng.random() < 0.7:
        o["metadata"]["annotations"] = {
            "kubernetes.io/ingress.allow-http":
                rng.choice(["false", "true", ""])
        }
    if rng.random() < 0.7:
        o["spec"] = {"tls": [{"secretName": "x"}] if rng.random() < 0.7 else []}
    else:
        o["spec"] = {}
    return o


@requires_reference
def test_httpsonly_differential():
    template = load_ref_template("library/general/httpsonly/template.yaml")
    rng = random.Random(13)
    constraints = [{
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sHttpsOnly",
        "metadata": {"name": "https-only"},
        "spec": {"match": {"kinds": [
            {"apiGroups": ["extensions", "networking.k8s.io"],
             "kinds": ["Ingress"]}]}},
    }]
    objects = [random_ingress(rng, i) for i in range(60)]
    run_both(template, constraints, objects)


# ---------------------------------------------------- match-mask batched path


@requires_reference
def test_matched_subset_only():
    """Constraints with kind/namespace/label matches: the batched mask must
    agree with the per-review matcher through the full driver stack."""
    template = load_ref_template("library/general/requiredlabels/template.yaml")
    rng = random.Random(17)
    constraints = []
    for i in range(6):
        c = requiredlabels_constraint(rng, i)
        match = {}
        roll = rng.random()
        if roll < 0.3:
            match["kinds"] = [{"apiGroups": [""], "kinds": ["Namespace"]}]
        elif roll < 0.5:
            match["kinds"] = [{"apiGroups": [""], "kinds": ["Pod"]}]
        if rng.random() < 0.4:
            match["namespaces"] = ["default", "prod"]
        if rng.random() < 0.3:
            match["labelSelector"] = {"matchExpressions": [
                {"key": "env", "operator": "Exists"}]}
        if match:
            c["spec"]["match"] = match
        constraints.append(c)
    objects = [random_namespace(rng, i) for i in range(30)]
    objects += [random_pod(rng, i) for i in range(20)]
    run_both(template, constraints, objects)


def test_uncompilable_template_falls_back():
    """A template using `with` stays on the interpreter and still works."""
    template = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sweird"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sWeird"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh", "rego": """
package k8sweird
violation[{"msg": "weird"}] {
  c := count(deny) with input as {"x": 1}
  c >= 0
  input.review.object.metadata.name == "target-me"
}
deny[m] { input.x > 0; m := "d" }
"""}],
        },
    }
    drv = TpuDriver()
    client = mk_client(drv)
    client.add_template(template)
    assert drv.compiled_for("K8sWeird") is None
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sWeird", "metadata": {"name": "w"}, "spec": {}})
    client.add_data({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": "target-me", "namespace": "d"}})
    client.add_data({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": "other", "namespace": "d"}})
    res = client.audit().results()
    assert [r.resource["metadata"]["name"] for r in res] == ["target-me"]


# --------------------------------------------------- numeric precision ties

BIGNUM_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "bignum"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "BigNum"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package bignum
violation[{"msg": msg}] {
  provided := input.review.object.spec.replicas
  maximum := input.parameters.max
  provided > maximum
  msg := "too many replicas"
}
""",
        }],
    },
}


def test_f32_tie_does_not_underfire():
    """Regression (ADVICE r1): 16777217 > 16777216 is a tie in float32;
    the device filter must over-fire on exact-id mismatch so the host
    re-check decides, never silently dropping the violation."""
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1", "kind": "BigNum",
        "metadata": {"name": "c1"},
        "spec": {"parameters": {"max": 16777216}},
    }
    objs = [
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "over", "namespace": "default"},
         "spec": {"replicas": 16777217}},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "at-limit", "namespace": "default"},
         "spec": {"replicas": 16777216}},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "under", "namespace": "default"},
         "spec": {"replicas": 3}},
    ]
    run_both(BIGNUM_TEMPLATE, [constraint], objs)


NEGATED_BIGNUM_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "bignumneg"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "BigNumNeg"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package bignumneg
violation[{"msg": msg}] {
  provided := input.review.object.spec.replicas
  maximum := input.parameters.max
  not provided < maximum
  msg := "not under the limit"
}
""",
        }],
    },
}


def test_f32_tie_does_not_underfire_under_negation():
    """Regression (r2 code review): over-fire bias at a comparison leaf is
    flipped by `not` — uncertainty must propagate as a (lo, hi) pair so
    negation swaps bounds instead of inverting the over-approximation."""
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "BigNumNeg", "metadata": {"name": "c1"},
        "spec": {"parameters": {"max": 16777217}},
    }
    objs = [
        # 16777216 < 16777217 exactly, but ties in f32: `not <` must not
        # drop the uncertainty (interpreter says no violation; and the
        # device filter may fire, host re-check settles it)
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "tie-under", "namespace": "default"},
         "spec": {"replicas": 16777216}},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "over", "namespace": "default"},
         "spec": {"replicas": 16777218}},
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "under", "namespace": "default"},
         "spec": {"replicas": 3}},
    ]
    run_both(NEGATED_BIGNUM_TEMPLATE, [constraint], objs)


def test_f32_tie_negated_exact_violation_found():
    """The exact case from the review: replicas == max ties in f32; `not
    provided < maximum` holds exactly (equal), so the violation must
    surface on the device path."""
    constraint = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "BigNumNeg", "metadata": {"name": "c1"},
        "spec": {"parameters": {"max": 16777216}},
    }
    objs = [
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": {"name": "at-tie", "namespace": "default"},
         "spec": {"replicas": 16777217}},
    ]
    run_both(NEGATED_BIGNUM_TEMPLATE, [constraint], objs)


def test_compiled_hlo_introspection():
    """The device program of any compiled template can be dumped at
    jaxpr / StableHLO / optimized-HLO stages (aux-subsystem parity with
    the reference's pprof-style introspection)."""
    from gatekeeper_tpu.parallel.workload import build_eval_setup
    from gatekeeper_tpu.utils.profiling import compiled_hlo

    _, ct, feats, params, table, derived, _, _ = build_eval_setup(8, 2)
    jx = compiled_hlo(ct, feats, params, table, derived, stage="jaxpr")
    assert "lambda" in jx or "let" in jx
    hlo = compiled_hlo(ct, feats, params, table, derived, stage="hlo")
    assert "func" in hlo or "HloModule" in hlo
    opt = compiled_hlo(ct, feats, params, table, derived,
                       stage="optimized")
    assert "HloModule" in opt or "func" in opt


def test_phase_timers():
    import time as _t

    from gatekeeper_tpu.utils.profiling import PhaseTimers

    pt = PhaseTimers()
    with pt.phase("sweep"):
        _t.sleep(0.01)
    with pt.phase("sweep"):
        pass
    snap = pt.snapshot()
    assert snap["sweep"][1] == 2 and snap["sweep"][0] >= 0.01
