"""Platform-keyed comparability in the perf-trend watchdog.

Rounds measured on different JAX backends (`jax_backend` in the bench
headline) must not gate each other: r03/r04 ran on accelerator hosts,
r06 on a 1-core CPU container, and device-bound walls differ ~20x by
host class alone. A platform change restarts every series baseline;
same-platform regressions still fail --check.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))
import bench_trend as bt  # noqa: E402


def _round(label, value, platform, unit=None):
    return {"round": label, "path": label,
            "metrics": {"sweep_wall_s": value}, "errors": {},
            "units": ({"sweep_wall_s": unit} if unit else {}),
            "platform": platform}


def test_same_platform_regression_gates():
    rounds = [_round("r01", 1.0, "cpu"), _round("r02", 2.0, "cpu")]
    regs = bt.find_regressions(rounds)
    assert [r["metric"] for r in regs] == ["sweep_wall_s"]


def test_platform_change_restarts_series():
    rounds = [_round("r01", 1.0, "axon"), _round("r02", 2.0, "cpu")]
    assert bt.find_regressions(rounds) == []


def test_legacy_rounds_compare_among_themselves():
    # rounds predating the jax_backend field carry platform None and
    # still gate each other — history stays watched
    rounds = [_round("r01", 1.0, None), _round("r02", 2.0, None)]
    assert bt.find_regressions(rounds)
    # ...but a None round never anchors a platform-carrying one
    rounds = [_round("r01", 1.0, None), _round("r02", 2.0, "cpu")]
    assert bt.find_regressions(rounds) == []


def test_unit_change_still_restarts_within_platform():
    rounds = [_round("r01", 1.0, "cpu", unit="objects/s @ 1k"),
              _round("r02", 2.0, "cpu", unit="objects/s @ 10k")]
    assert bt.find_regressions(rounds) == []


def test_loader_extracts_platform(tmp_path):
    p = tmp_path / "BENCH_r01.json"
    p.write_text(json.dumps(
        {"parsed": {"metric": "m", "value": 1.0,
                    "sweep_wall_s": 0.5, "jax_backend": "cpu"}}))
    rounds = bt.load_rounds([str(p)])
    assert rounds[0]["platform"] == "cpu"
    assert rounds[0]["metrics"]["sweep_wall_s"] == 0.5


def test_repo_history_check_passes():
    # the committed BENCH_r*.json history must be green: --check runs
    # in CI on every PR
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rc = bt.main(["--dir", repo, "--check"])
    assert rc == 0
