"""Differential test: native matcher vs the reference Rego matcher.

The native matcher (gatekeeper_tpu/target/matcher.py) re-implements the
semantics of the reference's generated Rego library
(pkg/target/regolib/src.rego). This test runs that exact Rego through our
interpreter (the semantic oracle validated against the reference's own
regolib test suites) and checks the native predicate agrees on a grid of
constraint × review shapes covering the library's edge cases.
"""

import itertools

import pytest

from gatekeeper_tpu.rego.interp import UNDEF, Interpreter
from gatekeeper_tpu.rego.parser import parse_module
from gatekeeper_tpu.target.matcher import constraint_matches, needs_autoreject
from gatekeeper_tpu.utils.values import thaw

from .conftest import REFERENCE, requires_reference

NS_OBJECTS = {
    "prod": {"apiVersion": "v1", "kind": "Namespace",
             "metadata": {"name": "prod", "labels": {"env": "prod"}}},
    "dev": {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "dev", "labels": {"env": "dev"}}},
}


def _constraints():
    """One constraint per interesting match shape."""
    matches = {
        "no-match-field": None,
        "empty-match": {},
        "null-match": {"kinds": None},
        "kinds-pod": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
        "kinds-star": {"kinds": [{"apiGroups": ["*"], "kinds": ["*"]}]},
        "kinds-no-apigroups": {"kinds": [{"kinds": ["Pod"]}]},
        "kinds-null-groups": {"kinds": [{"apiGroups": None, "kinds": ["Pod"]}]},
        "kinds-empty-list": {"kinds": []},
        "kinds-apps": {"kinds": [{"apiGroups": ["apps"], "kinds": ["Deployment"]}]},
        "ns-prod": {"namespaces": ["prod"]},
        "ns-null": {"namespaces": None},
        "ns-excluded-prod": {"excludedNamespaces": ["prod"]},
        "ns-excluded-null": {"excludedNamespaces": None},
        "label-eq": {"labelSelector": {"matchLabels": {"app": "web"}}},
        "label-in": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "In", "values": ["web", "api"]}]}},
        "label-in-empty": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "In", "values": []}]}},
        "label-notin": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "NotIn", "values": ["web"]}]}},
        "label-exists": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "Exists"}]}},
        "label-doesnotexist": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "DoesNotExist"}]}},
        "label-unknown-op": {"labelSelector": {"matchExpressions": [
            {"key": "app", "operator": "Mystery", "values": ["x"]}]}},
        "label-null-selector": {"labelSelector": None},
        "nssel-prod": {"namespaceSelector": {"matchLabels": {"env": "prod"}}},
        "nssel-null": {"namespaceSelector": None},
        "nssel-and-kinds": {
            "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
            "namespaceSelector": {"matchExpressions": [
                {"key": "env", "operator": "In", "values": ["prod", "dev"]}]},
        },
        "everything": {
            "kinds": [{"apiGroups": ["", "apps"], "kinds": ["Pod", "Deployment"]}],
            "namespaces": ["prod", "dev"],
            "excludedNamespaces": ["staging"],
            "labelSelector": {"matchLabels": {"app": "web"}},
            "namespaceSelector": {"matchLabels": {"env": "prod"}},
        },
    }
    out = {}
    for name, m in matches.items():
        spec = {}
        if m is not None:
            spec["match"] = m
        out[name] = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "TestKind",
            "metadata": {"name": name},
            "spec": spec,
        }
    return out


def _reviews():
    def pod(name, ns=None, labels=None):
        o = {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": name}}
        if ns:
            o["metadata"]["namespace"] = ns
        if labels is not None:
            o["metadata"]["labels"] = labels
        return o

    web = {"app": "web"}
    rs = {
        "pod-plain": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                      "object": pod("a"), "name": "a"},
        "pod-prod": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                     "namespace": "prod", "object": pod("a", "prod", web)},
        "pod-prod-sideloaded": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "prod", "object": pod("a", "prod", web),
            "_unstable": {"namespace": NS_OBJECTS["prod"]}},
        # sideload takes priority over the cache lookup (src.rego get_ns):
        # review.namespace says prod but the sideloaded object is dev
        "pod-sideload-overrides-cache": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "prod", "object": pod("a", "prod", web),
            "_unstable": {"namespace": NS_OBJECTS["dev"]}},
        # sideload resolves a namespace the cache has never seen — the
        # discovery-audit case (reference manager.go:250-271)
        "pod-unknown-ns-sideloaded": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "nowhere", "object": pod("a", "nowhere", web),
            "_unstable": {"namespace": {
                "apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "nowhere",
                             "labels": {"env": "prod"}}}}},
        "pod-sideload-unlabeled-ns": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "bare", "object": pod("a", "bare", web),
            "_unstable": {"namespace": {
                "apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "bare"}}}},
        "pod-unknown-ns": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                           "namespace": "nowhere", "object": pod("a", "nowhere")},
        "pod-empty-ns-string": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "", "object": pod("a")},
        "pod-dev-labeled": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                            "namespace": "dev",
                            "object": pod("a", "dev", {"app": "api"})},
        "deployment": {"kind": {"group": "apps", "version": "v1", "kind": "Deployment"},
                       "namespace": "prod",
                       "object": {"apiVersion": "apps/v1", "kind": "Deployment",
                                  "metadata": {"name": "d", "namespace": "prod",
                                               "labels": web}}},
        "namespace-obj": {"kind": {"group": "", "version": "v1", "kind": "Namespace"},
                          "object": NS_OBJECTS["prod"], "name": "prod"},
        "delete-oldobject-only": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "prod", "operation": "DELETE",
            "oldObject": pod("a", "prod", web)},
        "update-both-objects": {
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "prod",
            "object": pod("a", "prod", {"app": "api"}),
            "oldObject": pod("a", "prod", web)},
        "no-objects": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                       "namespace": "prod"},
        "null-labels": {"kind": {"group": "", "version": "v1", "kind": "Pod"},
                        "namespace": "prod",
                        "object": pod("a", "prod", None)},
    }
    return rs


@requires_reference
def test_native_matcher_agrees_with_reference_rego():
    src = (REFERENCE / "pkg" / "target" / "regolib" / "src.rego").read_text()
    src = src.replace("{{.ConstraintsRoot}}", "constraints")
    src = src.replace("{{.DataRoot}}", "external")
    interp = Interpreter({"target": parse_module(src, "regolib/src.rego")})
    constraints = _constraints()
    for name, c in constraints.items():
        interp.put_data(("constraints", "TestKind", name), c)
    for ns, obj in NS_OBJECTS.items():
        interp.put_data(("external", "cluster", "v1", "Namespace", ns), obj)

    def lookup(ns_name):
        return NS_OBJECTS.get(ns_name)

    mismatches = []
    for rname, review in _reviews().items():
        out = interp.eval_rule(("target",), "matching_constraints",
                               {"review": review})
        rego_matched = set()
        if out is not UNDEF:
            for c in out:
                rego_matched.add(c["metadata"]["name"])
        native_matched = {
            cname for cname, c in constraints.items()
            if constraint_matches(c, review, lookup)
        }
        if rego_matched != native_matched:
            mismatches.append(
                (rname, sorted(rego_matched ^ native_matched),
                 sorted(rego_matched), sorted(native_matched))
            )
    assert not mismatches, f"matcher disagreements: {mismatches}"


@requires_reference
def test_native_autoreject_agrees_with_reference_rego():
    src = (REFERENCE / "pkg" / "target" / "regolib" / "src.rego").read_text()
    src = src.replace("{{.ConstraintsRoot}}", "constraints")
    src = src.replace("{{.DataRoot}}", "external")
    interp = Interpreter({"target": parse_module(src, "regolib/src.rego")})
    constraints = _constraints()
    for name, c in constraints.items():
        interp.put_data(("constraints", "TestKind", name), c)
    for ns, obj in NS_OBJECTS.items():
        interp.put_data(("external", "cluster", "v1", "Namespace", ns), obj)

    def lookup(ns_name):
        return NS_OBJECTS.get(ns_name)

    mismatches = []
    for rname, review in _reviews().items():
        out = interp.eval_rule(("target",), "autoreject_review",
                               {"review": review})
        rego_rejected = set()
        if out is not UNDEF:
            for rejection in out:
                rego_rejected.add(rejection["constraint"]["metadata"]["name"])
        native_rejected = set()
        for cname, c in constraints.items():
            spec = c.get("spec") or {}
            match = spec.get("match")
            match = match if isinstance(match, dict) else {}
            if needs_autoreject(match, review, lookup):
                native_rejected.add(cname)
        if rego_rejected != native_rejected:
            mismatches.append(
                (rname, sorted(rego_rejected ^ native_rejected))
            )
    assert not mismatches, f"autoreject disagreements: {mismatches}"


def test_match_masks_equals_bruteforce_grid():
    """The grouped/memoized batch matcher (target/batch.py) must agree
    cell-for-cell with per-pair constraint_matches over the full edge-case
    grid — including _unstable sideloads and Namespace-kind reviews."""
    import numpy as np

    from gatekeeper_tpu.target.batch import match_masks

    cons = list(_constraints().values())
    reviews = list(_reviews().values())

    def lookup(name):
        return NS_OBJECTS.get(name)

    want = np.zeros((len(reviews), len(cons)), dtype=bool)
    for r, review in enumerate(reviews):
        for c, constraint in enumerate(cons):
            want[r, c] = constraint_matches(constraint, review, lookup)

    got = match_masks(cons, reviews, lookup)
    assert (got == want).all(), np.argwhere(got != want)[:10]

    # shared signature cache across calls (the per-kind audit loop)
    cache: dict = {}
    got1 = match_masks(cons[:5], reviews, lookup, cache)
    got2 = match_masks(cons[5:], reviews, lookup, cache)
    assert (np.concatenate([got1, got2], axis=1) == want).all()


# --------------------------------------------------- native edge-case grid
# (no reference checkout required: these pin the label-selector semantics
# the differential suites above cover only when /root/reference exists)


def _match_constraint(match):
    return {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "TestKind", "metadata": {"name": "edge"},
            "spec": {"match": match}}


def _pod_review(labels=None, ns="prod"):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "a", "namespace": ns}}
    if labels is not None:
        obj["metadata"]["labels"] = labels
    return {"kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": ns, "object": obj}


def _lookup(name):
    return NS_OBJECTS.get(name)


@pytest.mark.parametrize("op,values,labels,want", [
    # Exists / DoesNotExist on a missing key
    ("Exists", None, {}, False),
    ("Exists", None, {"app": "web"}, True),
    ("DoesNotExist", None, {}, True),
    ("DoesNotExist", None, {"app": "web"}, False),
    # NotIn on a missing key is NOT violated (src.rego:168-172 requires
    # the key to be present for a NotIn violation)
    ("NotIn", ["web"], {}, True),
    ("NotIn", ["web"], {"app": "web"}, False),
    ("NotIn", ["web"], {"app": "api"}, True),
    # empty values: In is violated only by a missing key; NotIn never
    ("In", [], {"app": "web"}, True),
    ("In", [], {}, False),
    ("NotIn", [], {"app": "web"}, True),
    ("NotIn", [], {}, True),
])
def test_label_selector_expression_edges(op, values, labels, want):
    expr = {"key": "app", "operator": op}
    if values is not None:
        expr["values"] = values
    c = _match_constraint({"labelSelector": {"matchExpressions": [expr]}})
    assert constraint_matches(c, _pod_review(labels), _lookup) is want


def test_nsselector_vs_cluster_scoped_reviews():
    c = _match_constraint(
        {"namespaceSelector": {"matchLabels": {"env": "prod"}}})
    # a cluster-scoped non-Namespace review has no resolvable namespace:
    # the constraint never matches (src.rego:286-302 get_ns undefined)
    crd_review = {"kind": {"group": "apiextensions.k8s.io",
                           "version": "v1beta1",
                           "kind": "CustomResourceDefinition"},
                  "object": {"apiVersion": "apiextensions.k8s.io/v1beta1",
                             "kind": "CustomResourceDefinition",
                             "metadata": {"name": "crd"}}}
    assert constraint_matches(c, crd_review, _lookup) is False
    # but a Namespace-kind review matches against its OWN labels
    ns_review = {"kind": {"group": "", "version": "v1",
                          "kind": "Namespace"},
                 "object": NS_OBJECTS["prod"], "name": "prod"}
    assert constraint_matches(c, ns_review, _lookup) is True
    dev_review = {"kind": {"group": "", "version": "v1",
                           "kind": "Namespace"},
                  "object": NS_OBJECTS["dev"], "name": "dev"}
    assert constraint_matches(c, dev_review, _lookup) is False
    # namespaced review in an uncached namespace: no match (autoreject
    # territory), while a cached one selects via the cache
    assert constraint_matches(c, _pod_review({}, ns="prod"), _lookup)
    assert not constraint_matches(c, _pod_review({}, ns="nowhere"),
                                  _lookup)


def test_nsselector_missing_key_expressions_on_namespace_labels():
    c = _match_constraint({"namespaceSelector": {"matchExpressions": [
        {"key": "team", "operator": "DoesNotExist"}]}})
    assert constraint_matches(c, _pod_review({}, ns="prod"), _lookup)
    c2 = _match_constraint({"namespaceSelector": {"matchExpressions": [
        {"key": "team", "operator": "Exists"}]}})
    assert not constraint_matches(c2, _pod_review({}, ns="prod"), _lookup)
