"""Streaming audit + what-if preview (ISSUE 9 tentpole).

Detection-latency contract: with --stream-audit, a watch event lands in
constraint status within the debounce window plus one dirty-row flush —
milliseconds — instead of waiting out the --audit-interval polling
sweep. The interval sweep is demoted to a reconciliation backstop whose
repairs are drift, reported as such.

Preview contract: a candidate template/constraint swept under its
content-hashed alias kind produces the SAME violation set on the device
path as the pure-interpreter oracle, without touching the serving
library; the endpoint answers caller errors as 400s.

enforcementAction parity: deny denies, dryrun is invisible to the
caller, warn rides the AdmissionReview warnings field and never flips
`allowed`.
"""

from __future__ import annotations

import json
import re
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.control.audit import AuditManager
from gatekeeper_tpu.control.kube import FakeKube
from gatekeeper_tpu.control.metrics import REGISTRY
from gatekeeper_tpu.control.preview import PreviewEngine, PreviewError
from gatekeeper_tpu.control.webhook import (
    MicroBatcher,
    ValidationHandler,
    WebhookServer,
)
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.parallel.workload import REQUIRED_LABELS_TEMPLATE
from gatekeeper_tpu.target import K8sValidationTarget

CONSTRAINT_GVK = ("constraints.gatekeeper.sh", "v1beta1",
                  "K8sRequiredLabels")
TEAM_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sRequiredLabels",
    "metadata": {"name": "pods-need-team", "uid": "c-team"},
    "spec": {
        "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
        "parameters": {"labels": [{"key": "team"}]},
    },
}


def _pod(name, labels, uid):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "uid": uid, "labels": dict(labels)}}


def _cluster(n_pods=24):
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    kube.create({"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": "default", "uid": "ns-u0"}})
    for i in range(n_pods):
        kube.create(_pod(f"p-{i}", {"team": "core"}, f"u{i}"))
    return kube


def _streaming_manager(kube, window_s=0.02, leader_check=None):
    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_constraint(TEAM_CONSTRAINT)
    kube.apply(dict(TEAM_CONSTRAINT))
    mgr = AuditManager(kube, client, incremental=True, interval=3600,
                       stream_audit=True, stream_window_s=window_s,
                       leader_check=leader_check)
    return client, mgr


def _start_armed(mgr, timeout=10.0):
    """Start the manager and wait until the stream loop has armed the
    tracker's event hooks (the manager must have swept once so the
    tracker exists)."""
    mgr.start()
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        tr = mgr.tracker
        if tr is not None and tr.track_event_times \
                and tr.on_event is not None:
            return
        time.sleep(0.01)
    raise AssertionError("stream loop never armed the tracker")


def _counter(name: str) -> float:
    m = re.search(rf"^{re.escape(name)} ([0-9.e+-]+)$",
                  REGISTRY.render(), re.M)
    return float(m.group(1)) if m else 0.0


def _flush_collector(mgr):
    out, cv = [], threading.Condition()

    def on_flush(lat, writes):
        with cv:
            out.append((list(lat), dict(writes)))
            cv.notify_all()

    mgr.on_flush = on_flush

    def wait(n=1, timeout=10.0):
        with cv:
            cv.wait_for(lambda: len(out) >= n, timeout=timeout)
            return list(out)

    return wait


# --------------------------------------------------------- streaming audit


def test_churn_detected_under_window_budget():
    """The headline contract: watch event -> constraint-status PATCH in
    ~window + one dirty-row flush, not an --audit-interval."""
    kube = _cluster()
    client, mgr = _streaming_manager(kube, window_s=0.02)
    mgr.audit_once()  # bootstrap: tracker + encoded inventory
    assert mgr.audit_once() is not None  # steady (delta) sweep
    wait = _flush_collector(mgr)
    base_count = _counter(
        "gatekeeper_tpu_violation_detection_seconds_count")
    _start_armed(mgr)
    try:
        sweeps_before = mgr._sweeps
        t_apply = time.monotonic()
        kube.apply(_pod("p-3", {}, "u3"))  # drop team -> NEW violation
        flushes = wait(1)
        t_flushed = time.monotonic()
        assert flushes, "no stream flush within timeout"
        lat, writes = flushes[0]
        # the detection clock: event receipt -> status write completed.
        # The contract is that detection rode the STREAM flush (the
        # interval sweep is parked at 3600s and must not have fired),
        # so the bound is what THIS test observed apply-to-callback
        # plus slack — a load-adjusted budget, not an absolute
        # wall-clock figure a starved CI worker can blow through.
        assert lat and max(lat) <= (t_flushed - t_apply) + 0.5
        assert mgr._sweeps == sweeps_before  # no interval sweep ran
        assert writes["status_writes"] >= 1
        stored = kube.get(CONSTRAINT_GVK, "pods-need-team")
        assert any(v["name"] == "p-3"
                   for v in stored["status"]["violations"])
        assert stored["status"]["totalViolations"] == 1
        # the latency landed in the headline histogram
        assert _counter(
            "gatekeeper_tpu_violation_detection_seconds_count") \
            >= base_count + 1
        assert mgr.stream_stats["flushes"] >= 1
        assert mgr.stream_stats["errors"] == 0
    finally:
        mgr.stop()


def test_healthy_churn_confirms_noop_without_writes():
    """Same-verdict churn still flushes (the confirmation IS the
    detection) but issues zero status PATCHes."""
    kube = _cluster()
    client, mgr = _streaming_manager(kube)
    mgr.audit_once()
    mgr.audit_once()
    wait = _flush_collector(mgr)
    _start_armed(mgr)
    try:
        kube.apply(_pod("p-5", {"team": "core", "extra": "x"}, "u5"))
        flushes = wait(1)
        assert flushes
        lat, writes = flushes[0]
        assert lat  # the event was still timed
        assert writes["status_writes"] == 0
    finally:
        mgr.stop()


def test_follower_drains_without_status_writes():
    """A follower replica's stream loop keeps the inventory current (a
    promoted survivor must sweep fresh rows) but never writes status."""
    kube = _cluster()
    client, mgr = _streaming_manager(kube, leader_check=lambda: False)
    # bootstrap as leader, then follow
    mgr.leader_check = None
    mgr.audit_once()
    mgr.leader_check = lambda: False
    _start_armed(mgr)
    try:
        before = kube.get(CONSTRAINT_GVK, "pods-need-team")
        kube.apply(_pod("p-7", {}, "u7"))
        t0 = time.monotonic()
        while mgr.stream_stats["skipped"] == 0 \
                and time.monotonic() - t0 < 10:
            time.sleep(0.01)
        assert mgr.stream_stats["skipped"] >= 1
        assert mgr.tracker.pending_count() == 0  # drained anyway
        after = kube.get(CONSTRAINT_GVK, "pods-need-team")
        assert after.get("status") == before.get("status")
    finally:
        mgr.stop()


def test_backstop_sweep_repairs_and_reports_drift():
    """With streaming keeping statuses current, any PATCH the interval
    sweep has to issue is drift — here an external status clobber. The
    sweep must repair it AND count it."""
    kube = _cluster()
    client, mgr = _streaming_manager(kube)
    mgr.audit_once()
    kube.apply(_pod("p-2", {}, "u2"))  # one standing violation
    mgr.audit_once()
    wait = _flush_collector(mgr)
    _start_armed(mgr)
    try:
        # clobber the published status behind the manager's back
        stored = kube.get(CONSTRAINT_GVK, "pods-need-team")
        clobbered = json.loads(json.dumps(stored))
        clobbered["status"]["violations"] = []
        clobbered["status"]["totalViolations"] = 0
        kube.apply(clobbered)
        drift0 = _counter("gatekeeper_tpu_audit_backstop_drift_total")
        mgr.audit_once()  # the reconciliation backstop
        stored = kube.get(CONSTRAINT_GVK, "pods-need-team")
        assert any(v["name"] == "p-2"
                   for v in stored["status"]["violations"])
        assert _counter("gatekeeper_tpu_audit_backstop_drift_total") \
            >= drift0 + 1
    finally:
        mgr.stop()


def test_stream_flush_error_is_counted_and_backstop_recovers():
    kube = _cluster()
    client, mgr = _streaming_manager(kube)
    mgr.audit_once()
    mgr.audit_once()
    _start_armed(mgr)
    try:
        real_audit = client.audit
        client.audit = lambda *a, **k: (_ for _ in ()).throw(
            RuntimeError("injected eval failure"))
        kube.apply(_pod("p-9", {}, "u9"))
        t0 = time.monotonic()
        while mgr.stream_stats["errors"] == 0 \
                and time.monotonic() - t0 < 10:
            time.sleep(0.01)
        assert mgr.stream_stats["errors"] >= 1
        client.audit = real_audit
        mgr.audit_once()  # backstop reconciles what the flush missed
        stored = kube.get(CONSTRAINT_GVK, "pods-need-team")
        assert any(v["name"] == "p-9"
                   for v in stored["status"]["violations"])
    finally:
        mgr.stop()


def test_stream_flush_lists_only_changed_kinds():
    """Per-flush status-write cost is O(changed constraints), not
    O(all constraints): a no-op flush issues ZERO constraint list
    calls and a real change lists only the kind whose violation set
    moved (the backstop sweep still passes over everything)."""
    kube = _cluster()
    client, mgr = _streaming_manager(kube)
    other = json.loads(json.dumps(REQUIRED_LABELS_TEMPLATE))
    other["spec"]["crd"]["spec"]["names"]["kind"] = "K8sOtherLabels"
    other["metadata"]["name"] = "k8sotherlabels"
    client.add_template(other)
    wait = _flush_collector(mgr)
    _start_armed(mgr)
    try:
        mgr.audit_once()  # establishes the fingerprint baseline
        lists = []
        orig = kube.list

        def spy(gvk, *a, **k):
            if gvk[0] == "constraints.gatekeeper.sh":
                lists.append(gvk[2])
            return orig(gvk, *a, **k)

        kube.list = spy
        # label churn that stays compliant: no violation set moves
        kube.apply(_pod("p-0", {"team": "core", "extra": "1"}, "u0"))
        wait(1)
        assert lists == [], lists
        # a real violation moves exactly one kind
        kube.apply(_pod("p-bad", {}, "u-bad"))
        wait(2)
        assert set(lists) == {"K8sRequiredLabels"}, lists
        stored = kube.get(CONSTRAINT_GVK, "pods-need-team")
        assert any(v["name"] == "p-bad"
                   for v in stored["status"]["violations"])
    finally:
        kube.list = orig
        mgr.stop()


# ------------------------------------------------------- what-if preview


def _mixed_client(n=3000):
    import bench_configs

    driver = TpuDriver()
    client = Backend(driver).new_client([K8sValidationTarget()])
    from gatekeeper_tpu import policies
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    for o in bench_configs.synth_mixed_objects(n):
        client.add_data(o)
    return driver, client


def test_preview_device_matches_interpreter_on_general_library():
    """The differential: every general-library candidate swept through
    audit_kind's DEVICE path must produce the interpreter oracle's
    violation set exactly."""
    import bench_configs

    driver, client = _mixed_client()
    driver._use_device_for_batch = lambda n: True  # force the device
    pv = PreviewEngine(client)

    def key(results):
        return sorted(
            (r.msg, (r.resource or {}).get("kind") or "",
             ((r.resource or {}).get("metadata") or {})
             .get("namespace") or "",
             ((r.resource or {}).get("metadata") or {})
             .get("name") or "")
            for r in results)

    checked = 0
    for kind, cname, params in bench_configs.GENERAL_CONSTRAINTS:
        con = {"kind": kind, "metadata": {"name": cname},
               "spec": ({"parameters": params} if params else {})}
        ent, _ = pv._ensure_template(None, kind)
        alias_con = dict(con, kind=ent["alias"],
                         apiVersion="constraints.gatekeeper.sh/v1beta1")
        device, path = driver.audit_kind(
            next(iter(client.targets)), ent["alias"], [alias_con])
        oracle = pv._interp_eval(ent["alias"], [alias_con])
        assert key(device) == key(oracle), \
            f"{kind}: device/{path} diverges from interpreter"
        checked += 1
        # the public entry agrees on the count
        out = pv.preview({"constraint": dict(
            con, apiVersion="constraints.gatekeeper.sh/v1beta1")})
        assert out["violations"] == len(oracle)
    assert checked == len(bench_configs.GENERAL_CONSTRAINTS)


def test_preview_isolates_serving_library():
    """Compiling + sweeping a candidate must not bump the client
    generation (decision-cache invalidation) or touch the serving
    kind's caches."""
    driver, client = _mixed_client(200)
    pv = PreviewEngine(client)
    gen0 = client.generation
    kinds0 = set(client.template_kinds())
    out = pv.preview({"constraint": {
        "kind": "K8sRequiredLabels", "metadata": {"name": "w"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Pod"]}]},
                 "parameters": {"labels": [{"key": "owner"}]}}}})
    assert out["reviewed"] > 0
    assert client.generation == gen0
    assert set(client.template_kinds()) == kinds0
    # repeat previews of identical content hit the compiled alias
    out2 = pv.preview({"constraint": {
        "kind": "K8sRequiredLabels", "metadata": {"name": "w"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Pod"]}]},
                 "parameters": {"labels": [{"key": "owner"}]}}}})
    assert out2["cold"] is False


def test_preview_lru_eviction_recompiles_evicted_candidate():
    """Pushing a candidate out of the compiled-alias LRU deletes its
    modules; a later preview of the same content must recompile cold
    and still produce the full violation set (previews serialize on
    _eval_lock, so eviction can never race an in-flight sweep)."""
    driver, client = _mixed_client(100)
    pv = PreviewEngine(client)
    pv.MAX_COMPILED = 1

    def candidate(kind):
        tpl = json.loads(json.dumps(REQUIRED_LABELS_TEMPLATE))
        tpl["spec"]["crd"]["spec"]["names"]["kind"] = kind
        tpl["metadata"]["name"] = kind.lower()
        return {"template": tpl,
                "constraint": {"kind": kind, "metadata": {"name": "w"},
                               "spec": {"parameters": {"labels": [
                                   {"key": "no-such-label"}]}}}}

    first = pv.preview(candidate("K8sEvictA"))
    assert first["cold"] is True and first["violations"] > 0
    pv.preview(candidate("K8sEvictB"))  # evicts K8sEvictA
    assert len(pv._compiled) == 1
    again = pv.preview(candidate("K8sEvictA"))
    assert again["cold"] is True  # recompiled, not a stale hit
    assert again["violations"] == first["violations"]


def test_preview_candidate_template_and_errors():
    """A not-yet-installed template rides the request; caller mistakes
    are PreviewErrors (HTTP 400), never 500s."""
    driver, client = _mixed_client(100)
    pv = PreviewEngine(client)
    candidate = json.loads(json.dumps(REQUIRED_LABELS_TEMPLATE))
    candidate["spec"]["crd"]["spec"]["names"]["kind"] = "K8sNovelKind"
    candidate["metadata"]["name"] = "k8snovelkind"
    out = pv.preview({
        "template": candidate,
        "constraint": {"kind": "K8sNovelKind",
                       "metadata": {"name": "novel"},
                       "spec": {"parameters": {"labels": [
                           {"key": "nonexistent-label"}]}}}})
    assert out["kind"] == "K8sNovelKind" and out["violations"] > 0
    assert "K8sNovelKind" not in client.template_kinds()
    with pytest.raises(PreviewError):
        pv.preview({})  # no constraint
    with pytest.raises(PreviewError):
        pv.preview({"constraint": {"kind": "NoSuchTemplateKind",
                                   "metadata": {"name": "x"}}})
    with pytest.raises(PreviewError):
        pv.preview({"constraint": {
            "kind": "K8sRequiredLabels", "metadata": {"name": "x"},
            "spec": {"enforcementAction": "bogus"}}})
    # transport layer: 400 with an error body, 200 with a verdict
    status, body = pv.handle_http(b"{not json")
    assert status == 400
    status, body = pv.handle_http(json.dumps({
        "constraint": {"kind": "K8sRequiredLabels",
                       "metadata": {"name": "w"},
                       "spec": {"parameters": {"labels": [
                           {"key": "team"}]}}}}).encode())
    assert status == 200
    assert json.loads(body)["reviewed"] >= 0


def test_preview_served_on_dedicated_listener():
    """The --preview-port topology: a WebhookServer with ONLY the
    preview engine 404s admission routes and answers /v1/preview."""
    import http.client

    driver, client = _mixed_client(100)
    server = WebhookServer(None, None, port=0,
                           preview=PreviewEngine(client))
    server.start()
    try:
        conn = http.client.HTTPConnection("localhost", server.port,
                                          timeout=30)
        conn.request("POST", "/v1/admit", body=b"{}")
        resp = conn.getresponse()
        assert resp.status == 404
        resp.read()
        conn.request("POST", "/v1/preview", body=json.dumps({
            "constraint": {"kind": "K8sRequiredLabels",
                           "metadata": {"name": "w"},
                           "spec": {"parameters": {"labels": [
                               {"key": "team"}]}}}}).encode())
        resp = conn.getresponse()
        assert resp.status == 200
        assert json.loads(resp.read())["reviewed"] >= 0
        conn.close()
    finally:
        server.stop(drain_timeout=1.0)


def test_preview_over_backplane_frontend():
    """The --admission-workers topology: a frontend forwards
    /v1/preview over the backplane; the engine serves it on the
    dedicated single-thread preview executor while /v1/admit keeps its
    own pool. Admission routes still answer alongside."""
    import http.client

    from gatekeeper_tpu.control.backplane import (
        BackplaneClient,
        BackplaneEngine,
        FrontendServer,
        default_socket_path,
    )

    driver, client = _mixed_client(100)
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "must-team"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Pod"]}]},
                 "parameters": {"labels": [{"key": "team"}]}}})
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    sock = default_socket_path() + ".pv"
    engine = BackplaneEngine(sock, validation=validation,
                             preview=PreviewEngine(client))
    engine.start()
    bc = BackplaneClient(sock, worker_id="test")
    fe = FrontendServer(bc, port=0, addr="127.0.0.1",
                        serve=("admit", "admitlabel", "preview"))
    fe.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=60)
        conn.request("POST", "/v1/preview", json.dumps({
            "constraint": {"kind": "K8sRequiredLabels",
                           "metadata": {"name": "w"},
                           "spec": {"parameters": {"labels": [
                               {"key": "owner"}]}}}}).encode())
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 200, body
        assert json.loads(body)["reviewed"] > 0
        # admission still answers on the same connection
        conn.request("POST", "/v1/admit", json.dumps(
            _admission_review(_pod("p-a", {"team": "t"},
                                   "uid-a"))).encode())
        resp = conn.getresponse()
        out = json.loads(resp.read())
        assert out["response"]["allowed"] is True
        conn.close()
    finally:
        fe.stop(drain_timeout=1.0)
        engine.stop(drain_timeout=1.0)


# -------------------------------------------- enforcementAction parity


def _admission_review(obj):
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {"uid": "u-1",
                        "kind": {"group": "", "version": "v1",
                                 "kind": obj["kind"]},
                        "operation": "CREATE",
                        "name": obj["metadata"]["name"],
                        "namespace": obj["metadata"].get("namespace"),
                        "object": obj}}


def _action_client(actions):
    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    for i, action in enumerate(actions):
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels",
            "metadata": {"name": f"labels-{action}-{i}"},
            "spec": {"enforcementAction": action,
                     "match": {"kinds": [{"apiGroups": [""],
                                          "kinds": ["Pod"]}]},
                     "parameters": {"labels": [{"key": action}]}},
        })
    return client


@pytest.mark.parametrize("action,allowed,warned", [
    ("deny", False, False),
    ("dryrun", True, False),
    ("warn", True, True),
])
def test_enforcement_action_parity(action, allowed, warned):
    client = _action_client([action])
    handler = ValidationHandler(client,
                                batcher=MicroBatcher(client))
    out = handler.handle(_admission_review(
        _pod("p-x", {}, "uid-x")))
    resp = out["response"]
    assert resp["allowed"] is allowed
    if warned:
        assert resp["warnings"] and "warn" in resp["warnings"][0]
    else:
        assert "warnings" not in resp


def test_warn_rides_alongside_deny_and_dryrun():
    """A deny verdict still carries the warn constraint's warning; the
    dryrun one stays invisible either way."""
    client = _action_client(["deny", "dryrun", "warn"])
    handler = ValidationHandler(client, batcher=MicroBatcher(client))
    out = handler.handle(_admission_review(_pod("p-y", {}, "uid-y")))
    resp = out["response"]
    assert resp["allowed"] is False
    assert len(resp["warnings"]) == 1
    assert "warn" in resp["warnings"][0]
    assert "dryrun" not in resp["status"]["reason"]
    # satisfying the warn+deny labels clears both
    ok = handler.handle(_admission_review(
        _pod("p-z", {"deny": "1", "warn": "1"}, "uid-z")))
    assert ok["response"]["allowed"] is True
    assert "warnings" not in ok["response"]


# ------------------------------------------------- bench skip records


def test_config5_sweeps_always_carry_a_record():
    import bench_configs as bc

    # single-core host, not forced: an explicit skip reason
    rec = bc.c5_skip_record([1, 2], cores=1, forced=False,
                            env_key="BENCH_C5_WORKERS", what="frontends")
    assert rec and "1 host core" in rec["skipped"]
    # forced by env: runs even on one core
    assert bc.c5_skip_record([1, 2], cores=1, forced=True,
                             env_key="BENCH_C5_WORKERS",
                             what="frontends") is None
    # empty count list: explicit, names the env var
    rec = bc.c5_skip_record([], cores=8, forced=True,
                            env_key="BENCH_C5_WORKERS", what="frontends")
    assert "BENCH_C5_WORKERS" in rec["skipped"]
    # multi-core unforced: runs
    assert bc.c5_skip_record([1], cores=8, forced=False,
                             env_key="BENCH_C5_WORKERS",
                             what="frontends") is None
    # the headline backstop: an empty sweep list can never reach the
    # JSON as a silent []
    out = bc.sweep_or_skip([], "multi_worker_sweep")
    assert out and "skipped" in out[0]
    kept = [{"workers": 1}]
    assert bc.sweep_or_skip(kept, "multi_worker_sweep") is kept
