"""Sharded inventory plane (ISSUE 16 tentpole).

The audit inventory partitions across N audit engine processes by
consistent hash of (GVK, namespace); each shard owns its slice end to
end while the leader routes deltas, broadcasts join-relevant columns,
and composes per-shard sweeps into ONE audit round that must be
BIT-EQUAL to the unsharded sweep — verdicts, materialized messages,
reviews, resources, enforcement actions, and their order.

Covers:
  * ShardMap: determinism, coverage, cluster-scope handling, and the
    consistent-hashing rebalance contract (2 -> 4 moves ~half, not all);
  * ScopedKube: list/watch restricted to one shard's slice;
  * broadcast pruning: identity skeleton + join-key columns only;
  * in-process 1/2/4-shard differential through the REAL plane
    (routing, pruning, slice servers, heap-merge composition) with
    cross-object join templates in the library;
  * subprocess 2-shard differential (real engine children over the
    backplane) and kill-a-shard chaos: SIGKILL one shard, the next
    round converges bit-equal after respawn + slice resync.
"""

from __future__ import annotations

import json
import signal
import time

import pytest

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.control.audit import (
    AuditManager,
    AuditSliceServer,
    ShardedAuditPlane,
    compose_shard_results,
)
from gatekeeper_tpu.control.kube import FakeKube, ScopedKube
from gatekeeper_tpu.control.shardmap import ShardMap
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.parallel.workload import REQUIRED_LABELS_TEMPLATE
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"
PER_TEST_TIMEOUT_S = 240


@pytest.fixture(autouse=True)
def _hard_timeout():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


# ------------------------------------------------------------- fixtures


def _pod(name, ns, labels=None, uid=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "uid": uid or f"u-{ns}-{name}",
                         "resourceVersion": "1",
                         **({"labels": labels} if labels else {})}}


def _ingress(name, ns, hosts):
    return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
            "metadata": {"name": name, "namespace": ns,
                         "uid": f"u-ing-{ns}-{name}",
                         "resourceVersion": "1"},
            "spec": {"rules": [{"host": h} for h in hosts]}}


def _service(name, ns, sel):
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "namespace": ns,
                         "uid": f"u-svc-{ns}-{name}",
                         "resourceVersion": "1"},
            "spec": {"selector": sel}}


def _namespace(name):
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name, "uid": f"u-ns-{name}",
                         "resourceVersion": "1"}}


TEAM_CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sRequiredLabels",
    "metadata": {"name": "pods-need-team", "uid": "c-team"},
    "spec": {
        "match": {"kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]},
        "parameters": {"labels": [{"key": "team"}]},
    },
}


def _join_constraint(kind, name):
    return {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": name, "uid": f"c-{name}"},
            "spec": {}}


def _library(client):
    """Per-kind constraint + BOTH cross-object join templates: the
    differential must hold where shards need each other's objects."""
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_template(policies.load("general/uniqueserviceselector"))
    client.add_constraint(TEAM_CONSTRAINT)
    client.add_constraint(
        _join_constraint("K8sUniqueIngressHost", "unique-hosts"))
    client.add_constraint(
        _join_constraint("K8sUniqueServiceSelector", "unique-selectors"))


def _objects(n_pods=18):
    """A workload whose violations span namespaces (so every shard
    count splits it) and whose join conflicts CROSS namespaces (so a
    shard missing the broadcast set would change verdicts)."""
    objs = [_namespace(f"ns{i}") for i in range(5)]
    for i in range(n_pods):
        objs.append(_pod(f"p-{i}", f"ns{i % 5}",
                         {"team": "core"} if i % 3 else {"app": "x"}))
    objs += [
        _ingress("ing-a", "ns0", ["x.com", "y.com"]),
        _ingress("ing-b", "ns1", ["x.com"]),          # cross-ns conflict
        _ingress("ing-c", "ns2", ["unique.com"]),
        _ingress("ing-d", "ns3", ["y.com", "z.com"]),  # conflicts on y
        _service("svc-1", "ns0", {"app": "web", "tier": "fe"}),
        _service("svc-2", "ns4", {"tier": "fe", "app": "web"}),  # same
        _service("svc-3", "ns1", {"app": "db"}),
    ]
    return objs


def _result_key(r):
    return (r.msg,
            json.dumps(r.metadata, sort_keys=True, default=str),
            json.dumps(r.constraint, sort_keys=True, default=str),
            json.dumps(r.review, sort_keys=True, default=str),
            json.dumps(r.resource, sort_keys=True, default=str),
            r.enforcement_action)


def _unsharded_results(objs):
    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    _library(client)
    for o in objs:
        client.add_data(o)
    return [_result_key(r) for r in client.audit().results()]


# ------------------------------------------------------------- shard map


def test_shardmap_deterministic_and_covering():
    a, b = ShardMap(4), ShardMap(4)
    keys = [(("", "v1", "Pod"), f"ns{i}") for i in range(200)]
    keys.append((("apps", "v1", "Deployment"), ""))  # cluster-scoped
    owners = [a.owner(g, ns) for g, ns in keys]
    assert owners == [b.owner(g, ns) for g, ns in keys], \
        "two rings over the same config must agree"
    assert all(0 <= o < 4 for o in owners)
    assert len(set(owners)) == 4, "200 keys must land on every shard"
    for (g, ns), o in zip(keys, owners):
        assert a.owns(o, g, ns)
        assert sum(a.owns(k, g, ns) for k in range(4)) == 1, \
            "exactly one owner per key"


def test_shardmap_rebalance_moves_consistent_fraction():
    keys = [(("", "v1", "Pod"), f"ns{i}") for i in range(2000)]
    m = ShardMap(2)
    v0 = m.version
    stats = m.rebalance(4, keys)
    assert m.version > v0
    assert stats["total"] == 2000
    # consistent hashing: 2 -> 4 moves ~(4-2)/4 = 1/2 of the keys,
    # not ~all of them (the whole point vs modulo hashing). Generous
    # envelope; a mod-N hash would move ~3/4 and fail the upper bound.
    assert 0.30 < stats["fraction"] < 0.70, stats
    owners = [m.owner(g, ns) for g, ns in keys]
    assert len(set(owners)) == 4


# ----------------------------------------------------------- scoped kube


def test_scoped_kube_filters_list_and_watch():
    kube = FakeKube()
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    for ns in ("ns0", "ns1"):
        kube.create(_namespace(ns))
        for i in range(4):
            kube.create(_pod(f"p{i}", ns))

    owns = lambda gvk, ns: ns == "ns0"  # noqa: E731
    scoped = ScopedKube(kube, owns)
    got = scoped.list(("", "v1", "Pod"))
    assert len(got) == 4
    assert {o["metadata"]["namespace"] for o in got} == {"ns0"}
    # cluster-scoped objects admit under ns=""
    assert scoped.list(("", "v1", "Namespace")) == [] \
        if not owns(("", "v1", "Namespace"), "") else True

    seen = []
    scoped.watch(("", "v1", "Pod"), lambda ev: seen.append(ev),
                 send_initial=False)
    kube.apply(_pod("w-in", "ns0"))
    kube.apply(_pod("w-out", "ns1"))
    names = {e.object["metadata"]["name"] for e in seen}
    assert "w-in" in names and "w-out" not in names
    # non-list/watch verbs pass through untouched
    assert scoped.get(("", "v1", "Pod"), "w-out", namespace="ns1")


# ------------------------------------------------------ broadcast pruning


def test_broadcast_prune_keeps_identity_and_columns():
    obj = _ingress("ing", "ns1", ["a.com"])
    obj["metadata"]["labels"] = {"team": "net"}
    obj["spec"]["tls"] = [{"hosts": ["a.com"], "secretName": "s"}]
    obj["data"] = {"huge": "x" * 64}
    pruned = ShardedAuditPlane._prune(obj, [("spec", "rules")])
    assert pruned["kind"] == "Ingress"
    meta = pruned["metadata"]
    assert meta["name"] == "ing" and meta["namespace"] == "ns1"
    assert meta["uid"] and meta["resourceVersion"]
    assert meta["labels"] == {"team": "net"}  # selector joins read them
    assert pruned["spec"]["rules"] == obj["spec"]["rules"]
    assert "tls" not in pruned["spec"], "non-join columns must not ship"
    assert "data" not in pruned
    # a column path missing on the object is skipped, not invented
    p2 = ShardedAuditPlane._prune(obj, [("spec", "nope", "deeper")])
    assert "spec" not in p2 or "nope" not in p2.get("spec", {})


def test_driver_broadcast_spec_names_join_partners():
    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    _library(client)
    spec = client.driver.audit_broadcast_spec()
    assert not spec["full"], \
        "compilable join templates must yield column sets, not a " \
        "full-inventory broadcast"
    assert spec["kinds"].get("Namespace", "missing") is None
    # uniqueingresshost binds a FIXED kind -> a per-kind column set;
    # uniqueserviceselector binds data.inventory.namespace[ns][_][_]
    # (any kind) -> the wildcard entry, each with its join columns
    assert ("spec", "rules") in [tuple(c) for c in
                                 spec["kinds"]["Ingress"]]
    assert ("spec", "selector") in [tuple(c) for c in
                                    spec["kinds"]["*"]]
    # WITHOUT the wildcard template, non-join kinds are owner-only
    narrow = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    narrow.add_template(REQUIRED_LABELS_TEMPLATE)
    narrow.add_template(policies.load("general/uniqueingresshost"))
    nspec = narrow.driver.audit_broadcast_spec()
    assert "*" not in nspec["kinds"] and "Pod" not in nspec["kinds"]


# --------------------------------------- in-process plane differential


class _InProcShardFleet:
    """AuditShardSupervisor stand-in: real LibrarySink + AuditSliceServer
    per shard, in this process — the plane's routing, pruning, sweep
    dispatch and composition run unchanged, minus the subprocess hop."""

    def __init__(self, shard_count):
        from gatekeeper_tpu.control.engine import LibrarySink

        self.clients = []
        self.sinks = []
        self.servers = []
        for k in range(shard_count):
            c = Backend(TpuDriver()).new_client([K8sValidationTarget()])
            if shard_count > 1:
                c.driver.set_audit_shard(k, shard_count)
            self.clients.append(c)
            self.sinks.append(LibrarySink(c))
            self.servers.append(
                AuditSliceServer(c, shard_id=k, shard_count=shard_count))

    def send(self, k, op, timeout=30.0):
        self.sinks[k](op)

    def replicate(self, op, obj):
        for sink in self.sinks:
            sink({"op": op, "obj": obj})

    def sweep(self, k, body, timeout_s=600.0):
        return self.servers[k].handle_http(body)


@pytest.mark.parametrize("shards", [1, 2, 4])
def test_sharded_plane_bit_equal_differential(shards):
    """THE acceptance invariant: the composed sharded round — routed,
    pruned, swept per shard, heap-merged — is bit-equal to the
    unsharded audit, join kinds included, at 1, 2, and 4 shards."""
    objs = _objects()
    baseline = _unsharded_results(objs)
    assert baseline, "workload must produce violations"
    join_msgs = [k for k in baseline if "host" in k[0] or
                 "selector" in k[0].lower()]
    assert join_msgs, "workload must exercise the join templates"

    kube = FakeKube()  # trackers are constructed but never started here
    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    fleet = _InProcShardFleet(shards)
    plane = ShardedAuditPlane(kube, leader, fleet, shards)
    plane.attach()
    _library(leader)   # on_change -> replicate to every shard sink
    for o in objs:     # on_change -> route_add (owner + broadcast)
        leader.add_data(o)
    results, stats = plane.sweep(None)
    assert [_result_key(r) for r in results] == baseline
    assert stats["shard_eval_max_s"] >= 0.0

    # sharding actually sharded: with > 1 shard no single slice client
    # audits the whole workload
    if shards > 1:
        per_shard = [len(c.audit().results()) for c in fleet.clients]
        assert sum(per_shard) == len(baseline)
        assert all(n < len(baseline) for n in per_shard), per_shard

    # deltas route too: removing the conflicting ingress heals the
    # cross-namespace join violation identically to unsharded
    leader.remove_data(_ingress("ing-b", "ns1", ["x.com"]))
    unsharded = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    _library(unsharded)
    for o in objs:
        unsharded.add_data(o)
    unsharded.remove_data(_ingress("ing-b", "ns1", ["x.com"]))
    after, _ = plane.sweep(None)
    assert [_result_key(r) for r in after] == \
        [_result_key(r) for r in unsharded.audit().results()]


def test_owner_only_kind_not_broadcast():
    """With no wildcard-join template loaded, Pods join nothing: a
    non-owner shard must never receive one (the 10M-object broadcast
    is the cost this plane exists to kill)."""
    ops = [[] for _ in range(2)]

    class Spy(_InProcShardFleet):
        def send(self, k, op, timeout=30.0):
            ops[k].append(op)
            super().send(k, op, timeout)

    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    fleet = Spy(2)
    plane = ShardedAuditPlane(FakeKube(), leader, fleet, 2)
    plane.attach()
    # required-labels (per-object) + the FIXED-kind ingress join only:
    # uniqueserviceselector's any-kind binding would wildcard-broadcast
    leader.add_template(REQUIRED_LABELS_TEMPLATE)
    leader.add_template(policies.load("general/uniqueingresshost"))
    leader.add_constraint(TEAM_CONSTRAINT)
    leader.add_constraint(
        _join_constraint("K8sUniqueIngressHost", "unique-hosts"))
    pod = _pod("solo", "nsX", {"team": "t"})
    leader.add_data(pod)
    holders = [k for k in range(2)
               if any(o.get("op") == "add_data" and
                      (o["obj"]["metadata"]["name"] == "solo")
                      for o in ops[k])]
    assert len(holders) == 1, "a Pod must land on exactly its owner"
    # a join partner broadcasts: full copy to the owner, pruned to the
    # rest — and the pruned copy carries the join columns
    ing = _ingress("bcast", "nsY", ["q.com"])
    ing["spec"]["extra"] = {"not": "a join column"}
    leader.add_data(ing)
    copies = [o["obj"] for k in range(2) for o in ops[k]
              if o.get("op") == "add_data" and
              o["obj"]["metadata"]["name"] == "bcast"]
    assert len(copies) == 2, "join partners must reach every shard"
    pruned = [c for c in copies if "extra" not in c.get("spec", {})]
    assert len(pruned) == 1, "exactly one copy is the pruned broadcast"
    assert pruned[0]["spec"]["rules"] == ing["spec"]["rules"]


# --------------------------------------------- subprocess fleet + chaos


def _cluster_kube(objs):
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    kube.register_kind(("", "v1", "Pod"), namespaced=True)
    kube.register_kind(("networking.k8s.io", "v1", "Ingress"),
                       namespaced=True)
    kube.register_kind(("", "v1", "Service"), namespaced=True)
    for o in objs:
        kube.apply(dict(o))
    for c in (TEAM_CONSTRAINT,
              _join_constraint("K8sUniqueIngressHost", "unique-hosts"),
              _join_constraint("K8sUniqueServiceSelector",
                               "unique-selectors")):
        kube.apply(dict(c))
    return kube


def _sharded_runtime(kube, shards, tmp_path):
    from gatekeeper_tpu.control.backplane import AuditShardSupervisor

    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    sock = str(tmp_path / "audit.sock")
    plane_box = []
    sup = AuditShardSupervisor(
        shards,
        socket_for=lambda k, s=sock: f"{s}.{k}",
        spawn_args=["--log-level", "WARNING"],
        snapshot_provider=lambda k: plane_box[0].sync_snapshot(k))
    plane = ShardedAuditPlane(kube, leader, sup, shards)
    plane_box.append(plane)
    plane.attach()
    _library(leader)
    mgr = AuditManager(kube, leader, interval=3600, shard_plane=plane)
    return leader, sup, plane, mgr


def test_subprocess_two_shard_differential_and_kill_chaos(tmp_path):
    """Real shard children over the backplane: the composed round is
    bit-equal to unsharded; then SIGKILL shard 1 and the NEXT round
    still converges bit-equal — the supervisor respawns the child, the
    resync rebuilds ONLY that slice from the leader's tree (generation
    bumps), the sweep retry re-dispatches only the orphaned partition,
    and per-kind statuses land once (no cross-shard clobber)."""
    objs = _objects(n_pods=10)
    kube = _cluster_kube(objs)

    # unsharded incremental manager over an IDENTICAL FakeKube (same
    # apply order -> same resourceVersions) is the oracle: results AND
    # status writes must match bit for bit
    okube = _cluster_kube(objs)
    oracle_client = Backend(TpuDriver()).new_client(
        [K8sValidationTarget()])
    _library(oracle_client)
    oracle = AuditManager(okube, oracle_client, interval=3600,
                          incremental=True)
    oracle_results = [_result_key(r) for r in oracle.audit_once()]
    assert oracle_results, "oracle cluster must produce violations"
    # materialized messages also match the raw-object baseline (the
    # kube round trip only rewrites resourceVersions)
    assert [k[0] for k in oracle_results] == \
        [k[0] for k in _unsharded_results(objs)]

    leader, sup, plane, mgr = _sharded_runtime(kube, 2, tmp_path)
    sup.start()
    try:
        round1 = [_result_key(r) for r in mgr.audit_once()]
        assert round1 == oracle_results
        gen_before = dict(sup.generation)

        # chaos: shard 1 dies; the next round must ride respawn+resync
        sup.kill_engine(1)
        round2 = [_result_key(r) for r in mgr.audit_once()]
        assert round2 == oracle_results, \
            "post-kill round must converge bit-equal"
        assert sup.generation[1] > gen_before[1], \
            "the victim must have been resynced (slice rebuilt)"
        assert sup.alive_count() == 2

        # status parity, kind by kind: same violation sets landed on
        # the same constraints as the unsharded oracle — one writer,
        # no cross-shard clobber
        for kind, name in (("K8sRequiredLabels", "pods-need-team"),
                           ("K8sUniqueIngressHost", "unique-hosts"),
                           ("K8sUniqueServiceSelector",
                            "unique-selectors")):
            gvk = ("constraints.gatekeeper.sh", "v1beta1", kind)
            want = (okube.get(gvk, name).get("status") or {})
            got = (kube.get(gvk, name).get("status") or {})
            assert got.get("totalViolations") == \
                want.get("totalViolations"), (kind, got, want)
            assert sorted((v["kind"], v.get("namespace", ""), v["name"],
                           v["message"])
                          for v in got.get("violations") or []) == \
                sorted((v["kind"], v.get("namespace", ""), v["name"],
                        v["message"])
                       for v in want.get("violations") or [])
    finally:
        sup.stop()
        plane.stop()


def test_subprocess_shard_resync_heals_routed_deltas(tmp_path):
    """Deltas applied WHILE a shard is down are not lost: the dirty
    mark drops the op, the monitor resync rebuilds the slice from the
    leader's (post-delta) tree, and the next round reflects them."""
    objs = _objects(n_pods=8)
    kube = _cluster_kube(objs)
    okube = _cluster_kube(objs)  # rv-identical oracle cluster
    oracle_client = Backend(TpuDriver()).new_client(
        [K8sValidationTarget()])
    _library(oracle_client)
    oracle = AuditManager(okube, oracle_client, interval=3600,
                          incremental=True)
    leader, sup, plane, mgr = _sharded_runtime(kube, 2, tmp_path)
    sup.start()
    try:
        assert oracle.audit_once() is not None
        assert mgr.audit_once() is not None
        sup.kill_engine(0)
        # a new unlabeled pod lands while shard 0 is a corpse: the
        # tracker's watch picks it up, the routed op to a dead/dirty
        # shard is dropped — the monitor's resync must carry it instead
        late = _pod("late-pod", "ns1", {"app": "late"})
        kube.apply(dict(late))
        okube.apply(dict(late))
        want = [_result_key(r) for r in oracle.audit_once()]
        got = [_result_key(r) for r in mgr.audit_once()]
        assert got == want
        assert any("late-pod" in k[3] for k in got), \
            "the while-dead delta must appear in the composed round"
    finally:
        sup.stop()
        plane.stop()
