"""Inventory-join compiler (ir/join.py) differential tests.

Cross-object templates (uniqueingresshost / uniqueserviceselector —
reference library/general/*/src.rego) must produce byte-identical results
through the aggregated-key join path and the interpreter driver, across
audit and admission, including the `not identical` own-copy exclusion.
"""

import pytest

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget


def ingress(name, ns, hosts, group="networking.k8s.io"):
    return {"apiVersion": f"{group}/v1", "kind": "Ingress",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"rules": [{"host": h} for h in hosts]}}


def service(name, ns, sel):
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"selector": sel}}


OBJS = [
    ingress("a", "ns1", ["x.com", "y.com"]),
    ingress("b", "ns2", ["x.com"]),           # conflicts with a
    ingress("c", "ns3", ["unique.com"]),      # no conflict
    ingress("d", "ns3", ["y.com", "z.com"]),  # conflicts with a on y.com
    service("s1", "ns1", {"app": "web", "tier": "fe"}),
    service("s2", "ns2", {"tier": "fe", "app": "web"}),  # same flattened
    service("s3", "ns2", {"app": "db"}),
    service("s4", "ns1", {}),
]

REVIEWS = [
    ingress("new", "ns9", ["x.com"]),          # CREATE conflicting
    ingress("c", "ns3", ["unique.com"]),       # UPDATE: own copy only
    ingress("c", "ns3", ["x.com"]),            # UPDATE into a conflict
    service("snew", "ns5", {"tier": "fe", "app": "web"}),
    service("s3", "ns2", {"app": "db"}),       # own copy only
]


def _run(driver):
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_template(policies.load("general/uniqueserviceselector"))
    for kind, name in (("K8sUniqueIngressHost", "unique-hosts"),
                       ("K8sUniqueServiceSelector", "unique-selectors")):
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": name}, "spec": {}})
    for o in OBJS:
        client.add_data(o)
    out = [sorted((r.msg,
                   (r.resource or {}).get("metadata", {}).get("name", ""))
                  for r in client.audit().results())]
    # run the audit twice: the steady-state (cached inv tables / keys)
    # second sweep must agree with the first
    out.append(sorted((r.msg,
                       (r.resource or {}).get("metadata", {}).get("name",
                                                                  ""))
                      for r in client.audit().results()))
    for rv in REVIEWS:
        out.append(sorted(
            r.msg for r in client.review(
                AugmentedUnstructured(rv)).results()))
    # mutate: delete the conflicting ingress, re-audit (cache invalidation)
    client.remove_data(OBJS[1])
    out.append(sorted((r.msg,
                       (r.resource or {}).get("metadata", {}).get("name",
                                                                  ""))
                      for r in client.audit().results()))
    return out


def test_join_templates_compile():
    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_template(policies.load("general/uniqueserviceselector"))
    assert sorted(drv._join_progs) == ["K8sUniqueIngressHost",
                                       "K8sUniqueServiceSelector"]
    assert drv.join_for("K8sUniqueIngressHost") is not None
    assert drv.join_for("K8sUniqueServiceSelector") is not None


def test_join_differential_audit_and_admission():
    a = _run(RegoDriver())
    b = _run(TpuDriver())
    assert a == b
    # the scenario must be non-vacuous: conflicts exist and resolve
    assert any(a[0]), "audit found no conflicts"
    assert a[2] and a[4], "admission conflicts missing"
    assert a[3] == [] and a[6] == [], "own-copy exclusion failed"


def test_join_device_path_matches_host_path():
    """The device searchsorted join and the host dict probe must agree
    on the same key tables."""
    import numpy as np

    from gatekeeper_tpu.utils.values import freeze

    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sUniqueIngressHost", "metadata": {"name": "u"},
        "spec": {}})
    for i in range(64):
        client.add_data(ingress(f"i{i}", f"ns{i % 8}",
                                [f"h{i % 24}.com", f"only{i}.com"]))
    jc = drv.join_for("K8sUniqueIngressHost")
    reviews = drv._inventory_reviews("admission.k8s.gatekeeper.sh")
    frz = [freeze(r) for r in reviews]
    inv = drv._inventory_tree("admission.k8s.gatekeeper.sh")
    host = jc.fires(frz, inv, drv._data_gen)
    saved = jc.MIN_DEVICE_REVIEWS
    try:
        jc.MIN_DEVICE_REVIEWS = 1  # force the device path
        jc._jit = None
        dev = jc.fires(frz, inv, drv._data_gen)
    finally:
        jc.MIN_DEVICE_REVIEWS = saved
    assert (np.asarray(host) == np.asarray(dev)).all()
    assert host.any(), "non-vacuous: some host collisions must fire"


def test_join_device_cache_not_keyed_by_shape_alone():
    """Two same-size review batches with different membership must get
    different fires through the device path — regression for the device
    input cache being keyed only by (data_gen, n, h, kb), which reused
    the previous batch's key tensors and silently under-fired."""
    import numpy as np

    from gatekeeper_tpu.utils.values import freeze

    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sUniqueIngressHost", "metadata": {"name": "u"},
        "spec": {}})
    client.add_data(ingress("base", "ns0", ["dup.com"]))
    jc = drv.join_for("K8sUniqueIngressHost")
    inv = drv._inventory_tree("admission.k8s.gatekeeper.sh")
    # batch A: 4 reviews, none colliding; batch B: same size/shape, all
    # colliding with the stored dup.com host
    def rv(name, ns, hosts):
        return freeze({"kind": {"group": "networking.k8s.io",
                                "version": "v1", "kind": "Ingress"},
                       "name": name, "namespace": ns,
                       "object": ingress(name, ns, hosts)})

    batch_a = [rv(f"a{i}", "nsA", [f"free{i}.com"]) for i in range(4)]
    batch_b = [rv(f"b{i}", "nsB", ["dup.com"]) for i in range(4)]
    saved = jc.MIN_DEVICE_REVIEWS
    try:
        jc.MIN_DEVICE_REVIEWS = 1  # force the device path
        fa = jc.fires(batch_a, inv, drv._data_gen)
        fb = jc.fires(batch_b, inv, drv._data_gen)
        # and back again, to also catch reuse in the other direction
        fa2 = jc.fires(batch_a, inv, drv._data_gen)
    finally:
        jc.MIN_DEVICE_REVIEWS = saved
    assert not np.asarray(fa).any(), "batch A has no collisions"
    assert np.asarray(fb).all(), "batch B must all fire"
    assert not np.asarray(fa2).any(), "stale device tensors reused"


def test_join_inv_tables_keyed_by_tree_identity():
    """Two different inventory trees at the same data generation must
    not share join tables — regression for the per-data_gen-only cache
    (second registered target reused the first target's tables)."""
    from gatekeeper_tpu.utils.values import freeze

    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    jc = drv.join_for("K8sUniqueIngressHost")
    tree_a = freeze({"cluster": {}, "namespace": {
        "ns1": {"networking.k8s.io/v1": {"Ingress": {
            "a": ingress("a", "ns1", ["x.com"])}}}}})
    tree_b = freeze({"cluster": {}, "namespace": {}})
    tabs_a = jc.inv_tables(tree_a, 7)
    tabs_b = jc.inv_tables(tree_b, 7)
    assert len(tabs_a[0][0]) == 1, "tree A has one join key"
    assert len(tabs_b[0][0]) == 0, "tree B is empty, must not reuse A"
    assert jc.inv_tables(tree_a, 7) is tabs_a, "cache hit expected"
