"""Inventory-join compiler (ir/join.py) differential tests.

Cross-object templates (uniqueingresshost / uniqueserviceselector —
reference library/general/*/src.rego) must produce byte-identical results
through the aggregated-key join path and the interpreter driver, across
audit and admission, including the `not identical` own-copy exclusion.
"""

import pytest

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget


def ingress(name, ns, hosts, group="networking.k8s.io"):
    return {"apiVersion": f"{group}/v1", "kind": "Ingress",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"rules": [{"host": h} for h in hosts]}}


def service(name, ns, sel):
    return {"apiVersion": "v1", "kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"selector": sel}}


OBJS = [
    ingress("a", "ns1", ["x.com", "y.com"]),
    ingress("b", "ns2", ["x.com"]),           # conflicts with a
    ingress("c", "ns3", ["unique.com"]),      # no conflict
    ingress("d", "ns3", ["y.com", "z.com"]),  # conflicts with a on y.com
    service("s1", "ns1", {"app": "web", "tier": "fe"}),
    service("s2", "ns2", {"tier": "fe", "app": "web"}),  # same flattened
    service("s3", "ns2", {"app": "db"}),
    service("s4", "ns1", {}),
]

REVIEWS = [
    ingress("new", "ns9", ["x.com"]),          # CREATE conflicting
    ingress("c", "ns3", ["unique.com"]),       # UPDATE: own copy only
    ingress("c", "ns3", ["x.com"]),            # UPDATE into a conflict
    service("snew", "ns5", {"tier": "fe", "app": "web"}),
    service("s3", "ns2", {"app": "db"}),       # own copy only
]


def _run(driver):
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_template(policies.load("general/uniqueserviceselector"))
    for kind, name in (("K8sUniqueIngressHost", "unique-hosts"),
                       ("K8sUniqueServiceSelector", "unique-selectors")):
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": name}, "spec": {}})
    for o in OBJS:
        client.add_data(o)
    out = [sorted((r.msg,
                   (r.resource or {}).get("metadata", {}).get("name", ""))
                  for r in client.audit().results())]
    # run the audit twice: the steady-state (cached inv tables / keys)
    # second sweep must agree with the first
    out.append(sorted((r.msg,
                       (r.resource or {}).get("metadata", {}).get("name",
                                                                  ""))
                      for r in client.audit().results()))
    for rv in REVIEWS:
        out.append(sorted(
            r.msg for r in client.review(
                AugmentedUnstructured(rv)).results()))
    # mutate: delete the conflicting ingress, re-audit (cache invalidation)
    client.remove_data(OBJS[1])
    out.append(sorted((r.msg,
                       (r.resource or {}).get("metadata", {}).get("name",
                                                                  ""))
                      for r in client.audit().results()))
    return out


def test_join_templates_compile():
    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_template(policies.load("general/uniqueserviceselector"))
    assert sorted(drv._join_progs) == ["K8sUniqueIngressHost",
                                       "K8sUniqueServiceSelector"]
    assert drv.join_for("K8sUniqueIngressHost") is not None
    assert drv.join_for("K8sUniqueServiceSelector") is not None


def test_join_differential_audit_and_admission():
    a = _run(RegoDriver())
    b = _run(TpuDriver())
    assert a == b
    # the scenario must be non-vacuous: conflicts exist and resolve
    assert any(a[0]), "audit found no conflicts"
    assert a[2] and a[4], "admission conflicts missing"
    assert a[3] == [] and a[6] == [], "own-copy exclusion failed"


def test_join_device_path_matches_host_path():
    """The device searchsorted join and the host dict probe must agree
    on the same key tables."""
    import numpy as np

    from gatekeeper_tpu.utils.values import freeze

    drv = TpuDriver()
    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/uniqueingresshost"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sUniqueIngressHost", "metadata": {"name": "u"},
        "spec": {}})
    for i in range(64):
        client.add_data(ingress(f"i{i}", f"ns{i % 8}",
                                [f"h{i % 24}.com", f"only{i}.com"]))
    jc = drv.join_for("K8sUniqueIngressHost")
    reviews = drv._inventory_reviews("admission.k8s.gatekeeper.sh")
    frz = [freeze(r) for r in reviews]
    inv = drv._inventory_tree("admission.k8s.gatekeeper.sh")
    host = jc.fires(frz, inv, drv._data_gen)
    saved = jc.MIN_DEVICE_REVIEWS
    try:
        jc.MIN_DEVICE_REVIEWS = 1  # force the device path
        jc._jit = None
        dev = jc.fires(frz, inv, drv._data_gen)
    finally:
        jc.MIN_DEVICE_REVIEWS = saved
    assert (np.asarray(host) == np.asarray(dev)).all()
    assert host.any(), "non-vacuous: some host collisions must fire"
