"""Cold-start elimination tests (ISSUE 8).

Covers the three tentpole legs: constraint-count (C-axis) power-of-two
bucketing (a library edit inside a bucket re-hits every cached device
program; results stay bit-equal to the unbucketed shapes, including the
mesh slab path), the AOT serialized-program store (a warm boot
deserializes instead of recompiling, and adopts the recorded sweep
signatures so the first sweep dispatches straight onto the device), and
the compile-cache observability satellites (enable_compile_cache returns
its status instead of swallowing failures; /debug/templates reports
per-kind compile provenance; the warm-cache prepack CLI).
"""

import json
import os
import time

import numpy as np
import pytest

from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.ir import aot as aotmod
from gatekeeper_tpu.ir.driver import _pad_cbucket, enable_compile_cache
from gatekeeper_tpu.ir.features import _bucket
from gatekeeper_tpu.target import K8sValidationTarget

LABEL_KEYS = ["owner", "team", "env", "cost", "tier",
              "zone", "org", "app", "rel", "stage"]


def _counts():
    return dict(aotmod.COMPILE_COUNTS)


def _delta(before, after):
    return {k: after[k] - before.get(k, 0) for k in after}


def _single_device_driver(aot_dir=None):
    """Single-device driver with the cost model pinned to the device
    path (the adaptive EMA must not route these small test sweeps back
    to the host and make the compile-count assertions vacuous)."""
    drv = TpuDriver(aot_dir=aot_dir)
    drv._mesh = None
    drv._dev_batch_lat_s = 1e-4
    return drv


@pytest.fixture
def fresh_xla_cache(tmp_path, monkeypatch):
    """Isolate the persistent XLA compilation cache per test: an
    executable XLA loaded from its own cache may serialize to a corrupt
    payload (see AotStore.save's round-trip probe), so warm-boot tests
    asserting source=aot need their first compiles genuinely fresh —
    not cache hits against the process-wide cache earlier tests
    populated."""
    import jax

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR",
                       str(tmp_path / "xla"))
    prev = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def _add_constraint(client, k):
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": f"need-{LABEL_KEYS[k]}"},
        "spec": {"parameters": {"labels": [{"key": LABEL_KEYS[k]}]}}})


def _labels_client(drv, n, n_cons):
    from gatekeeper_tpu import policies

    client = Backend(drv).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/requiredlabels"))
    for k in range(n_cons):
        _add_constraint(client, k)
    for i in range(n):
        labels = {LABEL_KEYS[j]: "x" for j in range(len(LABEL_KEYS))
                  if (i + j) % 3}
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": f"ns{i:05d}",
                                      "labels": labels}})
    return client


def _key(results):
    return sorted((r.msg, (r.resource or {}).get("metadata", {})
                   .get("name", "")) for r in results)


# ------------------------------------------------------ C-axis bucketing


def test_pad_cbucket_pads_to_bucket_replicating_edge():
    enc = {"slot": {"x": np.arange(12, dtype=np.int32).reshape(3, 4)}}
    out = _pad_cbucket(enc, 3)
    a = out["slot"]["x"]
    assert a.shape == (4, 4)
    assert (a[:3] == enc["slot"]["x"]).all()
    assert (a[3] == a[2]).all(), "padding replicates the LAST constraint"
    # exact power of two: no copy, no padding
    enc4 = {"slot": {"x": np.zeros((4, 2), np.int32)}}
    assert _pad_cbucket(enc4, 4) is enc4
    # parameterless programs have no encoded params to pad
    assert _pad_cbucket({}, 3) == {}


def test_cbucket_library_edit_within_bucket_zero_compiles(tmp_path):
    """Adding a constraint INSIDE the current power-of-two C bucket must
    re-hit every cached device program: zero XLA compiles, zero AOT
    store loads (the live executable serves). Crossing the bucket
    boundary acquires the new-shape program exactly once."""
    drv = _single_device_driver(aot_dir=str(tmp_path / "aot"))
    assert drv.cbucket and drv.aot.enabled
    client = _labels_client(drv, 2048, 5)  # C=5 -> bucket 8

    base = _counts()
    got5 = _key(client.audit().results())
    d = _delta(base, _counts())
    assert d["fresh"] + d["cache"] >= 1, \
        "first sweep must actually compile on the device path"
    assert drv._eval_counts.get(("K8sRequiredLabels", "device"))

    # within-bucket edit: 5 -> 6 constraints, still bucket 8
    _add_constraint(client, 5)
    base = _counts()
    got6 = _key(client.audit().results())
    d = _delta(base, _counts())
    assert d["fresh"] == 0 and d["cache"] == 0 and d["aot"] == 0, \
        f"within-bucket edit must not touch XLA: {d}"
    assert drv.last_audit_path == "single", \
        "the edited library must have re-swept (not the delta cache)"
    assert len(got6) > len(got5), "new constraint must add violations"

    # crossing the boundary: 6 -> 9 constraints -> bucket 16
    for k in range(6, 9):
        _add_constraint(client, k)
    base = _counts()
    got9 = _key(client.audit().results())
    d = _delta(base, _counts())
    assert d["fresh"] + d["cache"] == 1, \
        f"bucket crossing must compile exactly once: {d}"
    assert len(got9) > len(got6)

    # ... and only once: the next sweep at the new size is free
    # (healthy-value churn forces a real re-sweep past the delta cache)
    client.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "ns00000",
                                  "labels": {k: "y" for k in LABEL_KEYS}}})
    base = _counts()
    client.audit()
    d = _delta(base, _counts())
    assert d["fresh"] == 0 and d["cache"] == 0 and d["aot"] == 0


def test_cbucket_bit_equal_unbucketed_including_mesh_slab():
    """Bucketed C results must be bit-equal to the unbucketed shapes
    (GATEKEEPER_TPU_CBUCKET=0) and the interpreter — on the mesh SLAB
    path too, where the C slicing rides the per-shard decode."""
    from gatekeeper_tpu.client import RegoDriver
    from gatekeeper_tpu.ir.evaljax import _MeshSlabPairs

    N, NC = 4096, 5
    assert _bucket(NC) != NC, "non-vacuous: C must actually pad"

    dm = TpuDriver()
    assert dm._mesh is not None, "8-device platform must yield a mesh"
    assert dm.cbucket
    dm.MESH_MIN_REVIEWS = 64
    dm._dev_batch_lat_s = 1e-4
    dm.sweep_chunk = 64
    dm.mesh_slab_local = 256  # n_loc = 512 -> 2 slabs per shard
    cm = _labels_client(dm, N, NC)
    handles = []
    orig = dm._dispatch_handle

    def spy(*a, **k):
        h = orig(*a, **k)
        handles.append(h)
        return h

    dm._dispatch_handle = spy
    got_mesh = _key(cm.audit().results())
    dm._dispatch_handle = orig
    assert dm.last_audit_path == "mesh(data=8)", dm.last_audit_path
    assert any(isinstance(h, _MeshSlabPairs) for h in handles), \
        "audit did not take the mesh slab loop"

    os.environ["GATEKEEPER_TPU_CBUCKET"] = "0"
    try:
        ds = _single_device_driver()
        assert not ds.cbucket
    finally:
        del os.environ["GATEKEEPER_TPU_CBUCKET"]
    cs = _labels_client(ds, N, NC)
    got_single = _key(cs.audit().results())

    ci = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    from gatekeeper_tpu import policies
    ci.add_template(policies.load("general/requiredlabels"))
    for k in range(NC):
        _add_constraint(ci, k)
    for i in range(N):
        labels = {LABEL_KEYS[j]: "x" for j in range(len(LABEL_KEYS))
                  if (i + j) % 3}
        ci.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": f"ns{i:05d}",
                                  "labels": labels}})
    got_interp = _key(ci.audit().results())

    assert got_mesh == got_single == got_interp
    assert got_mesh, "non-vacuous: some violations must fire"


# -------------------------------------------------------- AOT store


def test_aot_store_warm_boot_deserializes(tmp_path, fresh_xla_cache):
    """Second driver on the same AOT dir: every device program
    deserializes (source=aot), zero XLA compiles, bit-equal results;
    /debug/templates reports the provenance."""
    aot_dir = str(tmp_path / "aot")
    d1 = _single_device_driver(aot_dir=aot_dir)
    c1 = _labels_client(d1, 2048, 5)
    got1 = _key(c1.audit().results())
    assert d1.aot.programs_count() >= 1, \
        "first boot must persist serialized executables"

    base = _counts()
    d2 = _single_device_driver(aot_dir=aot_dir)
    c2 = _labels_client(d2, 2048, 5)
    got2 = _key(c2.audit().results())
    d = _delta(base, _counts())
    assert got1 == got2
    assert d["aot"] >= 1 and d["fresh"] == 0 and d["cache"] == 0, \
        f"warm boot must deserialize, not compile: {d}"
    st = d2.warm_status()
    assert st["aot"]["aot"] >= 1 and st["aot"]["enabled"]

    dbg = d2.templates_debug()
    ev = dbg["templates"]["K8sRequiredLabels"]["compile"]
    assert ev and ev[-1]["source"] == "aot" and \
        ev[-1]["outcome"] == "ok" and "bucket_key" in ev[-1]


def test_aot_warm_boot_adopts_sweep_sigs(tmp_path, fresh_xla_cache):
    """With async compilation ON, a warm boot's ingest-time prewarm
    must deserialize the stored programs AND adopt the recorded sweep
    signatures, so the first sweep dispatches straight onto the device
    (no host-fallback round, no compile gate)."""
    aot_dir = str(tmp_path / "aot")
    d1 = _single_device_driver(aot_dir=aot_dir)
    c1 = _labels_client(d1, 2048, 5)
    got1 = _key(c1.audit().results())

    os.environ["GATEKEEPER_TPU_ASYNC_COMPILE"] = "1"
    try:
        d2 = _single_device_driver(aot_dir=aot_dir)
        assert d2.async_warm
        c2 = _labels_client(d2, 2048, 5)
    finally:
        os.environ["GATEKEEPER_TPU_ASYNC_COMPILE"] = "0"
    deadline = time.time() + 30
    while time.time() < deadline and not d2.warm_status()["warm"]:
        time.sleep(0.05)
    assert d2.warm_status()["warm"] >= 1, \
        "prewarm must mark stored sweep signatures warm before a sweep"
    base = _counts()
    got2 = _key(c2.audit().results())
    d = _delta(base, _counts())
    assert got1 == got2
    assert d["fresh"] == 0 and d["cache"] == 0
    assert d2._eval_counts.get(("K8sRequiredLabels", "device")), \
        "first sweep must dispatch on the device, not the host fallback"
    assert not d2._eval_counts.get(("K8sRequiredLabels", "interp"))


def test_adopted_sig_without_executable_serves_host_not_inline_compile(
        tmp_path, fresh_xla_cache):
    """A warm-boot-adopted sweep signature whose backing executable is
    gone (store GC'd, save refused on the previous boot) must NOT stall
    the serving path on an inline XLA compile: the sig is un-adopted,
    the host/interpreter answers this round, and the program re-warms
    in the background."""
    aot_dir = str(tmp_path / "aot")
    d1 = _single_device_driver(aot_dir=aot_dir)
    c1 = _labels_client(d1, 2048, 5)
    got1 = _key(c1.audit().results())
    assert d1.aot.programs_count() >= 1

    # simulate the executables vanishing while the manifest's sigs
    # survive (bounded-store eviction, manual cleanup, partial volume)
    for root, _dirs, files in os.walk(aot_dir):
        for fn in files:
            if fn.endswith(".aotx"):
                os.unlink(os.path.join(root, fn))

    os.environ["GATEKEEPER_TPU_ASYNC_COMPILE"] = "1"
    try:
        d2 = _single_device_driver(aot_dir=aot_dir)
        # pin the host model fast so the block-when-cheaper rule picks
        # the host fallback (the guard's outcome is then observable as
        # an interp eval instead of a waited-out background warm)
        d2._host_pair_rate = 1e9
        c2 = _labels_client(d2, 2048, 5)
        ct = d2.compiled_for("K8sRequiredLabels")
        # force the adoption a partially-loaded store would perform
        # (entries for the missing blobs were dropped at manifest load,
        # so the background prewarm alone would not adopt)
        d2._mark_stored_sigs_warm(ct.fingerprint, {"eval": 1})
        assert d2._warm_restored, "adoption precondition"
        got2 = _key(c2.audit().results())
    finally:
        os.environ["GATEKEEPER_TPU_ASYNC_COMPILE"] = "0"
    assert got2 == got1, "host fallback must still answer correctly"
    # the stale sig was un-adopted instead of inline-compiled: the
    # first audit served off the interpreter/host path while the
    # background thread re-warmed the program
    assert d2._eval_counts.get(("K8sRequiredLabels", "interp")), \
        "first audit must have served from the interpreter/host path"
    # background warm converges: a later audit runs on the device
    deadline = time.time() + 60
    while time.time() < deadline and d2.warm_status()["compiling"]:
        time.sleep(0.05)
    # a library edit (same C bucket) invalidates the results delta
    # cache, forcing a real re-sweep at the re-warmed shape; restore a
    # realistic host model so the cost model prefers the device again
    d2._host_pair_rate = 100.0
    _add_constraint(c2, 5)
    c2.audit()
    assert d2._eval_counts.get(("K8sRequiredLabels", "device")), \
        "re-warmed program must serve later audits on the device"


def test_aot_store_bounded_eviction_and_compaction(tmp_path,
                                                   fresh_xla_cache):
    """The store caps serialized programs (FIFO): oldest .aotx blobs
    are deleted, the manifest is compacted, and a reload sees only the
    survivors — a churn-heavy deployment can't fill the state volume."""
    import jax.numpy as jnp

    from gatekeeper_tpu.ir.aot import AotJit, AotStore

    # apply the fixture's fresh JAX_COMPILATION_CACHE_DIR to the live
    # jax config (no TpuDriver is constructed here to do it): compiles
    # must be genuinely fresh or save's round-trip probe refuses them
    enable_compile_cache()
    store = AotStore(str(tmp_path / "aot"))
    assert store.enabled
    store.max_programs = 2
    jit = AotJit(lambda x: jnp.sum(x) + 1, store=store,
                 fingerprint="fp-test", tag="t", kind="k")
    for n in (8, 16, 32):  # three distinct shapes -> three entries
        jit(np.zeros((n,), np.float32))
    assert store.programs_count() == 2, store.stats_snapshot()
    aotx = [f for f in os.listdir(store.dir) if f.endswith(".aotx")]
    assert len(aotx) == 2, "evicted blob must be deleted from disk"

    reloaded = AotStore(str(tmp_path / "aot"))
    assert reloaded.programs_count() == 2
    # survivors (the two NEWEST shapes) still deserialize
    loaded = 0
    for ent in reloaded.entries_for("fp-test"):
        key = reloaded.entry_key("fp-test", ent["tag"], ent["static"],
                                 ent["asig"])
        loaded += reloaded.load(key) is not None
    assert loaded == 2


def test_aot_store_survives_unusable_dir(tmp_path):
    """A file where the AOT dir should be: the store stays disabled and
    the driver serves normally (degrade, never break)."""
    bad = tmp_path / "occupied"
    bad.write_text("not a directory")
    drv = _single_device_driver(aot_dir=str(bad))
    assert not drv.aot.enabled
    client = _labels_client(drv, 256, 2)
    assert len(client.audit().results()) > 0


# ------------------------------------------------- compile cache gauge


def test_enable_compile_cache_reports_failure(tmp_path):
    """An unusable cache dir returns False (and is logged + gauged)
    instead of being silently swallowed; a usable one restores True."""
    import gatekeeper_tpu.ir.driver as drvmod

    occupied = tmp_path / "file"
    occupied.write_text("x")
    old = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = str(occupied / "sub")
    drvmod._cache_warned = False
    try:
        assert enable_compile_cache() is False
    finally:
        if old is None:
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        else:
            os.environ["JAX_COMPILATION_CACHE_DIR"] = old
    assert enable_compile_cache() is True


# ------------------------------------------------- warm-cache prepack


def test_warm_cache_cli_prepacks_from_snapshots(tmp_path, capsys):
    """`gatekeeper-tpu warm-cache --state-dir D`: restores the
    vocab/library/inventory snapshots, compiles inline, and persists
    serialized programs into <state-dir>/aot — the image/volume
    prepack path."""
    import logging as _logging

    from gatekeeper_tpu.control.main import warm_cache_main
    from gatekeeper_tpu.control.statestore import StateStore

    drv = _single_device_driver()
    client = _labels_client(drv, 2048, 5)
    client.audit()
    state = str(tmp_path / "state")
    store = StateStore(state)
    store.save("vocab", drv.vocab_snapshot())
    store.save("library", client.snapshot_library())
    store.save_blob("inventory",
                    {"tree": drv.inventory_snapshot() or {},
                     "tracker": {}}, codec="marshal")

    # warm_cache_main is a CLI entrypoint: its glog.setup() flips the
    # "gatekeeper" logger to propagate=False, which would blind caplog
    # for every later in-process test — snapshot and restore
    gklog = _logging.getLogger("gatekeeper")
    saved = (gklog.handlers[:], gklog.propagate, gklog.level)
    try:
        rc = warm_cache_main(["--state-dir", state])
    finally:
        gklog.handlers[:], gklog.propagate, gklog.level = saved
    out = [ln for ln in capsys.readouterr().out.splitlines()
           if ln.startswith("{")]
    assert rc == 0 and out
    summary = json.loads(out[-1])
    assert summary["restored"]["library"] and summary["objects"] == 2048
    assert summary["programs_stored"] >= 1
    assert os.path.isdir(os.path.join(state, "aot"))
