"""Offline fleet scan (control/scan.py): loader formats, dedupe
rejoin, the streaming reporter's exit-code contract, and the verdict
oracle — a scan verdict must be bit-equal to what a per-manifest
Client.review would have answered for the same object, dedupe path
included."""

from __future__ import annotations

import io
import json
import os

import pytest
import yaml

from gatekeeper_tpu.control import scan as scan_mod
from gatekeeper_tpu.control.scan import (
    DedupeTier,
    LoaderPool,
    Reporter,
    ScanFatal,
    build_inproc_tier,
    content_key,
    exit_code,
    is_k8s_manifest,
    parse_file,
    parse_jsonl,
    run_scan,
    scan_main,
    synthesize_request,
    walk_tree,
)
from gatekeeper_tpu.control.webhook import verdict_response
from gatekeeper_tpu.target import AugmentedReview

FIXTURE_TREE = os.path.join(os.path.dirname(__file__), "fixtures",
                            "fleet_scan")


def _pod(name, ns="a", labels=None):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         **({"labels": labels} if labels else {})},
            "spec": {"containers": [
                {"name": "c", "image": "registry.corp.example/app"}]}}


def _die_loader(*args, **kwargs):  # spawn target must be picklable
    os._exit(3)


class EchoTier:
    """Engine stand-in: denies pods whose name contains 'bad',
    synchronously. Counts what crossed the 'wire'."""

    name = "inproc"
    wants_bytes = False

    def __init__(self, fail_names=()):
        self.sent: list = []
        self.batches = 0
        self.fail_names = set(fail_names)

    def begin(self, batch):
        self.batches += 1
        self.sent.extend(r[3]["name"] for r in batch)
        out = []
        for rec in batch:
            name = rec[3]["name"]
            if name in self.fail_names:
                out.append({"error": f"engine failed on {name}"})
            elif "bad" in name:
                out.append({"allowed": False, "reason": "denied"})
            else:
                out.append({"allowed": True})
        return out

    def finish(self, token):
        return token

    def close(self):
        pass


# ------------------------------------------------------- loader formats


def test_tree_walk_skips_non_manifests(tmp_path):
    (tmp_path / "a.yaml").write_text("apiVersion: v1\nkind: Pod\n")
    (tmp_path / "sub").mkdir()
    (tmp_path / "sub" / "b.json").write_text("{}")
    (tmp_path / "README.md").write_text("docs")
    (tmp_path / ".hidden.yaml").write_text("x: 1")
    (tmp_path / ".git").mkdir()
    (tmp_path / ".git" / "c.yaml").write_text("x: 1")
    files, skipped = walk_tree(str(tmp_path))
    names = [os.path.relpath(f, tmp_path) for f in files]
    assert names == ["a.yaml", os.path.join("sub", "b.json")]
    assert skipped == 1  # README.md; dotfiles/dirs pruned silently


def test_multidoc_yaml_separators_and_skips(tmp_path):
    p = tmp_path / "m.yaml"
    with open(p, "w") as f:
        yaml.safe_dump_all(
            [_pod("one"), None, {"values": 1},  # blank + non-k8s doc
             {"kind": "List", "apiVersion": "v1",
              "items": [_pod("two"), _pod("three")]}], f)
    entries = list(parse_file(str(p)))
    states = [s for s, _ in entries]
    assert states == ["ok", "skip", "ok", "ok"]
    origins = [payload[0] for s, payload in entries if s == "ok"]
    # one origin per document, stable across re-parses
    assert origins == [f"{p}#0", f"{p}#2", f"{p}#3"]
    names = [payload[1]["metadata"]["name"]
             for s, payload in entries if s == "ok"]
    assert names == ["one", "two", "three"]


def test_jsonl_shards_partition_exactly(tmp_path):
    p = tmp_path / "inv.jsonl"
    with open(p, "w") as f:
        for i in range(17):
            f.write(json.dumps(_pod(f"p{i}")) + "\n")
        f.write("\n")           # blank line: ignored
        f.write("{broken\n")    # malformed line: one error record
    seen: list = []
    errs = 0
    for shard in range(3):
        for state, payload in parse_jsonl(str(p), shard, 3):
            if state == "ok":
                seen.append(payload[1]["metadata"]["name"])
            elif state == "err":
                errs += 1
    assert sorted(seen) == sorted(f"p{i}" for i in range(17))
    assert len(set(seen)) == 17  # no line claimed by two shards
    assert errs == 1


def test_malformed_files_error_but_never_abort(tmp_path):
    with open(tmp_path / "good.yaml", "w") as f:
        yaml.safe_dump_all([_pod("ok-one")], f)
    (tmp_path / "broken.yaml").write_text(
        "apiVersion: v1\nkind: Pod\n  bad: [\n")
    (tmp_path / "broken.json").write_text("{not json")
    tier = EchoTier()
    out = io.StringIO()
    files, _ = walk_tree(str(tmp_path))
    summary = run_scan(tier, LoaderPool("tree", files, 0, False), out)
    assert summary["errors"] == 2
    assert summary["allowed"] == 1  # the scan still evaluated the rest
    assert exit_code(summary) == 2  # errors take precedence
    recs = [json.loads(line) for line in out.getvalue().splitlines()]
    assert sum(1 for r in recs if r["outcome"] == "error") == 2
    assert all("error" in r for r in recs if r["outcome"] == "error")


def test_is_k8s_manifest():
    assert is_k8s_manifest(_pod("x"))
    assert not is_k8s_manifest({"values": {"x": 1}})
    assert not is_k8s_manifest({"apiVersion": "v1"})
    assert not is_k8s_manifest({"apiVersion": 3, "kind": "Pod"})
    assert not is_k8s_manifest(["apiVersion", "kind"])


# ---------------------------------------------------------------- dedupe


def test_dedupe_rejoins_and_counts(tmp_path):
    docs = [_pod("good"), _pod("bad-pod"), _pod("good"),
            _pod("bad-pod"), _pod("other", labels={"x": "y"})]
    with open(tmp_path / "m.yaml", "w") as f:
        yaml.safe_dump_all(docs, f)
    tier = EchoTier()
    out = io.StringIO()
    summary = run_scan(tier, LoaderPool(
        "tree", [str(tmp_path / "m.yaml")], 0, False), out)
    # only the 3 unique shapes crossed the wire
    assert sorted(tier.sent) == ["bad-pod", "good", "other"]
    assert summary["unique_evaluated"] == 3
    assert summary["deduped"] == 2
    assert summary["manifests"] == 5
    # every duplicate still gets its own record, with the SAME verdict
    recs = {r["origin"]: r
            for r in map(json.loads, out.getvalue().splitlines())}
    assert len(recs) == 5
    dedups = [r for r in recs.values() if r["outcome"] == "dedup"]
    assert len(dedups) == 2
    denied = [r for r in recs.values() if not r["allowed"]]
    assert len(denied) == 2  # bad-pod twice: one deny + one dedup
    assert summary["denied"] == 2
    assert exit_code(summary) == 1


def test_dedupe_never_replays_error_verdicts():
    d = DedupeTier(size=8)
    key = "k" * 32
    assert d.check(key, "o1") is None  # first: caller sends
    assert d.resolve(key, {"error": "shed"}) == []
    # the error was NOT cached: the next duplicate re-evaluates
    assert d.check(key, "o2") is None
    assert d.resolve(key, {"allowed": True}) == []
    assert d.check(key, "o3") == {"allowed": True}


def test_dedupe_lru_bounded():
    d = DedupeTier(size=2)
    for i in range(4):
        k = f"key{i}"
        assert d.check(k, f"o{i}") is None
        d.resolve(k, {"allowed": True})
    assert len(d._verdicts) == 2
    assert d.check("key0", "again") is None  # evicted: re-evaluates


def test_content_key_matches_decision_cache_recipe():
    from gatekeeper_tpu.control.webhook import DecisionCache

    req = synthesize_request(_pod("x"))
    req["uid"] = "ignored"
    req["timeoutSeconds"] = 5
    assert content_key(req) == DecisionCache.request_key(req).hex()


# ----------------------------------------------- engine failure honesty


def test_engine_failures_become_error_records(tmp_path):
    docs = [_pod("good"), _pod("flaky")]
    with open(tmp_path / "m.yaml", "w") as f:
        yaml.safe_dump_all(docs, f)
    tier = EchoTier(fail_names={"flaky"})
    out = io.StringIO()
    summary = run_scan(tier, LoaderPool(
        "tree", [str(tmp_path / "m.yaml")], 0, False), out)
    assert summary["errors"] == 1 and summary["allowed"] == 1
    assert exit_code(summary) == 2


def test_loader_death_is_error_records_not_a_hang(tmp_path,
                                                 monkeypatch):
    # a loader process that dies without its sentinel must surface as
    # an error record; the scan completes instead of blocking forever
    monkeypatch.setattr(scan_mod, "_loader_main", _die_loader)
    with open(tmp_path / "m.yaml", "w") as f:
        yaml.safe_dump_all([_pod("good")], f)
    tier = EchoTier()
    out = io.StringIO()
    summary = run_scan(tier, LoaderPool(
        "tree", [str(tmp_path / "m.yaml")], 1, False), out)
    assert summary["errors"] == 1
    assert "died" in out.getvalue()


def test_parallel_loaders_match_inline(tmp_path):
    """loaders=2 (spawned processes) and loaders=0 (inline) must produce
    the same records for the same source, origin for origin."""
    p = tmp_path / "inv.jsonl"
    with open(p, "w") as f:
        for i in range(40):
            f.write(json.dumps(_pod(f"p{i}" if i % 7 else f"bad{i}"))
                    + "\n")
    outs = []
    for loaders in (0, 2):
        out = io.StringIO()
        summary = run_scan(EchoTier(), LoaderPool(
            "jsonl", [str(p)], loaders, False), out, batch_size=16)
        assert summary["errors"] == 0
        outs.append(sorted(out.getvalue().splitlines()))
    assert outs[0] == outs[1]


# ------------------------------------------------------- verdict oracle


@pytest.fixture(scope="module")
def library_client():
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    client.add_template(policies.load("general/requiredlabels"))
    client.add_template(policies.load("general/allowedrepos"))
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "must-own"},
        "spec": {"parameters": {"labels": [
            {"key": "owner",
             "allowedRegex": "^[a-z]+.corp.example$"}]}}})
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sAllowedRepos", "metadata": {"name": "repos"},
        "spec": {"parameters": {"repos": [
            "registry.corp.example/", "gcr.io/corp/"]}}})
    return client


def _oracle_verdict(client, request):
    pairs = [(r.enforcement_action, r.msg)
             for r in client.review(AugmentedReview(request)).results()]
    return scan_mod._verdict_from_response(verdict_response(pairs))


def test_scan_verdicts_bit_equal_review_oracle(library_client):
    """Acceptance: scan verdicts == per-manifest Client.review on
    fixture-tree files, including the dedupe path (the fixture carries
    exact duplicates)."""
    files = [os.path.join(FIXTURE_TREE, f)
             for f in ("manifests_00.yaml", "manifests_01.yaml")]
    tier = build_inproc_tier([], client=library_client,
                             decision_cache=64, timeout_s=120.0)
    out = io.StringIO()
    try:
        summary = run_scan(tier, LoaderPool("tree", files, 0, False),
                           out, batch_size=64, dedupe_size=1024)
    finally:
        tier.close()
    assert summary["errors"] == 0
    recs = [json.loads(line) for line in out.getvalue().splitlines()]
    assert len(recs) == summary["manifests"] > 0
    assert summary["deduped"] > 0, \
        "fixture files must exercise the dedupe path"
    by_origin = {}
    for state, payload in (e for f in files for e in parse_file(f)):
        assert state == "ok"
        by_origin[payload[0]] = synthesize_request(payload[1])
    assert set(by_origin) == {r["origin"] for r in recs}
    for rec in recs:
        expected = _oracle_verdict(library_client,
                                   by_origin[rec["origin"]])
        got = {k: v for k, v in rec.items()
               if k not in ("origin", "outcome")}
        assert got == expected, rec["origin"]


def test_dedup_verdict_identical_to_first_occurrence(library_client):
    pod = _pod("same", labels={"owner": "team.corp.example"})
    with_dupes = [pod, _pod("bad"), pod, _pod("bad")]
    tier = build_inproc_tier([], client=library_client,
                             decision_cache=0, timeout_s=120.0)
    out = io.StringIO()
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "m.yaml")
        with open(p, "w") as f:
            yaml.safe_dump_all(with_dupes, f)
        try:
            run_scan(tier, LoaderPool("tree", [p], 0, False), out)
        finally:
            tier.close()
    recs = [json.loads(line) for line in out.getvalue().splitlines()]
    assert {r["outcome"] for r in recs} == {"allow", "deny", "dedup"}
    for outcome_pair in (("allow", 0), ("deny", 1)):
        first = next(r for r in recs if r["outcome"] == outcome_pair[0])
        twin = next(r for r in recs
                    if r["outcome"] == "dedup"
                    and r.get("allowed") == first["allowed"]
                    and r.get("reason") == first.get("reason"))
        assert {k: v for k, v in twin.items()
                if k not in ("origin", "outcome")} \
            == {k: v for k, v in first.items()
                if k not in ("origin", "outcome")}


# --------------------------------------------------- CLI + exit contract


def test_scan_main_exit_codes(tmp_path):
    pol = tmp_path / "policies"
    pol.mkdir()
    from gatekeeper_tpu import policies

    with open(pol / "req.yaml", "w") as f:
        yaml.safe_dump_all([
            policies.load("general/requiredlabels"),
            {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
             "kind": "K8sRequiredLabels",
             "metadata": {"name": "must-own"},
             "spec": {"parameters": {"labels": [{"key": "owner"}]}}},
        ], f)
    clean = tmp_path / "clean"
    clean.mkdir()
    with open(clean / "m.yaml", "w") as f:
        yaml.safe_dump_all([_pod("ok", labels={"owner": "me"})], f)
    out = tmp_path / "out.jsonl"
    assert scan_main([str(clean), "--policies", str(pol),
                      "--loaders", "0",
                      "--output", str(out)]) == 0
    denials = tmp_path / "denials"
    denials.mkdir()
    with open(denials / "m.yaml", "w") as f:
        yaml.safe_dump_all([_pod("no-labels")], f)
    assert scan_main([str(denials), "--policies", str(pol),
                      "--loaders", "0",
                      "--output", str(out)]) == 1
    (denials / "broken.yaml").write_text("a: [\n")
    assert scan_main([str(denials), "--policies", str(pol),
                      "--loaders", "0",
                      "--output", str(out)]) == 2
    # fatal: no policies for the in-process tier
    assert scan_main([str(clean), "--loaders", "0",
                      "--output", str(out)]) == 3
    # fatal: nonexistent source
    assert scan_main([str(tmp_path / "missing"), "--policies",
                      str(pol), "--loaders", "0"]) == 3


def test_scan_main_summary_file(tmp_path):
    pol = tmp_path / "pol.yaml"
    from gatekeeper_tpu import policies

    with open(pol, "w") as f:
        yaml.safe_dump_all([
            policies.load("general/requiredlabels"),
            {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
             "kind": "K8sRequiredLabels", "metadata": {"name": "o"},
             "spec": {"parameters": {"labels": [{"key": "owner"}]}}},
        ], f)
    src = tmp_path / "src"
    src.mkdir()
    with open(src / "m.yaml", "w") as f:
        yaml.safe_dump_all([_pod("a"), _pod("a"),
                            _pod("b", labels={"owner": "me"})], f)
    summary_path = tmp_path / "s.json"
    rc = scan_main([str(src), "--policies", str(pol), "--loaders", "0",
                    "--output", os.devnull,
                    "--summary", str(summary_path)])
    s = json.loads(summary_path.read_text())
    assert rc == 1
    assert s["manifests"] == 3
    assert s["deduped"] == 1
    assert s["unique_evaluated"] == 2
    assert s["denied"] == 2  # the deny and its dedup twin


# ---------------------------------------------------------------- preview


def test_preview_candidate_alias(library_client):
    """--preview ingests the candidate under the PR 9 content-hashed
    alias kind, isolated from any serving library."""
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    from tests.test_client import REQUIRED_LABELS_TEMPLATE

    client = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    alias = scan_mod.ingest_candidate(
        client, REQUIRED_LABELS_TEMPLATE,
        {"kind": "K8sRequiredLabelsTest",
         "spec": {"parameters": {"labels": ["owner"]}}})
    assert alias.startswith("K8sRequiredLabelsTestPV")
    assert len(alias) == len("K8sRequiredLabelsTest") + 2 + 12
    assert client.knows_kind(alias)
    tier2 = build_inproc_tier([], client=client, timeout_s=120.0)
    out = io.StringIO()
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "m.yaml")
        with open(p, "w") as f:
            yaml.safe_dump_all([_pod("nolabel"),
                                _pod("ok", labels={"owner": "x"})], f)
        try:
            summary = run_scan(tier2, LoaderPool("tree", [p], 0, False),
                               out)
        finally:
            tier2.close()
    assert summary["denied"] == 1
    assert summary["allowed"] == 1


def test_preview_fatal_without_kind():
    with pytest.raises(ScanFatal):
        scan_mod.ingest_candidate(object(), None, {"spec": {}})


# ---------------------------------------------------------------- stages


def test_scan_stages_registered():
    from gatekeeper_tpu.control.stages import STAGE_NAMES

    for s in ("scan_load", "scan_dedupe", "scan_feed", "scan_report"):
        assert s in STAGE_NAMES


def test_reporter_streams_not_accumulates():
    rep = Reporter(io.StringIO())
    for i in range(1000):
        rep.emit(f"o{i}", {"allowed": True}, "allow")
    assert rep.counts["allow"] == 1000
    # the reporter holds counters, never the verdict records
    assert not hasattr(rep, "records")
