"""Shipped policy library conformance.

Three tiers per shipped template (gatekeeper_tpu/policies/):
  1. it installs cleanly on both drivers;
  2. DIFFERENTIAL: over every input the reference's own src_test.rego
     corpus builds, our independently-authored rego must produce the same
     violation verdict and count as the reference's src.rego running on
     the same engine (behavior parity without copying);
  3. the reference's example.yaml fixture violates under the reference's
     constraint.yaml when evaluated against OUR template (drop-in check).
"""

from __future__ import annotations

import pytest
import yaml

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget

from .conftest import REFERENCE, requires_reference
from .test_ir_corpus import harvest_cases

TARGET = "admission.k8s.gatekeeper.sh"

# shipped name -> reference library dir (same basenames by construction)
REF_DIR = {name: f"library/{name}" for name in policies.names()}
assert len(REF_DIR) == 23


def test_library_is_complete():
    assert len(policies.names()) == 23
    assert len([n for n in policies.names() if n.startswith("general/")]) == 7


@pytest.mark.parametrize("name", policies.names())
def test_template_installs_on_both_drivers(name):
    for drv_cls in (RegoDriver, TpuDriver):
        client = Backend(drv_cls()).new_client([K8sValidationTarget()])
        client.add_template(policies.load(name))
        assert client.knows_kind(policies.kind_of(name))


@requires_reference
@pytest.mark.parametrize("name", policies.names())
def test_differential_vs_reference_corpus(name):
    """Verdict + count parity with the reference src.rego on every input
    harvested from the reference's own test suite."""
    ref_dir = REFERENCE / REF_DIR[name]
    src = (ref_dir / "src.rego").read_text()
    test_src = (ref_dir / "src_test.rego").read_text()
    cases = harvest_cases(src, test_src)
    assert cases, f"no corpus inputs harvested for {name}"

    kind = policies.kind_of(name)
    ours = RegoDriver()
    ours_client = Backend(ours).new_client([K8sValidationTarget()])
    ours_client.add_template(policies.load(name))

    theirs = RegoDriver()
    theirs_client = Backend(theirs).new_client([K8sValidationTarget()])
    theirs_client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": kind.lower()},
        "spec": {
            "crd": {"spec": {"names": {"kind": kind}}},
            "targets": [{"target": TARGET, "rego": src}],
        },
    })

    fired = 0
    for i, (doc, inventory) in enumerate(cases):
        review = doc.get("review") or {}
        params = doc.get("parameters")
        constraint = {
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": f"c{i}"},
            "spec": ({"parameters": params} if params is not None else {}),
        }
        inv = inventory if inventory is not None else {}
        a = ours._eval_template_violations(TARGET, constraint, review,
                                           "deny", inv, None)
        b = theirs._eval_template_violations(TARGET, constraint, review,
                                             "deny", inv, None)
        # message BYTES must match, not just verdict counts — users and
        # the reference's own tests key on exact messages, and the
        # policy files' provenance comments promise this pin
        assert sorted(r.msg for r in a) == sorted(r.msg for r in b), (
            f"{name} case {i}:\n"
            f"ours: {sorted(r.msg for r in a)[:4]}\n"
            f"reference: {sorted(r.msg for r in b)[:4]}"
        )
        fired += bool(b)
    assert fired > 0, f"{name}: corpus never exercised the violating path"


@requires_reference
@pytest.mark.parametrize("name", policies.names())
def test_reference_example_violates_our_template(name):
    """Drop-in check: the reference's published constraint + violating
    example must fire against OUR template."""
    ref_dir = REFERENCE / REF_DIR[name]
    cpath = ref_dir / "constraint.yaml"
    epath = ref_dir / "example.yaml"
    if not (cpath.is_file() and epath.is_file()):
        pytest.skip("reference ships no constraint/example fixture")
    constraint = yaml.safe_load(cpath.read_text())
    example = yaml.safe_load(epath.read_text())
    if name.startswith("general/unique"):
        pytest.skip("inventory-join example needs a populated cluster")

    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template(policies.load(name))
    client.add_constraint(constraint)
    # honor the constraint's namespace pin, if any
    spec = constraint.get("spec") or {}
    match = spec.get("match") or {}
    namespaces = match.get("namespaces") or []
    if namespaces:
        meta = example.setdefault("metadata", {})
        meta.setdefault("namespace", namespaces[0])
    results = client.review(AugmentedUnstructured(example)).results()
    assert results, f"{name}: reference example fixture did not violate"


def test_demo_runs(capsys):
    from gatekeeper_tpu.policies.demo import main

    main()
    out = capsys.readouterr().out
    assert "ALLOWED" in out and "DENIED" in out
    assert "no-privileged" in out
