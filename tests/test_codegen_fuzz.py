"""Grammar fuzzing: random rego modules, codegen vs interpreter.

The hand-written corpus exercises real templates; this generates random
rule bodies from a small grammar biased toward the codegen's tricky
machinery — join reordering (generators + pinning equalities),
review/params-pure memo classification, head-witness suffix memoization,
static input-path hoisting, negation, comprehensions — and asserts the
compiled evaluator is byte-identical to the interpreter over a grid of
inputs. Seeded; failures print the module source for replay.
"""

import random

import pytest

from gatekeeper_tpu.rego.codegen import Unsupported, compile_module
from gatekeeper_tpu.rego.interp import UNDEF, Interpreter
from gatekeeper_tpu.rego.parser import parse_module
from gatekeeper_tpu.utils.values import freeze, thaw

FIELDS = ["a", "b", "c", "key", "name", "labels", "items"]
STRS = ['"x"', '"y"', '"zz"', '""']
NUMS = ["0", "1", "2", "10"]


class Gen:
    def __init__(self, rng):
        self.r = rng
        self.n = 0

    def var(self):
        self.n += 1
        return f"v{self.n}"

    def path(self, root):
        segs = ".".join(self.r.choices(FIELDS,
                                       k=self.r.randint(1, 3)))
        return f"{root}.{segs}"

    def scalar(self):
        return self.r.choice(STRS + NUMS)

    def body(self, depth=0):
        lits = []
        bound = []
        root1 = self.r.choice(["input.review", "input.parameters"])
        # a generator over a dict/array with a key var
        k, v = self.var(), self.var()
        lits.append(f"{v} := {self.path(root1)}[{k}]")
        bound += [k, v]
        if self.r.random() < 0.7:
            # second generator over the OTHER section (join shape)
            root2 = ("input.parameters" if root1 == "input.review"
                     else "input.review")
            e = self.var()
            lits.append(f"{e} := {self.path(root2)}[_]")
            bound.append(e)
            if self.r.random() < 0.8:
                # pinning equality: the join-reorder trigger
                lits.append(f"{e}.{self.r.choice(FIELDS)} == {k}")
        if self.r.random() < 0.5:
            lits.append(f"{v} != {self.scalar()}")
        if self.r.random() < 0.4:
            lits.append(
                f"not {self.r.choice(bound)} == {self.scalar()}")
        if self.r.random() < 0.5:
            c = self.var()
            src = self.r.choice(["input.review", "input.parameters"])
            lits.append(f"{c} := {{ x | x := {self.path(src)}[_] }}")
            lits.append(f"count({c}) >= {self.r.choice(NUMS)}")
            bound.append(c)
        if self.r.random() < 0.4:
            lits.append(f"startswith({v}, {self.r.choice(STRS)})")
        if self.r.random() < 0.35:
            # round-5 builtin tail over arbitrary-typed bound values:
            # most raise BuiltinError on non-string/number inputs, and
            # the literal going UNDEFINED identically in interpreter
            # and codegen is the contract worth fuzzing
            lits.append(self.r.choice([
                f'glob.quote_meta({v}) != ""',
                f"time.parse_duration_ns({v}) >= 0",
                f'net.cidr_contains("10.0.0.0/8", {v})',
                f'regex.globs_match({v}, "a*")',
                f'regex.template_match("u:{{.*}}", {v}, "{{", "}}")',
                f"lt({v}, 5)",
                f"rem(to_number({v}), 3) == 0",
                f"not gte({v}, 100)",
            ]))
        m = self.var()
        w = self.r.sample(bound, min(len(bound), 2))
        fmt = "%v-" * len(w)
        lits.append(f'{m} := sprintf("{fmt}", [{", ".join(w)}])')
        return lits, m

    def module(self):
        rules = []
        for i in range(self.r.randint(1, 3)):
            lits, m = self.body()
            body = "\n  ".join(lits)
            rules.append(
                f'violation[{{"msg": {m}, "n": {i}}}] {{\n  {body}\n}}')
        return "package fz\n\n" + "\n\n".join(rules)


def rand_value(rng, depth=0):
    roll = rng.random()
    if depth >= 2 or roll < 0.4:
        return rng.choice(["x", "y", "zz", "", 0, 1, 2, 10, True, None])
    if roll < 0.65:
        return [rand_value(rng, depth + 1) for _ in range(rng.randint(0, 3))]
    return {rng.choice(FIELDS): rand_value(rng, depth + 1)
            for _ in range(rng.randint(0, 3))}


def rand_input(rng):
    return {
        "review": {rng.choice(FIELDS): rand_value(rng)
                   for _ in range(rng.randint(0, 4))},
        "parameters": {rng.choice(FIELDS): rand_value(rng)
                       for _ in range(rng.randint(0, 4))},
    }


@pytest.mark.parametrize("seed", range(8))
def test_codegen_matches_interpreter_on_random_modules(seed):
    rng = random.Random(seed)
    tried = agreed = 0
    for case in range(40):
        src = Gen(rng).module()
        try:
            module = parse_module(src)
            fn = compile_module(module)
        except Unsupported:
            continue
        interp = Interpreter({"m": module})
        for probe in range(6):
            inp = freeze(rand_input(rng))
            want = interp.eval_rule(("fz",), "violation", inp)
            got = fn.__input_call__(inp, freeze({}))
            tried += 1
            if want is UNDEF:
                want = frozenset()
            assert got == want, (
                f"seed={seed} case={case} probe={probe}\n{src}\n"
                f"input={thaw(inp)}\ninterp={thaw(want)}\n"
                f"codegen={thaw(got)}")
            agreed += 1
    assert tried >= 60, f"fuzzer generated too few comparable cases: {tried}"


class DevGen(Gen):
    """Variant biased toward the device compiler's subset: review paths
    rooted at object.*, parameter lists, string predicates."""

    def path(self, root):
        if root == "input.review":
            root = "input.review.object"
        segs = ".".join(self.r.choices(FIELDS, k=self.r.randint(1, 2)))
        return f"{root}.{segs}"


@pytest.mark.parametrize("seed", range(4))
def test_device_compiler_parity_on_random_templates(seed):
    """Random templates through BOTH drivers end-to-end: whatever subset
    of random modules the device compiler accepts must audit identically
    to the interpreter (over-fire is corrected by materialization; this
    equality also catches UNDER-fire)."""
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    rng = random.Random(1000 + seed)
    compiled_any = 0
    for case in range(12):
        body = DevGen(rng).module().replace("package fz",
                                            "package tfz")
        tpl = {"apiVersion": "templates.gatekeeper.sh/v1beta1",
               "kind": "ConstraintTemplate",
               "metadata": {"name": "tfz"},
               "spec": {"crd": {"spec": {"names": {"kind": "TFz"}}},
                        "targets": [{
                            "target": "admission.k8s.gatekeeper.sh",
                            "rego": body}]}}
        params = {rng.choice(FIELDS): rand_value(rng)
                  for _ in range(rng.randint(0, 3))}
        objs = []
        for i in range(25):
            o = {"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": f"o{i}", "namespace": "d"}}
            for f in rng.sample(FIELDS, rng.randint(0, 4)):
                o[f] = rand_value(rng)
            objs.append(o)
        outs = []
        for drv_cls in (RegoDriver, TpuDriver):
            drv = drv_cls()
            c = Backend(drv).new_client([K8sValidationTarget()])
            try:
                c.add_template(tpl)
            except Exception:
                outs = None
                break
            c.add_constraint({
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": "TFz", "metadata": {"name": "t"},
                "spec": {"parameters": params}})
            for o in objs:
                c.add_data(o)
            outs.append(sorted(
                (r.msg, (r.resource or {}).get("metadata",
                                               {}).get("name", ""))
                for r in c.audit().results()))
            if drv_cls is TpuDriver and drv.compiled_for("TFz"):
                compiled_any += 1
        if outs is None:
            continue
        assert outs[0] == outs[1], (
            f"seed={seed} case={case} device/interp divergence\n{body}\n"
            f"params={params}\ninterp={outs[0][:4]}\ntpu={outs[1][:4]}")
    # not every random module device-compiles, but the property must not
    # be vacuous across a seed's cases
    assert compiled_any >= 1, "no random template device-compiled"
