"""gRPC service tests beyond the shared conformance matrix
(tests/test_client.py runs its whole e2e suite over the wire already):
batched review RPC, the TPU-driver-backed server, error envelope
round-tripping, and concurrent client requests."""

import threading

import pytest

pytest.importorskip("grpc")

from gatekeeper_tpu.client.types import (  # noqa: E402
    ClientError,
    UnrecognizedConstraintError,
)
from gatekeeper_tpu.service import RemoteClient, make_server  # noqa: E402
from gatekeeper_tpu.target import AugmentedUnstructured  # noqa: E402

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sreqlbl"},
    "spec": {
        "crd": {"spec": {
            "names": {"kind": "K8sReqLbl"},
            "validation": {"openAPIV3Schema": {"properties": {
                "labels": {"type": "array",
                           "items": {"type": "string"}}}}},
        }},
        "targets": [{"target": "admission.k8s.gatekeeper.sh", "rego": """
package k8sreqlbl
violation[{"msg": msg}] {
  provided := {l | input.review.object.metadata.labels[l]}
  required := {l | l := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("missing: %v", [missing])
}
"""}],
    },
}

CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sReqLbl", "metadata": {"name": "need-owner"},
    "spec": {"parameters": {"labels": ["owner"]}},
}


def ns(name, labels=None):
    meta = {"name": name}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "Namespace", "metadata": meta}


@pytest.fixture(params=["rego", "tpu"])
def remote(request):
    server, port = make_server(driver=request.param)
    server.start()
    rc = RemoteClient(f"127.0.0.1:{port}")
    try:
        yield rc
    finally:
        rc.close()
        server.stop(grace=None)


def test_review_batch_rpc(remote):
    remote.add_template(TEMPLATE)
    remote.add_constraint(CONSTRAINT)
    objs = [AugmentedUnstructured(ns(f"n{i}",
                                     {"owner": "x"} if i % 2 else None))
            for i in range(10)]
    out = remote.review_batch(objs)
    assert len(out) == 10
    for i, resps in enumerate(out):
        msgs = [r.msg for r in resps.results()]
        if i % 2:
            assert msgs == []
        else:
            assert msgs == ['missing: {"owner"}']


def test_review_stream_pipelined_batches(remote):
    """Streaming ingest (ISSUE 14): batches pipeline over ONE
    bidirectional stream, responses come back per batch in order, and
    a bad batch answers an in-stream error without killing the
    stream's earlier results."""
    remote.add_template(TEMPLATE)
    remote.add_constraint(CONSTRAINT)
    batches = [
        [AugmentedUnstructured(ns("a0")),
         AugmentedUnstructured(ns("a1", {"owner": "x"}))],
        [AugmentedUnstructured(ns("b0", {"owner": "y"}))],
        [AugmentedUnstructured(ns("c0"))],
    ]
    out = list(remote.review_stream(batches))
    assert len(out) == 3
    assert [[len(r.results()) for r in b] for b in out] == \
        [[1, 0], [0], [1]]
    assert out[0][0].results()[0].msg == 'missing: {"owner"}'


def test_review_stream_bad_batch_survives_on_the_wire(remote):
    """A malformed batch answers an in-stream {"error": ...} message;
    the batches before AND after it still evaluate — one bad manifest
    must not kill a million-manifest scan's stream."""
    import grpc as grpc_mod  # noqa: F401 - importorskip'd above

    from gatekeeper_tpu.service.server import (
        SERVICE_NAME,
        _dumps,
        _loads,
    )

    remote.add_template(TEMPLATE)
    remote.add_constraint(CONSTRAINT)
    call = remote._channel.stream_stream(
        f"/{SERVICE_NAME}/ReviewStream",
        request_serializer=_dumps, response_deserializer=_loads)
    msgs = [
        {"reviews": [{"object": ns("ok", {"owner": "x"})}]},
        {"reviews": [{"bogus": 1}]},  # no object/admissionRequest/raw
        {"reviews": [{"object": ns("bad")}]},
    ]
    out = list(call(iter(msgs)))
    assert len(out) == 3
    assert "responses" in out[0]
    assert out[1].get("error", {}).get("error") == "ClientError"
    # the stream SURVIVED the bad batch and kept evaluating
    results = out[2]["responses"][0]["byTarget"][
        "admission.k8s.gatekeeper.sh"]["results"]
    assert len(results) == 1


def test_ingest_surface_excludes_library_lifecycle():
    """--ingest-grpc serves the evaluation-only method set: bulk
    callers can stream reviews but can never rewrite the serving
    library through the ingest port."""
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.service import INGEST_METHODS
    from gatekeeper_tpu.service.client import RemoteTransportError
    from gatekeeper_tpu.target import K8sValidationTarget

    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template(TEMPLATE)
    client.add_constraint(CONSTRAINT)
    server, port = make_server(client=client, expose=INGEST_METHODS)
    server.start()
    rc = RemoteClient(f"127.0.0.1:{port}")
    try:
        out = list(rc.review_stream(
            [[AugmentedUnstructured(ns("x"))]]))
        assert len(out) == 1 and len(out[0][0].results()) == 1
        with pytest.raises(RemoteTransportError):
            rc.add_template(TEMPLATE)
        with pytest.raises(RemoteTransportError):
            rc.reset()
    finally:
        rc.close()
        server.stop(grace=None)


def test_audit_over_wire(remote):
    remote.add_template(TEMPLATE)
    remote.add_constraint(CONSTRAINT)
    remote.add_data(ns("bad"))
    remote.add_data(ns("good", {"owner": "me"}))
    results = remote.audit().results()
    assert [r.resource["metadata"]["name"] for r in results] == ["bad"]
    assert results[0].constraint["metadata"]["name"] == "need-owner"
    assert results[0].enforcement_action == "deny"


def test_error_envelope_roundtrip(remote):
    with pytest.raises(UnrecognizedConstraintError) as ei:
        remote.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "NoSuchKind", "metadata": {"name": "x"}, "spec": {}})
    assert ei.value.kind == "NoSuchKind"
    bad = dict(TEMPLATE, spec=dict(TEMPLATE["spec"]))
    bad["spec"]["targets"] = [{"target": "admission.k8s.gatekeeper.sh",
                               "rego": "package x\nviolation[{"}]
    with pytest.raises(ClientError):
        remote.add_template(bad)


def test_concurrent_clients(remote):
    remote.add_template(TEMPLATE)
    remote.add_constraint(CONSTRAINT)
    errs = []

    def worker(i):
        try:
            for j in range(5):
                resps = remote.review(
                    AugmentedUnstructured(ns(f"w{i}-{j}")))
                assert len(resps.results()) == 1
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs


def test_template_kinds_and_dump(remote):
    remote.add_template(TEMPLATE)
    assert remote.template_kinds() == ["K8sReqLbl"]
    assert remote.knows_kind("K8sReqLbl")
    assert "modules" in remote.dump()
    remote.reset()
    assert remote.template_kinds() == []


def test_unhandled_dict_parity(remote):
    """A dict the local handler can't classify must come back unhandled
    over the wire too (r3 code-review finding: the wire mapping used to
    wrap it, silently making it handled)."""
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    local = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    weird = {"foo": 1}
    assert sorted(local.review(weird).by_target) == \
        sorted(remote.review(weird).by_target) == []


def test_transport_error_is_not_client_error():
    from gatekeeper_tpu.service import RemoteClient, RemoteTransportError

    rc = RemoteClient("127.0.0.1:1")  # nothing listens there
    with pytest.raises(RemoteTransportError):
        rc.template_kinds()
    rc.close()


def test_bind_failure_raises():
    server, port = make_server(driver="rego")
    server.start()
    try:
        # newer grpc raises RuntimeError itself; the port==0 OSError path
        # covers versions that signal failure by returning 0
        with pytest.raises((OSError, RuntimeError)):
            make_server(driver="rego", address=f"127.0.0.1:{port}")
    finally:
        server.stop(grace=None)


def test_join_templates_over_the_wire(remote):
    """Round-4 feature through the gRPC seam: the inventory-join
    templates (device-compiled in the TpuDriver-backed server) must
    produce the same audit/review answers as a local interpreter
    client."""
    from gatekeeper_tpu import policies
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.target import AugmentedUnstructured, \
        K8sValidationTarget

    rc = remote

    def ingress(name, ns, hosts):
        return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
                "metadata": {"name": name, "namespace": ns},
                "spec": {"rules": [{"host": h} for h in hosts]}}

    local = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    outs = []
    for client in (rc, local):
        client.add_template(policies.load("general/uniqueingresshost"))
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sUniqueIngressHost",
            "metadata": {"name": "uniq"}, "spec": {}})
        client.add_data(ingress("a", "ns1", ["x.com"]))
        client.add_data(ingress("b", "ns2", ["x.com", "y.com"]))
        client.add_data(ingress("c", "ns3", ["z.com"]))
        aud = sorted((r.msg,
                      (r.resource or {}).get("metadata", {}).get("name"))
                     for r in client.audit().results())
        rev = sorted(r.msg for r in client.review(
            AugmentedUnstructured(ingress("new", "ns9",
                                          ["y.com"]))).results())
        own = sorted(r.msg for r in client.review(
            AugmentedUnstructured(ingress("c", "ns3",
                                          ["z.com"]))).results())
        outs.append((aud, rev, own))
    assert outs[0] == outs[1]
    aud, rev, own = outs[0]
    assert len(aud) == 2 and rev and own == []
