"""Incremental inventory mutation (VERDICT r3 #4).

A single-object replacement between audits must NOT force full
re-extraction / re-upload: the patch journal replays the change onto the
cached review list, signature cache, frozen tree, match mask, and feature
tensors. Differential correctness against the interpreter driver is the
authority; the mechanism assertions pin the no-rebuild property.
"""

import numpy as np
import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.parallel.workload import (
    REQUIRED_LABELS_TEMPLATE, synth_constraints, synth_objects)
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"
N, C = 600, 12


def _setup(driver):
    client = Backend(driver).new_client([K8sValidationTarget()])
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    for c in synth_constraints(C, seed=1):
        client.add_constraint(c)
    for o in synth_objects(N, violate_frac=0.05, seed=0):
        client.add_data(o)
    return client


def _mutated(i: int, labels: dict) -> dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": f"ns-{i}", "labels": labels}}


MUTATIONS = [
    _mutated(7, {}),                                   # all labels gone
    _mutated(123, {"owner": "alpha.corp.example",      # healthy subset
                   "team": "payments", "env": "prod", "tier": "frontend",
                   "region": "us-east1", "app": "shop",
                   "cost-center": "cc-100", "compliance": "pci",
                   "zone": "a", "dept": "eng"}),
    _mutated(300, {"owner": "###BAD###", "team": "x"}),
]


def _audit_sorted(client):
    return sorted((r.msg, (r.resource or {}).get("metadata",
                                                 {}).get("name", ""))
                  for r in client.audit().results())


def test_mutation_parity_with_interpreter():
    ci = _setup(RegoDriver())
    ct = _setup(TpuDriver())
    assert _audit_sorted(ci) == _audit_sorted(ct)
    for m in MUTATIONS:
        ci.add_data(m)
        ct.add_data(m)
        assert _audit_sorted(ci) == _audit_sorted(ct)
    # deletes fall back to a rebuild but must stay correct
    ci.remove_data(MUTATIONS[0])
    ct.remove_data(MUTATIONS[0])
    assert _audit_sorted(ci) == _audit_sorted(ct)


def test_single_object_mutation_patches_not_rebuilds(monkeypatch):
    drv = TpuDriver()
    client = _setup(drv)
    client.audit()
    client.audit()  # steady state
    reviews_before = drv._inventory_reviews(TARGET)
    meta = drv._feat_cache["K8sRequiredLabels"]["__meta__"]
    feats_before = meta["feats"]
    leaf_ids = {id(a) for arrs in feats_before.values()
                for a in arrs.values()}
    mask_before = drv._mask_cache[(TARGET, "K8sRequiredLabels")][2]

    calls = {"extract": 0}
    import gatekeeper_tpu.ir.driver as drvmod
    orig = drvmod.extract_batch

    def counting(*a, **k):
        calls["extract"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(drvmod, "extract_batch", counting)

    client.add_data(MUTATIONS[0])
    res = client.audit()

    assert calls["extract"] == 0, "full re-extraction ran"
    # same review list object, one review replaced in place
    reviews_after = drv._inventory_reviews(TARGET)
    assert reviews_after is reviews_before
    # same feature tensors (patched rows), same device-cacheable leaves
    meta2 = drv._feat_cache["K8sRequiredLabels"]["__meta__"]
    assert meta2["feats"] is feats_before
    assert {id(a) for arrs in meta2["feats"].values()
            for a in arrs.values()} == leaf_ids
    # same mask array object, patched row
    assert drv._mask_cache[(TARGET, "K8sRequiredLabels")][2] is mask_before
    # and the mutated object's violations actually changed
    assert any((r.resource or {}).get("metadata", {}).get("name") == "ns-7"
               for r in res.results()), "mutation not reflected in audit"


def test_mutation_journal_breaks_on_insert_and_delete():
    drv = TpuDriver()
    client = _setup(drv)
    client.audit()
    # insert: a NEW object shifts indices -> journal breaks -> rebuild,
    # results must still be exact vs interpreter
    new_obj = _mutated(99999, {})
    ci = _setup(RegoDriver())
    ci.add_data(new_obj)
    client.add_data(new_obj)
    a, b = _audit_sorted(ci), _audit_sorted(client)
    assert a == b
    assert any(name == "ns-99999" for _m, name in b)


def test_namespace_mutation_with_namespace_selector():
    """Mutating a Namespace changes OTHER reviews' match verdicts via
    namespaceSelector — the journal must break (full rebuild), and both
    drivers must agree in both directions (match -> no-match -> match)."""
    def setup(driver):
        client = Backend(driver).new_client([K8sValidationTarget()])
        client.add_template(REQUIRED_LABELS_TEMPLATE)
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sRequiredLabels", "metadata": {"name": "sel"},
            "spec": {
                "match": {"namespaceSelector":
                          {"matchLabels": {"env": "prod"}}},
                "parameters": {"labels": [{"key": "team"}]},
            }})
        client.add_data({"apiVersion": "v1", "kind": "Namespace",
                         "metadata": {"name": "ns-x",
                                      "labels": {"env": "prod"}}})
        client.add_data({"apiVersion": "v1", "kind": "Pod",
                         "metadata": {"name": "p1", "namespace": "ns-x",
                                      "labels": {}}})
        return client

    ci, ct = setup(RegoDriver()), setup(TpuDriver())
    assert _audit_sorted(ci) == _audit_sorted(ct)
    assert _audit_sorted(ct), "selector must match initially"
    flip = {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "ns-x", "labels": {"env": "dev"}}}
    ci.add_data(flip)
    ct.add_data(flip)
    assert _audit_sorted(ci) == _audit_sorted(ct) == []
    back = {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "ns-x", "labels": {"env": "prod"}}}
    ci.add_data(back)
    ct.add_data(back)
    assert _audit_sorted(ci) == _audit_sorted(ct)
    assert _audit_sorted(ct), "selector must match again"
