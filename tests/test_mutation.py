"""Mutation subsystem: location-path parser, apply semantics, schema
conflict quarantine, convergence, JSONPatch emission, batched
applicability (differential vs the per-object predicate), the /v1/mutate
webhook, and the mutator controller lifecycle."""

import base64
import copy
import http.client
import json
import random
import time

import numpy as np
import pytest

from gatekeeper_tpu.control.main import Runtime, build_parser
from gatekeeper_tpu.control.metrics import REGISTRY
from gatekeeper_tpu.control.webhook import MicroBatcher, MutationHandler
from gatekeeper_tpu.mutation import (
    MutationError,
    MutationSystem,
    PathError,
    apply_patch,
    json_patch,
    load_mutator,
    parse,
    render,
)
from gatekeeper_tpu.mutation.path import ListNode, ObjectNode
from gatekeeper_tpu.target.matcher import constraint_matches


def assign(name, location, value, apply_to=None, match=None):
    spec = {
        "applyTo": apply_to if apply_to is not None else [
            {"groups": [""], "versions": ["v1"], "kinds": ["Pod"]}],
        "location": location,
        "parameters": {"assign": {"value": value}},
    }
    if match is not None:
        spec["match"] = match
    return {"apiVersion": "mutations.gatekeeper.sh/v1alpha1",
            "kind": "Assign", "metadata": {"name": name}, "spec": spec}


def assign_meta(name, location, value, match=None):
    spec = {"location": location,
            "parameters": {"assign": {"value": value}}}
    if match is not None:
        spec["match"] = match
    return {"apiVersion": "mutations.gatekeeper.sh/v1alpha1",
            "kind": "AssignMetadata", "metadata": {"name": name},
            "spec": spec}


def modify_set(name, location, values, operation="merge", match=None):
    spec = {
        "applyTo": [{"groups": [""], "versions": ["v1"], "kinds": ["Pod"]}],
        "location": location,
        "parameters": {"operation": operation,
                       "values": {"fromList": values}},
    }
    if match is not None:
        spec["match"] = match
    return {"apiVersion": "mutations.gatekeeper.sh/v1alpha1",
            "kind": "ModifySet", "metadata": {"name": name}, "spec": spec}


def pod_review(name="p", ns="default", labels=None, containers=None):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": ns},
           "spec": {"containers": containers if containers is not None
                    else [{"name": "main", "image": "x"}]}}
    if labels is not None:
        obj["metadata"]["labels"] = labels
    return {"kind": {"group": "", "version": "v1", "kind": "Pod"},
            "name": name, "namespace": ns, "operation": "CREATE",
            "object": obj}


# ---------------------------------------------------------------- parser


PATH_CASES = [
    "spec.replicas",
    "spec.containers[name: *].imagePullPolicy",
    "spec.containers[name: sidecar].resources.limits",
    'metadata.labels."corp.example/team"',
    'spec."weird.field"[key: "v.1"].x',
    "spec.template.spec.tolerations",
]


@pytest.mark.parametrize("path", PATH_CASES)
def test_path_round_trip(path):
    nodes = parse(path)
    assert parse(render(nodes)) == nodes
    # canonical form is a fixpoint
    assert render(parse(render(nodes))) == render(nodes)


def test_path_nodes_shape():
    nodes = parse("spec.containers[name: *].imagePullPolicy")
    assert nodes == [ObjectNode("spec"),
                     ListNode("containers", "name", None, glob=True),
                     ObjectNode("imagePullPolicy")]
    keyed = parse("spec.containers[name: sidecar]")
    assert keyed[-1] == ListNode("containers", "name", "sidecar")


def test_path_integer_list_keys():
    """Bare numeric key values are ints (real Pods carry int-typed
    containerPort); quoting forces a string. Both round-trip."""
    nodes = parse("spec.ports[containerPort: 8080].protocol")
    assert nodes[1] == ListNode("ports", "containerPort", 8080)
    assert parse(render(nodes)) == nodes
    quoted = parse('spec.ports[containerPort: "8080"].protocol')
    assert quoted[1] == ListNode("ports", "containerPort", "8080")
    assert parse(render(quoted)) == quoted
    assert nodes != quoted

    m = load_mutator(assign(
        "proto", "spec.ports[containerPort: 8080].protocol", "TCP"))
    obj = {"spec": {"ports": [{"containerPort": 8080}]}}
    assert m.apply(obj) is True
    # matched the existing int-keyed element; no duplicate appended
    assert obj["spec"]["ports"] == [{"containerPort": 8080,
                                     "protocol": "TCP"}]


def test_assign_rejects_glob_list_terminal():
    """A glob terminal would rewrite every element with one identical
    value (dropping the key field) — rejected at ingestion."""
    with pytest.raises(MutationError, match="glob"):
        load_mutator(assign("a", "spec.containers[name: *]",
                            {"image": "x"}))


@pytest.mark.parametrize("bad", [
    "", "   ", "spec.", ".spec", "spec..x", "spec.containers[name]",
    "spec.containers[name: ]", "spec.containers[name: *",
    "spec.x[*: y]", 'spec."unterminated', "spec.a b",
])
def test_path_rejects_malformed(bad):
    with pytest.raises(PathError):
        parse(bad)


# ----------------------------------------------------------------- apply


def test_assign_creates_intermediates_and_keyed_elements():
    m = load_mutator(assign("a", "spec.template.metadata.annotations.x",
                            "y"))
    obj = {"spec": {}}
    assert m.apply(obj) is True
    assert obj["spec"]["template"]["metadata"]["annotations"]["x"] == "y"
    assert m.apply(obj) is False  # second application: no change

    keyed = load_mutator(assign(
        "b", "spec.containers[name: sidecar].image", "img:v1"))
    obj = {"spec": {"containers": [{"name": "main", "image": "x"}]}}
    assert keyed.apply(obj) is True
    assert obj["spec"]["containers"][1] == {"name": "sidecar",
                                            "image": "img:v1"}


def test_assign_glob_never_creates():
    m = load_mutator(assign("a", "spec.containers[name: *].imagePullPolicy",
                            "Always"))
    obj = {"spec": {}}
    assert m.apply(obj) is False
    assert obj == {"spec": {}}  # no containers list conjured
    obj = {"spec": {"containers": [{"name": "a"}, {"name": "b"}]}}
    assert m.apply(obj) is True
    assert [c["imagePullPolicy"] for c in obj["spec"]["containers"]] == \
        ["Always", "Always"]


def test_assign_rejects_metadata_location():
    with pytest.raises(MutationError):
        load_mutator(assign("a", "metadata.labels.x", "y"))


def test_assign_metadata_only_sets_when_absent():
    m = load_mutator(assign_meta("a", "metadata.labels.team", "platform"))
    obj = {"metadata": {"labels": {"team": "existing"}}}
    assert m.apply(obj) is False
    assert obj["metadata"]["labels"]["team"] == "existing"
    obj = {"metadata": {}}
    assert m.apply(obj) is True
    assert obj["metadata"]["labels"]["team"] == "platform"


def test_assign_metadata_location_constrained():
    with pytest.raises(MutationError):
        load_mutator(assign_meta("a", "spec.labels.x", "y"))
    with pytest.raises(MutationError):
        load_mutator(assign_meta("a", "metadata.name", "y"))
    with pytest.raises(MutationError):
        load_mutator(assign_meta("a", "metadata.labels.x", {"not": "str"}))


def test_modify_set_merge_and_prune():
    merge = load_mutator(modify_set(
        "m", "spec.tolerations", [{"key": "gpu", "operator": "Exists"}]))
    obj = {"spec": {}}
    assert merge.apply(obj) is True
    assert obj["spec"]["tolerations"] == [{"key": "gpu",
                                           "operator": "Exists"}]
    assert merge.apply(obj) is False  # already present: set semantics

    prune = load_mutator(modify_set(
        "p", "spec.tolerations", [{"key": "gpu", "operator": "Exists"}],
        operation="prune"))
    assert prune.apply(obj) is True
    assert obj["spec"]["tolerations"] == []
    # prune of a missing list must not create it
    fresh = {"spec": {}}
    assert prune.apply(fresh) is False
    assert fresh == {"spec": {}}


# ------------------------------------------------------------- conflicts


def test_conflict_detector_quarantines_disagreeing_pair():
    system = MutationSystem()
    _, ch1 = system.upsert(assign(
        "as-list", "spec.containers[name: *].imagePullPolicy", "Always"))
    assert ch1 == set()
    assert system.conflicts() == {}
    # same prefix traversed as a plain object: terminal-type disagreement
    _, ch2 = system.upsert(assign("as-object", "spec.containers.image",
                                  "img"))
    conflicts = system.conflicts()
    assert set(conflicts) == {("Assign", "as-list"),
                              ("Assign", "as-object")}
    assert ch2 == set(conflicts)
    assert "spec.containers" in conflicts[("Assign", "as-list")]
    # quarantined mutators do not apply (None = nothing applied at all)
    assert system.mutate(pod_review()) is None
    # removal clears the quarantine for the survivor
    ch3 = system.remove(("Assign", "as-object"))
    assert system.conflicts() == {}
    assert ("Assign", "as-list") in ch3
    out = system.mutate(pod_review())
    assert out["spec"]["containers"][0]["imagePullPolicy"] == "Always"


def test_conflict_scoped_by_apply_to():
    """Disagreeing implied types only conflict when the mutators'
    applyTo scopes can select the same object (the reference's schema
    DB binds per GVK): a Pod list-mutator and a CRD object-mutator on
    the same path prefix coexist."""
    system = MutationSystem()
    system.upsert(assign(
        "pod-list", "spec.containers[name: *].imagePullPolicy", "Always",
        apply_to=[{"groups": [""], "versions": ["v1"], "kinds": ["Pod"]}]))
    system.upsert(assign(
        "crd-object", "spec.containers.image", "img",
        apply_to=[{"groups": ["widgets.example"], "versions": ["v1"],
                   "kinds": ["Widget"]}]))
    assert system.conflicts() == {}
    # a wildcard scope overlaps everything and re-introduces the clash
    system.upsert(assign(
        "star-object", "spec.containers.image", "img",
        apply_to=[{"groups": ["*"], "versions": ["*"], "kinds": ["*"]}]))
    assert {("Assign", "pod-list"), ("Assign", "star-object")} <= \
        set(system.conflicts())


def test_conflict_reason_refreshes_when_third_mutator_joins():
    """A mutator joining an EXISTING conflict must flip the original
    pair into the changed set (their reason text now cites it), and its
    later removal must flip them again."""
    system = MutationSystem()
    system.upsert(assign("a-list", "spec.containers[name: *].x", "v"))
    system.upsert(assign("b-object", "spec.containers.y", "v"))
    _, ch = system.upsert(assign("c-object", "spec.containers.z", "v"))
    # a-list's opponents grew (its reason now cites c-object); b-object's
    # reason is unchanged, so only the affected pair is in the set
    assert {("Assign", "a-list"), ("Assign", "c-object")} <= ch
    assert "c-object" in system.conflicts()[("Assign", "a-list")]
    ch2 = system.remove(("Assign", "c-object"))
    assert {("Assign", "a-list"), ("Assign", "c-object")} <= ch2
    assert "c-object" not in system.conflicts()[("Assign", "a-list")]


def test_modifyset_terminal_implies_list_conflict():
    system = MutationSystem()
    system.upsert(modify_set("ms", "spec.tolerations", [{"key": "a"}]))
    system.upsert(assign("as", "spec.tolerations.effect", "NoSchedule"))
    assert set(system.conflicts()) == {("ModifySet", "ms"),
                                       ("Assign", "as")}


# ----------------------------------------------------- convergence + patch


def test_convergence_cap_errors_on_ping_pong_pair():
    system = MutationSystem(max_iterations=5)
    system.upsert(assign("ping", "spec.priorityClassName", "low"))
    system.upsert(assign("pong", "spec.priorityClassName", "high"))
    with pytest.raises(MutationError, match="did not converge"):
        system.mutate(pod_review())
    # the batched entry carries the error instead of raising
    outs = system.mutate_batch([pod_review()])
    assert isinstance(outs[0], MutationError)


def test_second_pass_idempotence_yields_empty_patch():
    system = MutationSystem()
    system.upsert(assign(
        "pull", "spec.containers[name: *].imagePullPolicy", "Always"))
    system.upsert(assign_meta("team", "metadata.labels.team", "plat"))
    system.upsert(modify_set("tol", "spec.tolerations", [{"key": "gpu"}]))
    review = pod_review()
    mutated = system.mutate(review)
    patch = json_patch(review["object"], mutated)
    assert patch  # first pass did mutate
    # a second trip through the webhook sees the already-mutated object
    second = dict(review, object=mutated)
    remutated = system.mutate(second)
    assert json_patch(mutated, remutated) == []


def test_json_patch_round_trip_and_escaping():
    before = {"metadata": {"labels": {"a/b": "x", "t~e": "y"}},
              "spec": {"items": [1, 2, 3], "drop": True}}
    after = {"metadata": {"labels": {"a/b": "z", "new": "n"}},
             "spec": {"items": [1, 9], "add": {"k": "v"}}}
    ops = json_patch(before, after)
    assert apply_patch(before, ops) == after
    paths = [op["path"] for op in ops]
    assert "/metadata/labels/a~1b" in paths  # RFC-6901 '/' escape
    assert any(p.startswith("/metadata/labels/t~0e") for p in paths)
    assert json_patch(after, after) == []


# --------------------------------------------- batched applicability (diff)


def _random_match(rng):
    match = {}
    if rng.random() < 0.5:
        match["kinds"] = [{
            "apiGroups": rng.choice(([""], ["*"], ["apps"])),
            "kinds": rng.choice((["Pod"], ["*"], ["Deployment"],
                                 ["Pod", "Service"])),
        }]
    if rng.random() < 0.35:
        match["namespaces"] = rng.sample(
            ["prod", "dev", "staging", "default"], rng.randrange(1, 3))
    if rng.random() < 0.25:
        match["excludedNamespaces"] = [rng.choice(["prod", "dev"])]
    if rng.random() < 0.4:
        match["labelSelector"] = rng.choice((
            {"matchLabels": {"app": "web"}},
            {"matchExpressions": [{"key": "tier", "operator": "Exists"}]},
            {"matchExpressions": [{"key": "app", "operator": "In",
                                   "values": ["web", "api"]}]},
        ))
    if rng.random() < 0.3:
        match["namespaceSelector"] = {"matchLabels": {"env": "prod"}}
    return match


def _random_review(rng, i):
    kind = rng.choice((("", "v1", "Pod"), ("", "v1", "Service"),
                       ("apps", "v1", "Deployment"),
                       ("", "v1", "Namespace")))
    labels = rng.choice((None, {"app": "web"}, {"app": "api", "tier": "be"},
                         {"tier": "fe"}))
    obj = {"apiVersion": "v1", "kind": kind[2],
           "metadata": {"name": f"o{i}"}}
    if labels is not None:
        obj["metadata"]["labels"] = labels
    review = {"kind": {"group": kind[0], "version": kind[1],
                       "kind": kind[2]},
              "name": f"o{i}", "object": obj}
    if kind[2] != "Namespace" and rng.random() < 0.8:
        ns = rng.choice(["prod", "dev", "staging", "default", "unknown"])
        review["namespace"] = ns
        obj["metadata"]["namespace"] = ns
    return review


def test_batched_applicability_matches_per_object_predicate():
    """The micro-batch mask must agree with per-object
    constraint_matches AND the applyTo gate on every (review, mutator)
    pair — ≥200 randomized reviews x a mixed mutator library."""
    rng = random.Random(42)
    ns_cache = {
        "prod": {"apiVersion": "v1", "kind": "Namespace",
                 "metadata": {"name": "prod", "labels": {"env": "prod"}}},
        "dev": {"apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": "dev", "labels": {"env": "dev"}}},
        "default": {"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "default", "labels": {}}},
    }
    lookup = ns_cache.get
    system = MutationSystem()
    mutators = []
    for i in range(24):
        shape = i % 3
        match = _random_match(rng)
        if shape == 0:
            cr = assign(f"a{i}", "spec.one", "v", match=match,
                        apply_to=[{
                            "groups": rng.choice(([""], ["*"], ["apps"])),
                            "versions": ["*"],
                            "kinds": rng.choice((["Pod"], ["*"],
                                                 ["Deployment"]))}])
        elif shape == 1:
            cr = assign_meta(f"m{i}", f"metadata.labels.x{i}", "v",
                             match=match)
        else:
            cr = modify_set(f"s{i}", "spec.two", ["v"], match=match)
        mut, _ = system.upsert(cr)
        mutators.append(mut)
    reviews = [_random_review(rng, i) for i in range(240)]
    mask = system.match_mask(mutators, reviews, lookup)
    assert mask.shape == (240, 24)
    for r, review in enumerate(reviews):
        kind = review["kind"]
        for c, mut in enumerate(mutators):
            want = constraint_matches({"spec": {"match": mut.match}},
                                      review, lookup) and \
                mut.applies_to_gvk(kind["group"], kind["version"],
                                   kind["kind"])
            assert mask[r, c] == want, (
                f"disagreement at review {r} ({kind}), mutator "
                f"{mut.id}: batched={mask[r, c]} per-object={want}")


# -------------------------------------------------------- webhook handler


def test_mutation_handler_patches_and_envelope():
    system = MutationSystem()
    system.upsert(assign(
        "pull", "spec.containers[name: *].imagePullPolicy", "Always"))
    handler = MutationHandler(system)
    try:
        review = pod_review()
        out = handler.handle({
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": dict(review, uid="u-1",
                            userInfo={"username": "alice"})})
        # envelope fidelity (required by admission.k8s.io/v1)
        assert out["apiVersion"] == "admission.k8s.io/v1"
        assert out["kind"] == "AdmissionReview"
        resp = out["response"]
        assert resp["uid"] == "u-1"
        assert resp["allowed"] is True
        assert resp["patchType"] == "JSONPatch"
        ops = json.loads(base64.b64decode(resp["patch"]))
        patched = apply_patch(review["object"], ops)
        assert patched["spec"]["containers"][0]["imagePullPolicy"] == \
            "Always"
        # idempotence over the wire: mutated object → no patch key
        again = handler.handle({
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": dict(review, object=patched, uid="u-2",
                            userInfo={"username": "alice"})})
        assert "patch" not in again["response"]
        assert again["response"]["allowed"] is True
    finally:
        handler.batcher.stop()


def test_mutation_handler_failure_policy():
    system = MutationSystem(max_iterations=2)
    system.upsert(assign("ping", "spec.x", "a"))
    system.upsert(assign("pong", "spec.x", "b"))
    review = {"apiVersion": "admission.k8s.io/v1",
              "kind": "AdmissionReview",
              "request": dict(pod_review(), uid="u",
                              userInfo={"username": "alice"})}
    open_h = MutationHandler(system)
    closed_h = MutationHandler(system, fail_closed=True)
    try:
        allowed = open_h.handle(copy.deepcopy(review))["response"]
        denied = closed_h.handle(copy.deepcopy(review))["response"]
    finally:
        open_h.batcher.stop()
        closed_h.batcher.stop()
    assert allowed["allowed"] is True  # fail-open default
    assert allowed["status"]["code"] == 500
    assert denied["allowed"] is False  # --fail-closed
    assert denied["status"]["code"] == 500
    rendered = REGISTRY.render()
    assert 'mutation_request_count{admission_status="error"}' in rendered


def test_mutation_handler_skips_gatekeeper_resources_and_deletes():
    system = MutationSystem()
    system.upsert(assign_meta("lbl", "metadata.labels.x", "y"))
    handler = MutationHandler(system)
    try:
        delete = handler.handle({"request": {
            "uid": "d", "kind": {"group": "", "version": "v1",
                                 "kind": "Pod"},
            "operation": "DELETE", "object": None,
            "userInfo": {"username": "alice"}}})
        assert "patch" not in delete["response"]
        own = handler.handle({"request": {
            "uid": "o",
            "kind": {"group": "mutations.gatekeeper.sh",
                     "version": "v1alpha1", "kind": "Assign"},
            "object": assign("x", "spec.a", "b"),
            "userInfo": {"username": "alice"}}})
        assert "patch" not in own["response"]
    finally:
        handler.batcher.stop()


# -------------------------------------------------- micro-batcher timeout


def test_microbatcher_timeout_drops_queued_entry():
    """Satellite regression: a submit() whose deadline expires before
    its batch can flush raises TimeoutError, removes any still-queued
    entry, and counts into admission_batch_timeouts — and the batcher
    keeps serving afterward. (Deadline-aware sealing flushes tight
    deadlines immediately, so the expiry is forced by saturating the
    flusher with a hung batch.)"""
    import threading

    release = threading.Event()
    flushed: list = []

    def evaluate(reviews):
        if any("hang" in r for r in reviews):
            release.wait(10)
        flushed.extend(reviews)
        return [[] for _ in reviews]

    b = MicroBatcher(None, max_wait=0.001, max_batch=1, evaluate=evaluate)
    try:
        hang = threading.Thread(
            target=lambda: b.submit({"hang": 1}, timeout=10.0),
            daemon=True)
        hang.start()
        deadline = time.time() + 5
        while time.time() < deadline:  # hung batch occupies the flusher
            with b._scv:
                if b._flushing:
                    break
            time.sleep(0.005)
        before = b.timeouts
        with pytest.raises(TimeoutError):
            b.submit({"probe": 1}, timeout=0.05)
        assert b.timeouts == before + 1
        with b._cv:
            assert b._queue == []  # the timed-out entry is gone
        assert 'admission_batch_timeouts' in REGISTRY.render()
        release.set()
        hang.join(5)
        # the batcher still serves later requests; the abandoned
        # review's late flush (if it sealed) is harmless
        assert b.submit({"probe": 2}, timeout=5.0) == []
    finally:
        release.set()
        b.stop()


# -------------------------------------------------- controller lifecycle


@pytest.fixture
def mutation_runtime():
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--operation", "webhook", "--operation", "mutation-webhook",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    yield rt
    rt.stop()


def test_mutator_controller_lifecycle(mutation_runtime):
    rt = mutation_runtime
    kube = rt.kube
    gvk = ("mutations.gatekeeper.sh", "v1alpha1", "Assign")
    kube.create(assign("pull", "spec.containers[name: *].imagePullPolicy",
                       "Always"))
    rt.manager.drain()
    assert rt.mutation_system.counts()["Assign"] == 1
    status = kube.get(gvk, "pull").get("status") or {}
    assert status["byPod"][0]["enforced"] is True

    # conflicting mutator quarantines BOTH, including the pre-existing one
    kube.create(assign("clash", "spec.containers.image", "img"))
    rt.manager.drain()
    assert set(rt.mutation_system.conflicts()) == {
        ("Assign", "pull"), ("Assign", "clash")}
    for name in ("pull", "clash"):
        st = kube.get(gvk, name).get("status") or {}
        assert st["byPod"][0]["enforced"] is False
        assert "schema conflict" in st["byPod"][0]["errors"][0]["message"]

    # deletion clears the quarantine and refreshes the survivor's status
    kube.delete(gvk, "clash")
    rt.manager.drain()
    assert rt.mutation_system.conflicts() == {}
    deadline = time.time() + 5
    while time.time() < deadline:
        st = kube.get(gvk, "pull").get("status") or {}
        if st["byPod"][0]["enforced"]:
            break
        time.sleep(0.02)
    assert st["byPod"][0]["enforced"] is True

    # invalid mutator: ingestion error surfaces in status
    kube.create(assign("bad", "metadata.labels.x", "y"))
    rt.manager.drain()
    st = kube.get(gvk, "bad").get("status") or {}
    assert st["byPod"][0]["enforced"] is False
    assert rt.mutation_system.get(("Assign", "bad")) is None


def test_mutation_only_operation_does_not_serve_validation():
    """--operation mutation-webhook alone: /v1/admit and /v1/admitlabel
    404 (a leftover VWC must not get decisions from an operation the
    operator turned off); /v1/mutate serves."""
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--operation", "mutation-webhook"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        assert rt.webhook.validation is None
        assert rt.webhook.ns_label is None
        body = json.dumps({"apiVersion": "admission.k8s.io/v1",
                           "kind": "AdmissionReview",
                           "request": dict(pod_review(), uid="u",
                                           userInfo={"username": "a"})})
        for path, want in (("/v1/admit", 404), ("/v1/admitlabel", 404),
                           ("/v1/mutate", 200)):
            conn = http.client.HTTPConnection("127.0.0.1",
                                              rt.webhook.port, timeout=10)
            conn.request("POST", path, body,
                         {"Content-Type": "application/json"})
            assert conn.getresponse().status == want, path
    finally:
        rt.stop()


def test_mutate_webhook_over_http(mutation_runtime):
    rt = mutation_runtime
    rt.kube.create(assign_meta("team", "metadata.labels.team", "plat"))
    rt.manager.drain()
    review = {"apiVersion": "admission.k8s.io/v1",
              "kind": "AdmissionReview",
              "request": dict(pod_review(), uid="uid-7",
                              userInfo={"username": "alice"})}
    conn = http.client.HTTPConnection("127.0.0.1", rt.webhook.port,
                                      timeout=10)
    conn.request("POST", "/v1/mutate", json.dumps(review),
                 {"Content-Type": "application/json"})
    out = json.loads(conn.getresponse().read())
    assert out["apiVersion"] == "admission.k8s.io/v1"
    assert out["kind"] == "AdmissionReview"
    resp = out["response"]
    assert resp["uid"] == "uid-7"
    ops = json.loads(base64.b64decode(resp["patch"]))
    assert {"op": "add", "path": "/metadata/labels",
            "value": {"team": "plat"}} in ops
