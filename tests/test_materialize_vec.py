"""Vectorized violation materialization (ISSUE 11 tentpole).

Contract: for every kind with a message plan (ir/vecmat.py), the
vectorized numpy message assembly is BIT-EQUAL to the exact per-pair
evaluator — messages, details, enforcement, and order — across the
shipped general + pod-security-policy libraries and adversarial
witness shapes (multi-arg sprintf, unicode, >512-char strings that
veto the fixed-width window). Witnesses outside the plan's subset
must veto their pair back to the exact path, never render wrong.

The pre-materialization cap: with audit_violations_cap armed, each
constraint's first `cap` pairs materialize fully and the rest become
count-only results — totals intact, published entries unaffected.
"""

from __future__ import annotations

import numpy as np
import pytest

import bench_configs as bc
from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.target.batch import match_masks

TARGET = "admission.k8s.gatekeeper.sh"


def mk_client(drv=None):
    drv = drv or TpuDriver()
    return drv, Backend(drv).new_client([K8sValidationTarget()])


def _materialize_both(drv, kind, cons, reviews, with_cand=True):
    """Device firing pairs for one kind, materialized twice: through
    the vectorized plan and with the plan disabled (exact evaluator).
    Returns (vec_results, exact_results, n_pairs, plan_active)."""
    lookup_ns = drv._namespace_lookup(TARGET)
    inventory = drv._inventory_tree(TARGET)
    ct = drv.compiled_for(kind)
    assert ct is not None, f"{kind} must device-compile for this test"
    mask = match_masks(cons, reviews, lookup_ns)
    cand = np.flatnonzero(mask.any(axis=1))
    cand_reviews = [reviews[int(i)] for i in cand]
    rows, cols = drv.eval_compiled_pairs(
        ct, kind, cand_reviews, cons,
        feat_key=(drv._data_gen, hash(cand.tobytes())), cand=cand,
        target=TARGET)
    keep = mask[cand[rows], cols]
    rows, cols = rows[keep], cols[keep]
    kw = {"cand": cand} if with_cand else {}
    plan_active = drv._vec_msgs(TARGET, kind, cons, cand_reviews, rows,
                                cols, cand if with_cand else None) \
        is not None
    r_vec = drv.materialize_pairs(TARGET, cons, cand_reviews, rows, cols,
                                  inventory, **kw)
    orig = drv._vec_msgs
    drv._vec_msgs = lambda *a, **k: None
    try:
        r_exact = drv.materialize_pairs(TARGET, cons, cand_reviews, rows,
                                        cols, inventory, **kw)
    finally:
        drv._vec_msgs = orig
    return r_vec, r_exact, len(rows), plan_active


def _key(r):
    return (r.constraint["kind"], r.constraint["metadata"]["name"],
            r.msg, r.metadata, r.enforcement_action,
            id(r.review))


def _load_library(prefix, constraints, objects):
    drv, client = mk_client()
    for name in policies.names():
        if name.startswith(prefix):
            client.add_template(policies.load(name))
    for kind, cname, params in constraints:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": cname},
            "spec": ({"parameters": params} if params else {}),
        })
    for o in objects:
        client.add_data(o)
    return drv, client


# ------------------------------------------------- library differential


def test_psp_library_bit_equal():
    """Every PSP kind's device pairs materialize bit-equal messages on
    the vectorized and exact paths; the dominant kinds actually take
    the vectorized path."""
    drv, client = _load_library("pod-security-policy/",
                                bc.PSP_CONSTRAINTS,
                                bc.synth_pods_psp(1500))
    reviews = drv._inventory_reviews(TARGET)
    cons_all = drv._constraints(TARGET)
    vec_kinds = set()
    total = 0
    for kind in sorted({c.get("kind") for c in cons_all}):
        cons = [c for c in cons_all if c.get("kind") == kind]
        if drv.compiled_for(kind) is None:
            continue
        r_vec, r_exact, n, active = _materialize_both(drv, kind, cons,
                                                      reviews)
        assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact], \
            f"{kind}: vectorized messages diverge from the evaluator"
        if active:
            vec_kinds.add(kind)
        total += n
    assert total > 0
    # the kinds that dominate the BENCH_r05 materialization tail must
    # be on the vectorized path, or the tentpole regressed
    assert {"K8sPSPSELinux", "K8sPSPForbiddenSysctls"} <= vec_kinds


def test_general_library_bit_equal():
    drv, client = _load_library("general/", bc.GENERAL_CONSTRAINTS,
                                bc.synth_mixed_objects(1200))
    reviews = drv._inventory_reviews(TARGET)
    cons_all = drv._constraints(TARGET)
    for kind in sorted({c.get("kind") for c in cons_all}):
        cons = [c for c in cons_all if c.get("kind") == kind]
        if drv.compiled_for(kind) is None:
            continue
        r_vec, r_exact, _n, _a = _materialize_both(drv, kind, cons,
                                                   reviews)
        assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact], \
            f"{kind}: vectorized messages diverge from the evaluator"


def test_plan_gating_per_axis_witnesses_stay_exact():
    """Kinds whose messages carry per-axis witnesses (container names)
    or non-const details must have NO plan — the device verdict cannot
    attribute which element fired."""
    drv, client = _load_library("pod-security-policy/",
                                bc.PSP_CONSTRAINTS,
                                bc.synth_pods_psp(50))
    for name in policies.names():
        if name.startswith("general/"):
            client.add_template(policies.load(name))
    assert drv._msg_plan("K8sPSPSELinux") is not None
    assert drv._msg_plan("K8sPSPForbiddenSysctls") is not None
    assert drv._msg_plan("K8sPSPHostNamespace") is not None
    assert drv._msg_plan("K8sHttpsOnly") is not None
    # c.name is a per-axis witness; %v of securityContext is composite
    assert drv._msg_plan("K8sPSPAllowPrivilegeEscalationContainer") is None
    assert drv._msg_plan("K8sPSPPrivilegedContainer") is None
    assert drv._msg_plan("K8sPSPCapabilities") is None
    # details carry a witness -> exact path
    assert drv._msg_plan("K8sRequiredLabels") is None


# --------------------------------------------- adversarial witnesses


VECDIFF_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "vecdiff"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "VecDiff"}}},
        "targets": [{"target": "admission.k8s.gatekeeper.sh", "rego": """
package vecdiff

violation[{"msg": msg, "details": {}}] {
  input.review.object.metadata.labels["flag"] == "bad"
  msg := sprintf("object <%v> in namespace <%v> flagged (note: %v, max: %v)", [input.review.object.metadata.name, input.review.object.metadata.namespace, input.parameters.note, input.parameters.max])
}
"""}],
    },
}


def _vecdiff_client(pods):
    drv, client = mk_client()
    client.add_template(VECDIFF_TEMPLATE)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "VecDiff", "metadata": {"name": "vd"},
        "spec": {"parameters": {"note": "uñícødé «note»",
                                "max": 3}},
    })
    for p in pods:
        client.add_data(p)
    return drv, client


def _pod(name, ns="d", flag="bad"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": {"flag": flag}}}


def test_multiarg_sprintf_unicode_and_oversize_witnesses():
    """Multi-arg sprintf with unicode witnesses and a >512-char name
    (vetoes the fixed-width window -> exact path) stay bit-equal."""
    from gatekeeper_tpu.ir.vecmat import MAX_WITNESS_STRLEN

    long_name = "pé-" + "x" * (MAX_WITNESS_STRLEN + 10)
    pods = [
        _pod("pød-世界"),          # unicode witness
        _pod(long_name),                          # oversize: veto
        _pod("plain"),
        _pod("skipped", flag="ok"),               # no violation
    ]
    drv, client = _vecdiff_client(pods)
    assert drv._msg_plan("VecDiff") is not None
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    r_vec, r_exact, n, active = _materialize_both(drv, "VecDiff", cons,
                                                  reviews)
    assert active and n == 3
    assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact]
    msgs = sorted(r.msg for r in r_vec)
    assert any("uñícødé «note»" in m for m in msgs)
    assert any(long_name in m for m in msgs)
    assert all("max: 3)" in m for m in msgs)


def test_absent_and_nonstring_witnesses_veto_to_exact():
    """A pair whose witness is absent or non-string must fall back to
    the exact evaluator (which emits nothing for an undefined msg
    binding) — never render a wrong message."""
    pods = [
        _pod("named"),
        {"apiVersion": "v1", "kind": "Pod",       # no namespace witness
         "metadata": {"name": "no-ns", "labels": {"flag": "bad"}}},
    ]
    drv, client = _vecdiff_client(pods)
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    r_vec, r_exact, _n, active = _materialize_both(drv, "VecDiff", cons,
                                                   reviews)
    assert active
    assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact]
    # the cluster-scoped pod has no namespace: the msg binding fails,
    # so only the namespaced pod produces a violation on BOTH paths
    assert sorted(r.review["name"] for r in r_vec) == ["named"]


def test_undefined_param_witness_skips_constraint():
    """A constraint whose parameters lack the msg witness path emits no
    violations (the msg binding is undefined) — the vectorized path
    must skip those columns exactly like the evaluator."""
    drv, client = mk_client()
    client.add_template(VECDIFF_TEMPLATE)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "VecDiff", "metadata": {"name": "no-note"},
        "spec": {"parameters": {"max": 1}},  # no "note": msg undefined
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "VecDiff", "metadata": {"name": "with-note"},
        "spec": {"parameters": {"note": "n", "max": 1}},
    })
    for p in [_pod("a"), _pod("b")]:
        client.add_data(p)
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    r_vec, r_exact, _n, active = _materialize_both(drv, "VecDiff", cons,
                                                   reviews)
    assert active
    assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact]
    assert {r.constraint["metadata"]["name"] for r in r_vec} == \
        {"with-note"}


# ----------------------------------------------------------- capping


def test_cap_before_materialization():
    """With audit_violations_cap armed (as the audit manager arms it),
    each constraint's first `cap` pairs materialize full messages and
    the rest are count-only — totals and publishable entries identical
    to the uncapped sweep."""
    pods = [_pod(f"p-{i:03d}") for i in range(30)]
    drv, client = _vecdiff_client(pods)
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    lookup_ns = drv._namespace_lookup(TARGET)
    inventory = drv._inventory_tree(TARGET)
    ct = drv.compiled_for("VecDiff")
    mask = match_masks(cons, reviews, lookup_ns)
    cand = np.flatnonzero(mask.any(axis=1))
    cand_reviews = [reviews[int(i)] for i in cand]
    rows, cols = drv.eval_compiled_pairs(
        ct, "VecDiff", cand_reviews, cons,
        feat_key=(drv._data_gen, hash(cand.tobytes())), cand=cand,
        target=TARGET)
    keep = mask[cand[rows], cols]
    rows, cols = rows[keep], cols[keep]

    uncapped = drv.materialize_pairs(TARGET, cons, cand_reviews, rows,
                                     cols, inventory, cand=cand)
    drv.audit_violations_cap = 5
    drv._in_audit_sweep = True
    try:
        capped = drv.materialize_pairs(TARGET, cons, cand_reviews, rows,
                                       cols, inventory, cand=cand)
    finally:
        drv._in_audit_sweep = False
        drv.audit_violations_cap = None
    assert len(capped) == len(uncapped) == 30  # totals intact
    # the first 5 per constraint are fully materialized, byte-equal to
    # the uncapped sweep; the rest are count-only
    assert [r.msg for r in capped[:5]] == [r.msg for r in uncapped[:5]]
    assert all(r.msg == "" for r in capped[5:])
    assert all(r.enforcement_action == uncapped[i].enforcement_action
               for i, r in enumerate(capped))


def test_cap_ignored_outside_audit_sweep():
    """Previews and direct materialization stay uncapped even when the
    manager armed the cap on the shared driver."""
    pods = [_pod(f"q-{i}") for i in range(8)]
    drv, client = _vecdiff_client(pods)
    drv.audit_violations_cap = 2  # armed, but no sweep flag
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    r_vec, r_exact, _n, _a = _materialize_both(drv, "VecDiff", cons,
                                               reviews)
    assert all(r.msg for r in r_vec)
    assert [_key(r) for r in r_vec] == [_key(r) for r in r_exact]


def test_manager_sweep_caps_direct_audit_stays_uncapped():
    """End to end: a manager-driven sweep caps materialization at its
    status limit, while a direct client.audit() on the SAME driver
    right after stays uncapped — including not being served capped
    messages from the results delta cache."""
    from gatekeeper_tpu.control.audit import AuditManager
    from gatekeeper_tpu.control.kube import FakeKube

    pods = [_pod(f"m-{i:02d}") for i in range(12)]
    drv, client = _vecdiff_client(pods)
    # force the device sweep path at this tiny scale so the
    # materialize_pairs pipeline (where the cap lives) actually runs
    drv._dev_batch_lat_s = 1e-6
    drv._host_pair_rate = 1.0
    kube = FakeKube()
    mgr = AuditManager(kube, client, audit_from_cache=True,
                       constraint_violations_limit=4,
                       gc_stale_statuses=False,
                       stream_status_writes=False)
    res = mgr.audit_once()
    assert len(res) == 12  # totals are never capped
    assert sum(1 for r in res if r.msg) == 4
    assert all(r.msg == "" for r in res[4:])
    # direct caller on the shared driver: full messages, even though
    # the delta cache was just populated by the capped sweep
    direct = client.audit().results()
    assert len(direct) == 12
    assert all(r.msg for r in direct)


# ------------------------------------------------- witness cache reuse


def test_witness_columns_cached_and_invalidated():
    """Witness columns over the stable review list are reused across
    sweeps and rebuilt after an inventory write."""
    pods = [_pod(f"w-{i}") for i in range(6)]
    drv, client = _vecdiff_client(pods)
    reviews = drv._inventory_reviews(TARGET)
    cons = drv._constraints(TARGET)
    r1, _e1, _n, active = _materialize_both(drv, "VecDiff", cons, reviews)
    assert active
    keys = [k for k in drv._witcols if k[0] == TARGET]
    assert keys
    ent_before = drv._witcols[keys[0]]
    r2, _e2, _n2, _a2 = _materialize_both(drv, "VecDiff", cons, reviews)
    assert drv._witcols[keys[0]] is ent_before  # cache hit
    # rename a pod: the column must rebuild and messages must follow
    client.add_data(_pod("w-renamed"))
    reviews = drv._inventory_reviews(TARGET)
    r3, e3, _n3, _a3 = _materialize_both(drv, "VecDiff", cons, reviews)
    assert [_key(r) for r in r3] == [_key(r) for r in e3]
    assert any("w-renamed" in r.msg for r in r3)
