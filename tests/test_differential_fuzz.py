"""Differential fuzzing: randomized objects through BOTH drivers across
the full policy library.

The corpus tests replay the reference's hand-written cases; this suite
generates structured-random Kubernetes objects (valid shapes, adversarial
field values: missing/empty/wrong-typed/unicode/huge) and asserts the
TpuDriver's audit and admission results are byte-identical to the
interpreter driver's for EVERY general + pod-security-policy constraint
at once. Seeded, so failures replay deterministically.
"""

import random

import pytest

from gatekeeper_tpu import policies
from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.target import AugmentedUnstructured, K8sValidationTarget

CONSTRAINTS = [
    ("K8sAllowedRepos", {"repos": ["good.example/", "ok.example/"]}),
    ("K8sContainerLimits", {"cpu": "500m", "memory": "1Gi"}),
    ("K8sContainerRatios", {"ratio": "2"}),
    ("K8sHttpsOnly", None),
    ("K8sRequiredLabels", {"labels": [
        {"key": "owner", "allowedRegex": "^[a-z]+$"}, {"key": "team"}]}),
    ("K8sUniqueIngressHost", None),
    ("K8sUniqueServiceSelector", None),
    ("K8sPSPAllowPrivilegeEscalationContainer", None),
    ("K8sPSPAppArmor", {"allowedProfiles": ["runtime/default"]}),
    ("K8sPSPCapabilities", {"allowedCapabilities": ["NET_BIND_SERVICE"],
                            "requiredDropCapabilities": ["ALL"]}),
    ("K8sPSPForbiddenSysctls", {"forbiddenSysctls": ["kernel.*"]}),
    ("K8sPSPHostFilesystem", {"allowedHostPaths": [
        {"pathPrefix": "/var/log", "readOnly": True}]}),
    ("K8sPSPHostNamespace", None),
    ("K8sPSPHostNetworkingPorts", {"hostNetwork": False,
                                   "min": 8000, "max": 9000}),
    ("K8sPSPPrivilegedContainer", None),
    ("K8sPSPReadOnlyRootFilesystem", None),
    ("K8sPSPSeccomp", {"allowedProfiles": ["runtime/default"]}),
    ("K8sPSPAllowedUsers", {"runAsUser": {"rule": "MustRunAsNonRoot"}}),
    ("K8sPSPVolumeTypes", {"volumes": ["configMap", "secret"]}),
]

_STRS = ["", "a", "owner", "good.example/app:v1", "bad.example/app",
         "runtime/default", "unconfined", "Ü-nicode-✓", "x" * 300,
         "NET_BIND_SERVICE", "SYS_ADMIN", "kernel.msgmax", "net.core.x",
         "/var/log/app", "/etc/shadow", "500m", "2Gi", "4", "0", "-1",
         "host.example", "ALL"]


def _rand_value(rng: random.Random, depth: int = 0):
    roll = rng.random()
    if depth > 2 or roll < 0.45:
        return rng.choice(_STRS + [0, 1, 1000, True, False, None,
                                   0.5, 4096])
    if roll < 0.65:
        return [_rand_value(rng, depth + 1)
                for _ in range(rng.randrange(3))]
    return {rng.choice(_STRS[:8] or ["k"]) or "k":
            _rand_value(rng, depth + 1) for _ in range(rng.randrange(3))}


def _container(rng: random.Random) -> dict:
    c = {"name": rng.choice(["main", "side", "opa"]),
         "image": rng.choice(_STRS[3:6] + ["good.example/x:1"])}
    if rng.random() < 0.7:
        c["resources"] = {k: {"cpu": rng.choice(["100m", "1", "abc", 2]),
                              "memory": rng.choice(["1Gi", "10Mi", ""])}
                          for k in rng.sample(["limits", "requests"],
                                              rng.randrange(1, 3))}
    if rng.random() < 0.7:
        sc = {}
        for key, vals in (("privileged", [True, False, "yes"]),
                          ("allowPrivilegeEscalation", [True, False]),
                          ("readOnlyRootFilesystem", [True, False, None]),
                          ("runAsUser", [0, 1000, -5, "root"])):
            if rng.random() < 0.5:
                sc[key] = rng.choice(vals)
        if rng.random() < 0.5:
            sc["capabilities"] = {
                k: rng.sample(["ALL", "SYS_ADMIN", "NET_BIND_SERVICE"],
                              rng.randrange(3))
                for k in rng.sample(["add", "drop"], rng.randrange(1, 3))}
        c["securityContext"] = sc
    if rng.random() < 0.3:
        c["ports"] = [{"hostPort": rng.choice([80, 8080, 8500, 9999])}]
    if rng.random() < 0.15:
        c[rng.choice(_STRS[:8]) or "extra"] = _rand_value(rng)
    return c


def _rand_object(rng: random.Random, i: int) -> dict:
    kind = rng.choice(["Pod", "Namespace", "Service", "Ingress"])
    meta = {"name": f"obj-{i}"}
    if kind != "Namespace":
        meta["namespace"] = rng.choice(["default", "prod", "kube-system"])
    if rng.random() < 0.8:
        meta["labels"] = {k: rng.choice(_STRS)
                          for k in rng.sample(["owner", "team", "app",
                                               "env"], rng.randrange(4))}
    if rng.random() < 0.5:
        meta["annotations"] = {
            rng.choice([
                "container.apparmor.security.beta.kubernetes.io/main",
                "seccomp.security.alpha.kubernetes.io/pod",
                "kubernetes.io/ingress.allow-http", "x"]):
            rng.choice(["runtime/default", "unconfined", "false", "true"])}
    obj = {"apiVersion": {"Pod": "v1", "Namespace": "v1", "Service": "v1",
                          "Ingress": "networking.k8s.io/v1"}[kind],
           "kind": kind, "metadata": meta}
    if kind == "Pod":
        spec = {"containers": [_container(rng)
                               for _ in range(rng.randrange(1, 3))]}
        if rng.random() < 0.4:
            spec["securityContext"] = {
                "sysctls": [{"name": rng.choice(["kernel.msgmax",
                                                 "net.core.x"]),
                             "value": "1"}]}
        if rng.random() < 0.3:
            spec["hostNetwork"] = rng.choice([True, False])
        if rng.random() < 0.3:
            spec["volumes"] = [
                {"name": "v",
                 **rng.choice([{"configMap": {"name": "c"}},
                               {"hostPath": {"path": "/var/log/x"}},
                               {"hostPath": {"path": "/etc"}},
                               {"emptyDir": {}}])}]
        obj["spec"] = spec
    elif kind == "Service":
        obj["spec"] = {"selector": {k: rng.choice(_STRS[:6])
                                    for k in rng.sample(["app", "tier"],
                                                        rng.randrange(3))},
                       "ports": [{"port": 80}]}
    elif kind == "Ingress":
        obj["spec"] = {"rules": [{"host": rng.choice(
            ["a.example", "b.example", "a.example"])}
            for _ in range(rng.randrange(1, 3))]}
        if rng.random() < 0.4:
            obj["spec"]["tls"] = [{"hosts": ["a.example"]}]
    elif rng.random() < 0.1:
        obj["spec"] = _rand_value(rng)
    return obj


def _client(driver):
    client = Backend(driver).new_client([K8sValidationTarget()])
    for name in policies.names():
        client.add_template(policies.load(name))
    for kind, params in CONSTRAINTS:
        client.add_constraint({
            "apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": kind, "metadata": {"name": kind.lower()},
            "spec": ({"parameters": params} if params else {}),
        })
    return client


def _norm(resp):
    return sorted(
        (r.msg, r.constraint["metadata"]["name"],
         (r.resource or {}).get("metadata", {}).get("name", ""))
        for r in resp.results())


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_audit_and_admission_parity(seed):
    rng = random.Random(seed)
    objs = [_rand_object(rng, i) for i in range(120)]
    ci = _client(RegoDriver())
    ct = _client(TpuDriver())
    for o in objs:
        ci.add_data(o)
        ct.add_data(o)
    a, b = _norm(ci.audit()), _norm(ct.audit())
    assert a == b, f"audit divergence (seed={seed})"
    assert a, f"vacuous fuzz audit (seed={seed})"
    # admission parity on a fresh batch of mutants
    for i in range(40):
        o = _rand_object(rng, 10_000 + i)
        ra = _norm(ci.review(AugmentedUnstructured(o)))
        rb = _norm(ct.review(AugmentedUnstructured(o)))
        assert ra == rb, f"admission divergence (seed={seed}, obj={o})"


@pytest.mark.parametrize("seed", [7])
def test_fuzz_mutation_parity(seed):
    """Churn fuzzing: random single-object replacements, inserts, and
    deletes between audits — the incremental patch journal must stay
    byte-identical to the interpreter's full recomputation at every
    step."""
    rng = random.Random(seed)
    objs = [_rand_object(rng, i) for i in range(80)]
    ci = _client(RegoDriver())
    ct = _client(TpuDriver())
    for o in objs:
        ci.add_data(o)
        ct.add_data(o)
    assert _norm(ci.audit()) == _norm(ct.audit())
    live = list(objs)
    for step in range(25):
        roll = rng.random()
        if roll < 0.6 and live:
            # replace an existing object with a fresh mutant (same
            # name/kind coordinates -> the journaled patch path)
            victim = rng.choice(live)
            mutant = _rand_object(rng, 0)
            mutant["apiVersion"] = victim["apiVersion"]
            mutant["kind"] = victim["kind"]
            mutant["metadata"]["name"] = victim["metadata"]["name"]
            if "namespace" in victim["metadata"]:
                mutant["metadata"]["namespace"] = \
                    victim["metadata"]["namespace"]
            else:
                mutant["metadata"].pop("namespace", None)
            live[live.index(victim)] = mutant
            ci.add_data(mutant)
            ct.add_data(mutant)
        elif roll < 0.8:
            new = _rand_object(rng, 1000 + step)
            live.append(new)
            ci.add_data(new)
            ct.add_data(new)
        elif live:
            victim = live.pop(rng.randrange(len(live)))
            ci.remove_data(victim)
            ct.remove_data(victim)
        a, b = _norm(ci.audit()), _norm(ct.audit())
        assert a == b, f"mutation divergence at step {step} (seed={seed})"
