"""Control-plane lifecycle tests (envtest/kind-e2e analog, SURVEY.md §4
tiers 3-4): real reconcilers, watch manager, audit manager, webhook server
and cert rotation run against the in-memory apiserver model, driven through
the same motions as the reference's bats suite (apply template -> apply
constraint -> admission deny -> audit populates status.violations -> sync
config -> ns-label webhook -> teardown)."""

import http.client
import json
import ssl
import time

import pytest

from gatekeeper_tpu.control.audit import AuditManager
from gatekeeper_tpu.control.certs import CertRotator
from gatekeeper_tpu.control.controllers import (
    CONSTRAINT_GROUP,
    TEMPLATE_GVK,
    ControllerManager,
)
from gatekeeper_tpu.control.kube import FakeKube, NotFound
from gatekeeper_tpu.control.main import Runtime, build_parser
from gatekeeper_tpu.control.metrics import REGISTRY
from gatekeeper_tpu.control.upgrade import UpgradeManager
from gatekeeper_tpu.control.watch import WatchManager

requires_crypto = pytest.mark.skipif(
    __import__("importlib").util.find_spec("cryptography") is None,
    reason="cryptography not installed (cert rotation is gated on it)")


TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8srequiredlabels"},
    "spec": {
        "crd": {"spec": {
            "names": {"kind": "K8sRequiredLabels"},
            "validation": {"openAPIV3Schema": {"properties": {
                "labels": {"type": "array", "items": {"type": "string"}}}}},
        }},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package k8srequiredlabels
violation[{"msg": msg}] {
  provided := {l | input.review.object.metadata.labels[l]}
  required := {l | l := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("missing labels: %v", [missing])
}
""",
        }],
    },
}

CONSTRAINT = {
    "apiVersion": "constraints.gatekeeper.sh/v1beta1",
    "kind": "K8sRequiredLabels",
    "metadata": {"name": "ns-must-have-owner"},
    "spec": {
        "match": {"kinds": [{"apiGroups": [""], "kinds": ["Namespace"]}]},
        "parameters": {"labels": ["owner"]},
    },
}


def admission_review(obj, operation="CREATE", username="alice", old=None):
    group, _, version = (obj.get("apiVersion") or "").rpartition("/")
    req = {
        "uid": "uid-1",
        "kind": {"group": group, "version": version, "kind": obj["kind"]},
        "operation": operation,
        "name": obj["metadata"]["name"],
        "userInfo": {"username": username},
        "object": obj if operation != "DELETE" else None,
    }
    if old is not None:
        req["oldObject"] = old
    ns = obj["metadata"].get("namespace")
    if ns:
        req["namespace"] = ns
    return {"apiVersion": "admission.k8s.io/v1beta1",
            "kind": "AdmissionReview", "request": req}


@pytest.fixture
def runtime():
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation", "--log-denies",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    yield rt
    rt.stop()


def ns(name, labels=None):
    o = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}}
    if labels is not None:
        o["metadata"]["labels"] = labels
    return o


def test_full_lifecycle(runtime):
    kube = runtime.kube
    # 1. apply the template; reconciler ingests + creates the constraint CRD
    kube.create(TEMPLATE)
    runtime.manager.drain()
    crd = kube.get(("apiextensions.k8s.io", "v1beta1",
                    "CustomResourceDefinition"),
                   "k8srequiredlabels.constraints.gatekeeper.sh")
    assert crd["spec"]["names"]["kind"] == "K8sRequiredLabels"
    templ = kube.get(TEMPLATE_GVK, "k8srequiredlabels")
    assert templ["status"]["created"] is True
    assert templ["status"]["byPod"][0]["observedGeneration"] == 0
    assert runtime.opa.knows_kind("K8sRequiredLabels")

    # 2. apply a constraint; constraint controller enforces it
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    stored = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                      "ns-must-have-owner")
    assert stored["status"]["byPod"][0]["enforced"] is True

    # 3. admission: violating namespace denied, compliant allowed
    handler = runtime.webhook.validation
    out = handler.handle(admission_review(ns("shipping")))
    assert out["response"]["allowed"] is False
    assert "missing labels" in out["response"]["status"]["reason"]
    out = handler.handle(admission_review(ns("ok", {"owner": "me"})))
    assert out["response"]["allowed"] is True

    # 4. audit: cluster objects produce status.violations
    kube.create(ns("bad-1"))
    kube.create(ns("good-1", {"owner": "me"}))
    runtime.audit.audit_once()
    stored = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                      "ns-must-have-owner")
    viol = stored["status"]["violations"]
    assert {v["name"] for v in viol} == {"bad-1", "shipping"} - {"shipping"} \
        or any(v["name"] == "bad-1" for v in viol)
    assert stored["status"]["totalViolations"] >= 1
    assert all(v["enforcementAction"] == "deny" for v in viol)

    # 5. deleting the constraint stops enforcement
    kube.delete((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                "ns-must-have-owner")
    runtime.manager.drain()
    out = handler.handle(admission_review(ns("shipping")))
    assert out["response"]["allowed"] is True

    # 6. deleting the template removes the kind
    kube.delete(TEMPLATE_GVK, "k8srequiredlabels")
    runtime.manager.drain()
    assert not runtime.opa.knows_kind("K8sRequiredLabels")


def test_audit_respects_violation_limit(runtime):
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    runtime.audit.limit = 3
    for i in range(10):
        kube.create(ns(f"bad-{i}"))
    runtime.audit.audit_once()
    stored = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                      "ns-must-have-owner")
    assert len(stored["status"]["violations"]) == 3
    assert stored["status"]["totalViolations"] == 10


def test_sync_config_populates_inventory(runtime):
    kube = runtime.kube
    kube.create({
        "apiVersion": "config.gatekeeper.sh/v1alpha1", "kind": "Config",
        "metadata": {"name": "config", "namespace": "gatekeeper-system"},
        "spec": {"sync": {"syncOnly": [
            {"group": "", "version": "v1", "kind": "Namespace"}]}},
    })
    kube.create(ns("synced-ns", {"team": "a"}))
    runtime.manager.drain()
    time.sleep(0.05)
    runtime.manager.drain()
    data = runtime.opa.driver.get_data(
        ("external", "admission.k8s.gatekeeper.sh", "cluster", "v1",
         "Namespace", "synced-ns"))
    assert data is not None and data["metadata"]["name"] == "synced-ns"
    # deleting the object removes it from inventory
    kube.delete(("", "v1", "Namespace"), "synced-ns")
    runtime.manager.drain()
    assert runtime.opa.driver.get_data(
        ("external", "admission.k8s.gatekeeper.sh", "cluster", "v1",
         "Namespace", "synced-ns")) is None


def test_dryrun_constraint_does_not_deny(runtime):
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    c = json.loads(json.dumps(CONSTRAINT))
    c["spec"]["enforcementAction"] = "dryrun"
    kube.create(c)
    runtime.manager.drain()
    out = runtime.webhook.validation.handle(admission_review(ns("shipping")))
    assert out["response"]["allowed"] is True
    # but audit still reports it
    kube.create(ns("bad-dry"))
    runtime.audit.audit_once()
    stored = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                      "ns-must-have-owner")
    assert any(v["enforcementAction"] == "dryrun"
               for v in stored["status"]["violations"])


def test_discovery_audit_resolves_namespace_selector(runtime):
    """namespaceSelector constraints must evaluate with REAL match
    decisions in discovery-mode audit on an UNSYNCED cluster — the
    listed Namespaces are sideloaded per review (reference
    manager.go:250-271), not read from synced inventory. Regression:
    the audit staged raw objects, the matcher fell back to the (empty)
    inventory cache, and every namespaceSelector constraint
    autorejected with "Namespace is not cached in OPA"."""
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    c = json.loads(json.dumps(CONSTRAINT))
    c["metadata"]["name"] = "owner-in-prod-ns"
    c["spec"]["match"] = {
        "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}],
        "namespaceSelector": {"matchLabels": {"env": "prod"}},
    }
    kube.create(c)
    runtime.manager.drain()
    kube.create(ns("prod-ns", {"env": "prod"}))
    kube.create(ns("dev-ns", {"env": "dev"}))

    def pod(name, namespace):
        return {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": namespace}}

    kube.create(pod("unlabeled-prod", "prod-ns"))
    kube.create(pod("unlabeled-dev", "dev-ns"))
    # no Config sync: the driver's inventory namespace cache is empty
    assert runtime.opa.driver.get_data(
        ("external", "admission.k8s.gatekeeper.sh", "cluster", "v1",
         "Namespace", "prod-ns")) is None
    runtime.audit.audit_once()
    stored = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                      "owner-in-prod-ns")
    viol = stored["status"]["violations"]
    names = {v["name"] for v in viol}
    assert "unlabeled-prod" in names, viol
    assert "unlabeled-dev" not in names, viol
    assert all("not cached in OPA" not in v["message"] for v in viol), viol


def test_gatekeeper_resource_validation(runtime):
    handler = runtime.webhook.validation
    bad_template = json.loads(json.dumps(TEMPLATE))
    bad_template["spec"]["targets"][0]["rego"] = "package broken\n}{"
    review = admission_review(bad_template)
    out = handler.handle(review)
    assert out["response"]["allowed"] is False
    assert out["response"]["status"]["code"] == 422
    ok = handler.handle(admission_review(TEMPLATE))
    assert ok["response"]["allowed"] is True
    # constraint with bogus enforcement action rejected
    runtime.kube.create(TEMPLATE)
    runtime.manager.drain()
    bad_c = json.loads(json.dumps(CONSTRAINT))
    bad_c["spec"]["enforcementAction"] = "warn-everyone"
    out = handler.handle(admission_review(bad_c))
    assert out["response"]["allowed"] is False


def test_delete_operation_reviews_old_object(runtime):
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    review = admission_review(ns("victim"), operation="DELETE",
                              old=ns("victim"))
    review["request"]["object"] = None
    out = runtime.webhook.validation.handle(review)
    assert out["response"]["allowed"] is False


def test_self_service_account_short_circuits(runtime):
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    review = admission_review(
        ns("shipping"),
        username="system:serviceaccount:gatekeeper-system:gatekeeper-admin")
    out = runtime.webhook.validation.handle(review)
    assert out["response"]["allowed"] is True


def test_namespace_label_webhook(runtime):
    h = runtime.webhook.ns_label
    labeled = ns("sneaky", {"admission.gatekeeper.sh/ignore": "true"})
    out = h.handle(admission_review(labeled))
    assert out["response"]["allowed"] is False
    plain = h.handle(admission_review(ns("plain", {})))
    assert plain["response"]["allowed"] is True


def test_admission_review_envelope_echoes_request(runtime):
    """admission.k8s.io/v1 requires the response to echo the request's
    apiVersion/kind; v1beta1 callers keep their version, and an
    envelope-free review gets the legacy defaults (regression for the
    envelope-fidelity satellite — both handlers)."""
    review = admission_review(
        ns("anything"),
        username="system:serviceaccount:gatekeeper-system:gatekeeper-admin")
    for handler in (runtime.webhook.validation, runtime.webhook.ns_label):
        v1 = dict(review, apiVersion="admission.k8s.io/v1")
        out = handler.handle(v1)
        assert out["apiVersion"] == "admission.k8s.io/v1"
        assert out["kind"] == "AdmissionReview"
        out = handler.handle(review)
        assert out["apiVersion"] == "admission.k8s.io/v1beta1"
        assert out["kind"] == "AdmissionReview"
        bare = handler.handle({"request": review["request"]})
        assert bare["apiVersion"] == "admission.k8s.io/v1beta1"
        assert bare["kind"] == "AdmissionReview"


def test_validation_failure_policy_flag():
    """--fail-closed: internal errors deny instead of the fail-open
    default, and either way the decision lands in metrics as
    status="error", not "allow"."""
    from gatekeeper_tpu.control.webhook import ValidationHandler

    class _Boom:
        def __getattr__(self, name):
            raise RuntimeError("engine exploded")

    review = admission_review(ns("shipping"))
    open_h = ValidationHandler(_Boom(), batcher=object())
    out = open_h.handle(review)
    assert out["response"]["allowed"] is True  # deployed fail-open
    assert out["response"]["status"]["code"] == 500

    closed_h = ValidationHandler(_Boom(), batcher=object(),
                                 fail_closed=True)
    out = closed_h.handle(review)
    assert out["response"]["allowed"] is False
    assert out["response"]["status"]["code"] == 500
    assert 'request_count{admission_status="error"}' in REGISTRY.render()


def test_namespace_label_webhook_exemption():
    from gatekeeper_tpu.control.webhook import NamespaceLabelHandler
    h = NamespaceLabelHandler(exempt_namespaces=("kube-system",))
    exempt = ns("kube-system", {"admission.gatekeeper.sh/ignore": "true"})
    assert h.handle(admission_review(exempt))["response"]["allowed"] is True


@requires_crypto
def test_webhook_over_https(runtime):
    """Full transport path: TLS server + cert rotation against the fake
    apiserver (secret + CA files), then a real HTTPS admission request."""
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    import tempfile

    from gatekeeper_tpu.control.webhook import WebhookServer

    with tempfile.TemporaryDirectory() as td:
        rotator = CertRotator(kube, td)
        rotator.refresh_certs()
        secret = kube.get(("", "v1", "Secret"),
                          "gatekeeper-webhook-server-cert",
                          "gatekeeper-system")
        assert "tls.crt" in secret["data"]
        server = WebhookServer(runtime.webhook.validation,
                               runtime.webhook.ns_label, port=0,
                               certfile=f"{td}/tls.crt",
                               keyfile=f"{td}/tls.key")
        server.start()
        try:
            ctx = ssl.create_default_context(cafile=f"{td}/ca.crt")
            ctx.check_hostname = False  # SANs are for the cluster DNS name
            conn = http.client.HTTPSConnection("127.0.0.1", server.port,
                                               context=ctx, timeout=10)
            body = json.dumps(admission_review(ns("shipping")))
            conn.request("POST", "/v1/admit", body,
                         {"Content-Type": "application/json"})
            resp = json.loads(conn.getresponse().read())
            assert resp["response"]["allowed"] is False
            assert resp["response"]["uid"] == "uid-1"
        finally:
            server.server.shutdown()


@requires_crypto
def test_cert_rotation_injects_vwh(runtime):
    kube = runtime.kube
    kube.create({
        "apiVersion": "admissionregistration.k8s.io/v1beta1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "gatekeeper-validating-webhook-configuration"},
        "webhooks": [{"name": "validation.gatekeeper.sh",
                      "clientConfig": {"service": {"name": "gk"}}},
                     {"name": "check-ignore-label.gatekeeper.sh",
                      "clientConfig": {}}],
    })
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        CertRotator(kube, td).refresh_certs()
    vwh = kube.get(("admissionregistration.k8s.io", "v1beta1",
                    "ValidatingWebhookConfiguration"),
                   "gatekeeper-validating-webhook-configuration")
    bundles = [w["clientConfig"].get("caBundle") for w in vwh["webhooks"]]
    assert all(bundles)


@requires_crypto
def test_vwh_recreate_reinjects_ca_bundle(runtime):
    """ReconcileVWH analog (reference certs.go:454-530): a VWH recreated
    between 12-hour refresh ticks must get the caBundle re-injected by
    the watch-driven reconciler, not wait for the next tick."""
    import tempfile

    kube = runtime.kube
    vwh_gvk = ("admissionregistration.k8s.io", "v1beta1",
               "ValidatingWebhookConfiguration")
    vwh = {
        "apiVersion": "admissionregistration.k8s.io/v1beta1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": "gatekeeper-validating-webhook-configuration"},
        "webhooks": [{"name": "validation.gatekeeper.sh",
                      "clientConfig": {"service": {"name": "gk"}}}],
    }
    kube.create(json.loads(json.dumps(vwh)))
    with tempfile.TemporaryDirectory() as td:
        rotator = CertRotator(kube, td)
        rotator.refresh_certs()
        assert kube.get(vwh_gvk, vwh["metadata"]["name"])["webhooks"][0][
            "clientConfig"].get("caBundle")
        rotator.start_reconciler(runtime.manager.wm)
        try:
            # recreate the VWH with no bundle; the reconciler must
            # restore it without any timer tick
            kube.delete(vwh_gvk, vwh["metadata"]["name"])
            kube.create(json.loads(json.dumps(vwh)))
            # generous: cert regeneration is ~seconds of RSA keygen on a
            # loaded single-core host
            deadline = time.time() + 30
            bundle = None
            while time.time() < deadline:
                cur = kube.get(vwh_gvk, vwh["metadata"]["name"])
                bundle = cur["webhooks"][0]["clientConfig"].get("caBundle")
                if bundle:
                    break
                time.sleep(0.02)
            assert bundle, "caBundle not re-injected on VWH recreate"

            # secret deleted: reconciler regenerates and re-injects
            kube.delete(("", "v1", "Secret"),
                        "gatekeeper-webhook-server-cert",
                        "gatekeeper-system")
            deadline = time.time() + 30
            ok = False
            while time.time() < deadline:
                try:
                    sec = kube.get(("", "v1", "Secret"),
                                   "gatekeeper-webhook-server-cert",
                                   "gatekeeper-system")
                except NotFound:
                    time.sleep(0.02)
                    continue
                if (sec.get("data") or {}).get("tls.crt"):
                    ok = True
                    break
            assert ok, "secret not regenerated after delete"
        finally:
            rotator.stop()


def test_watch_manager_refcounting():
    kube = FakeKube()
    kube.register_kind(("", "v1", "Namespace"), namespaced=False)
    wm = WatchManager(kube)
    r1 = wm.registrar("a")
    r2 = wm.registrar("b")
    kube.create(ns("pre-existing"))
    r1.add_watch(("", "v1", "Namespace"))
    assert wm.is_watched(("", "v1", "Namespace"))
    # r1 got the initial object
    ev = r1.events.get(timeout=1)
    assert ev.object["metadata"]["name"] == "pre-existing"
    # late joiner replays from cache
    r2.add_watch(("", "v1", "Namespace"))
    ev = r2.events.get(timeout=1)
    assert ev.object["metadata"]["name"] == "pre-existing"
    # removal is ref-counted
    r1.remove_watch(("", "v1", "Namespace"))
    assert wm.is_watched(("", "v1", "Namespace"))
    r2.remove_watch(("", "v1", "Namespace"))
    assert not wm.is_watched(("", "v1", "Namespace"))


def test_upgrade_manager_touches_objects():
    kube = FakeKube()
    kube.register_kind(TEMPLATE_GVK, namespaced=False)
    kube.register_kind((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                       namespaced=False)
    kube.create(TEMPLATE)
    kube.create(CONSTRAINT)
    rv_before = kube.get(TEMPLATE_GVK,
                         "k8srequiredlabels")["metadata"]["resourceVersion"]
    touched = UpgradeManager(kube).upgrade()
    assert touched == 2
    rv_after = kube.get(TEMPLATE_GVK,
                        "k8srequiredlabels")["metadata"]["resourceVersion"]
    assert rv_after != rv_before


def test_metrics_rendered(runtime):
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    kube.create(ns("bad-metrics"))
    runtime.audit.audit_once()
    runtime.webhook.validation.handle(admission_review(ns("nope")))
    text = REGISTRY.render()
    for name in ("violations", "audit_duration_seconds", "audit_last_run_time",
                 "request_count", "request_duration_seconds", "constraints",
                 "constraint_templates",
                 "gatekeeper_tpu_device_programs_warm"):
        assert name in text, f"metric {name} missing"


def test_status_writes_reach_fixpoint(runtime):
    """Regression: unconditional status writes used to emit MODIFIED events
    back into the controllers' own queues, reconciling forever."""
    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    gvk = (CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels")
    rv0 = kube.get(gvk, "ns-must-have-owner")["metadata"]["resourceVersion"]
    trv0 = kube.get(TEMPLATE_GVK,
                    "k8srequiredlabels")["metadata"]["resourceVersion"]
    time.sleep(0.5)  # idle: no event should cause further writes
    runtime.manager.drain()
    rv1 = kube.get(gvk, "ns-must-have-owner")["metadata"]["resourceVersion"]
    trv1 = kube.get(TEMPLATE_GVK,
                    "k8srequiredlabels")["metadata"]["resourceVersion"]
    assert rv0 == rv1, "constraint status keeps rewriting (reconcile loop)"
    assert trv0 == trv1, "template status keeps rewriting (reconcile loop)"


def test_deleted_constraint_not_resurrected_by_stale_event(runtime):
    """Regression: a MODIFIED event drained after DELETED must not re-add
    the constraint from the stale event payload."""
    from gatekeeper_tpu.control.kube import WatchEvent

    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    kube.create(CONSTRAINT)
    runtime.manager.drain()
    stale = kube.get((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                     "ns-must-have-owner")
    kube.delete((CONSTRAINT_GROUP, "v1beta1", "K8sRequiredLabels"),
                "ns-must-have-owner")
    runtime.manager.drain()
    # simulate the race: stale MODIFIED delivered after the delete
    ctrl = runtime.manager.constraint_ctrl
    ctrl.reconcile(WatchEvent("MODIFIED", stale))
    out = runtime.webhook.validation.handle(admission_review(ns("anything")))
    assert out["response"]["allowed"] is True, \
        "deleted constraint still denying admissions"


def test_webhook_tracing_via_config(caplog):
    """Config CRD traces opt (user, kind) pairs into per-request tracing
    (reference policy.go:290-309): the traced request bypasses the
    batcher, its trace is logged, dump: All logs the engine state, and
    the verdict is unchanged (r2 weak #4: the plumbing existed but
    nothing ever called it)."""
    import logging as _logging

    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.control.webhook import ValidationHandler
    from gatekeeper_tpu.target import K8sValidationTarget

    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8strace"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sTrace"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh", "rego": """
package k8strace
violation[{"msg": "traced deny"}] { input.review.object.metadata.name }
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sTrace", "metadata": {"name": "c"}, "spec": {}})
    traces = [{"user": "alice", "kind": {"group": "", "kind": "Pod"},
               "dump": "All"}]
    handler = ValidationHandler(client, traces_provider=lambda: traces)
    review = {
        "apiVersion": "admission.k8s.io/v1beta1", "kind": "AdmissionReview",
        "request": {
            "uid": "u1", "operation": "CREATE",
            "userInfo": {"username": "alice"},
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "namespace": "d", "name": "p",
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": "p", "namespace": "d"}},
        },
    }
    with caplog.at_level(_logging.INFO):
        out = handler.handle(review)
    assert out["response"]["allowed"] is False
    text = "\n".join(r.message for r in caplog.records)
    assert "request trace" in text and "state dump" in text
    traced = [getattr(r, "structured", {}) for r in caplog.records
              if r.message == "request trace"]
    assert traced and "traced deny" in traced[0]["trace"]
    # a non-matching user goes through the batcher, no trace logged
    caplog.clear()
    review["request"]["userInfo"]["username"] = "bob"
    with caplog.at_level(_logging.INFO):
        out = handler.handle(review)
    assert out["response"]["allowed"] is False
    assert "request trace" not in "\n".join(
        r.message for r in caplog.records)
    handler.batcher.stop()


def test_webhook_survives_adversarial_payloads(runtime):
    """Malformed admission bodies must never crash the server: garbage
    bytes/non-JSON get 400; structurally-broken reviews fail OPEN with
    an error log (the validating webhook's Ignore failure policy — the
    reference's posture for handler errors)."""
    import http.client
    import json as pyjson

    payloads = [
        b"not json at all",
        b"\xff\xfe garbage bytes",
        b"{}",
        pyjson.dumps({"request": None}).encode(),
        pyjson.dumps({"request": {"uid": "u"}}).encode(),
        pyjson.dumps({"request": {"uid": "u", "kind": "notadict",
                                  "object": []}}).encode(),
        pyjson.dumps({"request": {"uid": "u",
                                  "kind": {"group": 1, "version": [],
                                           "kind": {}},
                                  "object": {"metadata": None}}}).encode(),
    ]
    for body in payloads:
        for path in ("/v1/admit", "/v1/admitlabel"):
            conn = http.client.HTTPConnection("127.0.0.1",
                                              runtime.webhook.port,
                                              timeout=10)
            conn.request("POST", path, body,
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            data = r.read()
            if r.status == 400:
                continue  # unparseable body rejected at the transport
            assert r.status == 200
            out = pyjson.loads(data)
            assert out["response"]["allowed"] is True  # fail open
    # and the server still serves real traffic afterwards
    conn = http.client.HTTPConnection("127.0.0.1", runtime.webhook.port,
                                      timeout=10)
    conn.request("POST", "/v1/admit",
                 pyjson.dumps(admission_review(ns("post-fuzz"))),
                 {"Content-Type": "application/json"})
    assert pyjson.loads(conn.getresponse().read())["response"] is not None


def test_webhook_reuse_port_flag():
    """--webhook-reuse-port: two Runtimes share one webhook port (the
    kernel balances accepts across the SO_REUSEPORT listeners)."""
    import socket

    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

        def mk():
            args = build_parser().parse_args([
                "--fake-kube", "--port", str(port), "--prometheus-port",
                "0", "--health-addr", ":0", "--disable-cert-rotation",
                "--webhook-reuse-port", "--operation", "webhook",
            ])
            rt = Runtime(args)
            rt.args.metrics_backend = "none"
            rt.start()
            return rt

        a = mk()
        b = mk()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        body = json.dumps(admission_review(ns("x")))
        conn.request("POST", "/v1/admit", body,
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        assert out["response"]["allowed"] is True  # no constraints
    finally:
        a.stop()
        b.stop()


def test_audit_from_cache_sweeps_synced_inventory_only():
    """--audit-from-cache: one vectorized sweep over SYNCED inventory
    (reference manager.go:157-164) — objects of kinds outside the
    Config's syncOnly set are invisible to the audit, unlike discovery
    mode which lists everything."""
    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--health-addr", ":0", "--disable-cert-rotation",
        "--audit-from-cache", "true",
    ])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        kube = rt.kube
        kube.create(TEMPLATE)
        rt.manager.drain()
        c = json.loads(json.dumps(CONSTRAINT))
        # match Namespaces AND Pods so the sync filter is what decides
        c["spec"]["match"]["kinds"] = [
            {"apiGroups": [""], "kinds": ["Namespace", "Pod"]}]
        kube.create(c)
        # sync ONLY namespaces
        kube.create({
            "apiVersion": "config.gatekeeper.sh/v1alpha1", "kind": "Config",
            "metadata": {"name": "config", "namespace": "gatekeeper-system"},
            "spec": {"sync": {"syncOnly": [
                {"group": "", "version": "v1", "kind": "Namespace"}]}},
        })
        kube.create(ns("unlabeled-ns"))
        kube.create({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": "unlabeled-pod",
                                  "namespace": "unlabeled-ns"}})
        rt.manager.drain()
        rt.audit.audit_once()
        stored = kube.get((CONSTRAINT_GROUP, "v1beta1",
                           "K8sRequiredLabels"), "ns-must-have-owner")
        names = {v["name"] for v in stored["status"]["violations"]}
        assert "unlabeled-ns" in names, names
        # the pod violates too, but pods are not synced: invisible to
        # the from-cache sweep
        assert "unlabeled-pod" not in names, names
        assert stored["status"]["totalViolations"] == len(names)
    finally:
        rt.stop()


def test_teardown_scrubs_finalizers_on_shutdown(runtime):
    """TearDownState analog (reference main.go:221-246 +
    constrainttemplate_controller.go:466-556): graceful shutdown removes
    the gatekeeper finalizer from every template so etcd objects are
    deletable after the controller is gone."""
    from gatekeeper_tpu.control.controllers import FINALIZER

    kube = runtime.kube
    kube.create(TEMPLATE)
    runtime.manager.drain()
    stored = kube.get(TEMPLATE_GVK, "k8srequiredlabels")
    assert FINALIZER in (stored["metadata"].get("finalizers") or []), \
        "reconcile must add the finalizer"
    runtime.stop()
    stored = kube.get(TEMPLATE_GVK, "k8srequiredlabels")
    assert FINALIZER not in (stored["metadata"].get("finalizers") or []), \
        "shutdown must scrub the finalizer"
