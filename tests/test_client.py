"""Client/driver conformance suite.

Python analog of the reference framework's driver-agnostic e2e suite
(vendor/.../constraint/pkg/client/e2e_tests.go): deny templates, dryrun
enforcement, autoreject, data sync + audit, template/constraint lifecycle,
validation failures. Runs against any Driver; parametrized so the TPU
driver reuses it unchanged.
"""

import pytest

from gatekeeper_tpu.client import (
    Backend,
    Client,
    ClientError,
    RegoDriver,
    UnrecognizedConstraintError,
)
from gatekeeper_tpu.target import (
    AugmentedReview,
    AugmentedUnstructured,
    K8sValidationTarget,
)

DENY_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sdenyall"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sDenyAll"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package k8sdenyall
violation[{"msg": msg}] {
  msg := "denied!"
}
""",
        }],
    },
}

REQUIRED_LABELS_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8srequiredlabelstest"},
    "spec": {
        "crd": {"spec": {
            "names": {"kind": "K8sRequiredLabelsTest"},
            "validation": {"openAPIV3Schema": {"properties": {
                "labels": {"type": "array", "items": {"type": "string"}},
            }}},
        }},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package k8srequiredlabelstest
violation[{"msg": msg, "details": {"missing_labels": missing}}] {
  provided := {label | input.review.object.metadata.labels[label]}
  required := {label | label := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("you must provide labels: %v", [missing])
}
""",
        }],
    },
}

LIB_TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8swithlib"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sWithLib"}}},
        "targets": [{
            "target": "admission.k8s.gatekeeper.sh",
            "rego": """
package k8swithlib
violation[{"msg": msg}] {
  data.lib.helpers.is_bad(input.review.object)
  msg := data.lib.helpers.badness
}
""",
            "libs": ["""
package lib.helpers
badness = "object is bad"
is_bad(obj) { obj.metadata.labels["bad"] }
"""],
        }],
    },
}


def constraint(kind, name, *, params=None, match=None, enforcement=None):
    c = {
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": kind,
        "metadata": {"name": name},
        "spec": {},
    }
    if params is not None:
        c["spec"]["parameters"] = params
    if match is not None:
        c["spec"]["match"] = match
    if enforcement is not None:
        c["spec"]["enforcementAction"] = enforcement
    return c


def obj(kind, name, *, api_version="v1", namespace=None, labels=None, spec=None):
    o = {"apiVersion": api_version, "kind": kind, "metadata": {"name": name}}
    if namespace:
        o["metadata"]["namespace"] = namespace
    if labels is not None:
        o["metadata"]["labels"] = labels
    if spec is not None:
        o["spec"] = spec
    return o


def admission_request(o, operation="CREATE", old=None, namespace=None):
    group, _, version = (o.get("apiVersion") or "").rpartition("/")
    req = {
        "uid": "test-uid",
        "kind": {"group": group, "version": version, "kind": o["kind"]},
        "operation": operation,
        "name": o["metadata"]["name"],
        "object": o,
    }
    if old is not None:
        req["oldObject"] = old
    ns = namespace or o["metadata"].get("namespace")
    if ns:
        req["namespace"] = ns
    return req


@pytest.fixture(params=["local", "grpc"])
def client(request):
    """Every conformance case runs twice: against the in-process Client
    and against a live localhost gRPC service (service/) through
    RemoteClient — the wire protocol must not change any semantics."""
    if request.param == "local":
        yield Backend(RegoDriver()).new_client([K8sValidationTarget()])
        return
    pytest.importorskip("grpc")
    from gatekeeper_tpu.service import RemoteClient, make_server

    server, port = make_server(driver="rego")
    server.start()
    rc = RemoteClient(f"127.0.0.1:{port}")
    try:
        yield rc
    finally:
        rc.close()
        server.stop(grace=None)


def test_deny_all(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    rsp = client.review(AugmentedReview(admission_request(obj("Pod", "p1"))))
    results = rsp.results()
    assert len(results) == 1
    assert results[0].msg == "denied!"
    assert results[0].enforcement_action == "deny"
    assert results[0].constraint["metadata"]["name"] == "deny-all"
    assert results[0].resource["kind"] == "Pod"


def test_dryrun_enforcement_action(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(
        constraint("K8sDenyAll", "dry", enforcement="dryrun"))
    rsp = client.review(AugmentedReview(admission_request(obj("Pod", "p"))))
    assert [r.enforcement_action for r in rsp.results()] == ["dryrun"]


def test_required_labels_params_and_details(client):
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_constraint(constraint(
        "K8sRequiredLabelsTest", "need-gk", params={"labels": ["gatekeeper"]}))
    bad = obj("Namespace", "ns1")
    rsp = client.review(AugmentedReview(admission_request(bad)))
    results = rsp.results()
    assert len(results) == 1
    assert results[0].msg == 'you must provide labels: {"gatekeeper"}'
    assert results[0].metadata["details"] == {"missing_labels": ["gatekeeper"]}
    good = obj("Namespace", "ns2", labels={"gatekeeper": "yes"})
    assert client.review(AugmentedReview(admission_request(good))).results() == []


def test_template_libs_are_namespaced(client):
    client.add_template(LIB_TEMPLATE)
    client.add_constraint(constraint("K8sWithLib", "lib-c"))
    bad = obj("Pod", "p", labels={"bad": "yes"})
    rsp = client.review(AugmentedReview(admission_request(bad)))
    assert [r.msg for r in rsp.results()] == ["object is bad"]
    ok = obj("Pod", "p2", labels={})
    assert client.review(AugmentedReview(admission_request(ok))).results() == []


def test_match_kinds_namespaces_and_labels(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "pods-only", match={
        "kinds": [{"apiGroups": [""], "kinds": ["Pod"]}]}))
    assert client.review(
        AugmentedReview(admission_request(obj("Pod", "p")))).results()
    assert not client.review(
        AugmentedReview(admission_request(obj("Service", "s")))).results()

    client.add_constraint(constraint("K8sDenyAll", "ns-scoped", match={
        "namespaces": ["prod"]}))
    in_prod = client.review(AugmentedReview(
        admission_request(obj("Pod", "p", namespace="prod"))))
    assert {r.constraint["metadata"]["name"] for r in in_prod.results()} == \
        {"pods-only", "ns-scoped"}

    client.add_constraint(constraint("K8sDenyAll", "labeled", match={
        "kinds": [{"apiGroups": ["*"], "kinds": ["*"]}],
        "labelSelector": {"matchExpressions": [
            {"key": "env", "operator": "In", "values": ["prod"]}]},
    }))
    labeled = client.review(AugmentedReview(
        admission_request(obj("Service", "svc", labels={"env": "prod"}))))
    assert {r.constraint["metadata"]["name"] for r in labeled.results()} == \
        {"labeled"}


def test_autoreject_when_namespace_not_cached(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "ns-sel", match={
        "kinds": [{"apiGroups": [""], "kinds": ["Service"]}],
        "namespaceSelector": {"matchLabels": {"team": "a"}},
    }))
    req = admission_request(obj("Service", "s", namespace="unknown"))
    rsp = client.review(AugmentedReview(req))
    assert [r.msg for r in rsp.results()] == ["Namespace is not cached in OPA."]

    # sideloading the namespace (webhook fetches it) resolves the selector
    ns = obj("Namespace", "unknown", labels={"team": "a"})
    rsp = client.review(AugmentedReview(req, namespace=None), tracing=False)
    rsp2 = client.review(AugmentedReview(admission_request(
        obj("Service", "s", namespace="unknown"))))
    # now cache the namespace instead
    client.add_data(ns)
    rsp3 = client.review(AugmentedReview(req))
    assert [r.msg for r in rsp3.results()] == ["denied!"]
    # non-matching cached namespace -> no match, no autoreject
    client.add_data(obj("Namespace", "unknown", labels={"team": "b"}))
    assert client.review(AugmentedReview(req)).results() == []


def test_add_data_and_audit(client):
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_constraint(constraint(
        "K8sRequiredLabelsTest", "need-owner", params={"labels": ["owner"]}))
    client.add_data(obj("Namespace", "unlabeled"))
    client.add_data(obj("Namespace", "labeled", labels={"owner": "me"}))
    client.add_data(obj("Pod", "pod-1", namespace="default"))
    rsp = client.audit()
    results = rsp.results()
    assert len(results) == 2  # unlabeled ns + pod
    by_name = {r.resource["metadata"]["name"] for r in results}
    assert by_name == {"unlabeled", "pod-1"}
    assert all(r.msg == 'you must provide labels: {"owner"}' for r in results)
    # removing data removes findings
    client.remove_data(obj("Pod", "pod-1", namespace="default"))
    assert len(client.audit().results()) == 1


def test_audit_review_shapes(client):
    """Audit reviews carry kind/name/namespace the way regolib's
    make_review does (src.rego:40-61)."""
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-prod", match={
        "namespaces": ["prod"]}))
    client.add_data(obj("Pod", "p1", namespace="prod"))
    client.add_data(obj("Pod", "p2", namespace="dev"))
    client.add_data(obj("Namespace", "prod"))
    results = client.audit().results()
    # only the namespaced prod pod matches the namespaces selector;
    # the Namespace object itself has metadata.name == "prod"  -> matches too
    names = {r.resource["metadata"]["name"] for r in results}
    assert names == {"p1", "prod"}


def test_inventory_visible_to_templates(client):
    templ = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8suniquename"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sUniqueName"}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "rego": """
package k8suniquename
violation[{"msg": msg}] {
  other := data.inventory.namespace[ns][_]["Pod"][name]
  name == input.review.object.metadata.name
  ns != input.review.object.metadata.namespace
  msg := sprintf("name collision with %v/%v", [ns, name])
}
""",
            }],
        },
    }
    client.add_template(templ)
    client.add_constraint(constraint("K8sUniqueName", "uniq"))
    client.add_data(obj("Pod", "dup", namespace="other"))
    req = admission_request(obj("Pod", "dup", namespace="mine"))
    rsp = client.review(AugmentedReview(req))
    assert [r.msg for r in rsp.results()] == ["name collision with other/dup"]


def test_remove_constraint_and_template(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    req = AugmentedReview(admission_request(obj("Pod", "p")))
    assert client.review(req).results()
    client.remove_constraint(constraint("K8sDenyAll", "deny-all"))
    assert client.review(req).results() == []
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    client.remove_template(DENY_TEMPLATE)
    with pytest.raises(UnrecognizedConstraintError):
        client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    assert client.review(req).results() == []


def test_reset(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    client.add_data(obj("Namespace", "ns"))
    client.reset()
    req = AugmentedReview(admission_request(obj("Pod", "p")))
    assert client.review(req).results() == []
    assert client.audit().results() == []


def test_template_validation_errors(client):
    bad_name = {**DENY_TEMPLATE, "metadata": {"name": "wrong-name"}}
    with pytest.raises(ClientError):
        client.add_template(bad_name)
    no_targets = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sfoo"},
        "spec": {"crd": {"spec": {"names": {"kind": "K8sFoo"}}}},
    }
    with pytest.raises(ClientError):
        client.add_template(no_targets)
    no_violation = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sbar"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sBar"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": "package k8sbar\nallow { true }"}],
        },
    }
    with pytest.raises(ClientError, match="violation"):
        client.add_template(no_violation)
    bad_data_ref = {
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sbaz"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sBaz"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": """
package k8sbaz
violation[{"msg": "x"}] { data.constraints.secret }
"""}],
        },
    }
    with pytest.raises(ClientError, match="data reference"):
        client.add_template(bad_data_ref)


def test_constraint_validation_errors(client):
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    wrong_kind = constraint("K8sOther", "c1")
    with pytest.raises(UnrecognizedConstraintError):
        client.add_constraint(wrong_kind)
    wrong_group = constraint("K8sRequiredLabelsTest", "c2")
    wrong_group["apiVersion"] = "other.group/v1beta1"
    with pytest.raises(ClientError, match="wrong group"):
        client.add_constraint(wrong_group)
    bad_params = constraint("K8sRequiredLabelsTest", "c3",
                            params={"labels": "not-a-list"})
    with pytest.raises(ClientError, match="expected array"):
        client.add_constraint(bad_params)
    bad_operator = constraint("K8sRequiredLabelsTest", "c4", match={
        "labelSelector": {"matchExpressions": [
            {"key": "k", "operator": "Bogus"}]}})
    with pytest.raises(Exception, match="invalid operator|not in enum"):
        client.add_constraint(bad_operator)
    bad_name = constraint("K8sRequiredLabelsTest", "Not_A_DNS_Name")
    with pytest.raises(ClientError, match="Invalid Name"):
        client.add_constraint(bad_name)


def test_template_dedupe_and_update(client):
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    client.add_constraint(constraint(
        "K8sRequiredLabelsTest", "need-a", params={"labels": ["a"]}))
    # re-adding identical template keeps constraints
    client.add_template(REQUIRED_LABELS_TEMPLATE)
    req = AugmentedReview(admission_request(obj("Namespace", "n")))
    assert client.review(req).results()
    # updating the rego swaps behavior
    import copy
    updated = copy.deepcopy(REQUIRED_LABELS_TEMPLATE)
    updated["spec"]["targets"][0]["rego"] = """
package k8srequiredlabelstest
violation[{"msg": "always"}] { true }
"""
    client.add_template(updated)
    assert [r.msg for r in client.review(req).results()] == ["always"]


def test_create_crd_shape(client):
    crd = client.create_crd(REQUIRED_LABELS_TEMPLATE)
    assert crd["metadata"]["name"] == \
        "k8srequiredlabelstest.constraints.gatekeeper.sh"
    assert crd["spec"]["names"]["kind"] == "K8sRequiredLabelsTest"
    assert crd["spec"]["scope"] == "Cluster"
    spec_props = crd["spec"]["validation"]["openAPIV3Schema"][
        "properties"]["spec"]["properties"]
    assert set(spec_props) == {"match", "parameters", "enforcementAction"}


def test_review_of_unstructured_object(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    rsp = client.review(AugmentedUnstructured(obj("Pod", "p")))
    assert [r.msg for r in rsp.results()] == ["denied!"]
    # plain unstructured dicts work too
    rsp = client.review(obj("Pod", "p2"))
    assert [r.msg for r in rsp.results()] == ["denied!"]


def test_dump_contains_state(client):
    client.add_template(DENY_TEMPLATE)
    client.add_constraint(constraint("K8sDenyAll", "deny-all"))
    client.add_data(obj("Namespace", "ns1"))
    dump = client.dump()
    assert "deny-all" in dump and "ns1" in dump
