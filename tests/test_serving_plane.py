"""Admission serving plane (perf tentpole): pre-fork frontends over the
shared batching backplane, the generation-keyed decision cache, and the
HTTP hot-path overhaul.

Covers:
  * HTTP/1.1 keep-alive regression — two requests MUST reuse one
    connection (the server answered HTTP/1.0 before the fix);
  * `?timeout=` query hardening — duplicates, percent-encoding, bare
    keys, junk;
  * envelope fast-path encoding equivalence with the full encoder;
  * decision cache: hits across uid churn, generation invalidation on
    constraint updates, namespace-label invalidation, --log-denies
    deny re-evaluation;
  * backplane frame round-trip, deadline propagation, unreachable-
    engine failure stance (both stances + the `backplane.engine` fault
    point), frontend respawn, and the full Runtime smoke the CI
    `serving` job boots.

Every test runs under a hard SIGALRM timeout: a wedged socket must fail
that test fast, not eat the CI budget.
"""

from __future__ import annotations

import http.client
import json
import signal
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control.backplane import (
    BackplaneClient,
    BackplaneEngine,
    BackplaneError,
    FrontendServer,
    default_socket_path,
)
from gatekeeper_tpu.control.webhook import (
    DecisionCache,
    MicroBatcher,
    NamespaceLabelHandler,
    ValidationHandler,
    WebhookServer,
    encode_envelope,
    parse_timeout_query,
)
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.faults import FAULTS

TARGET = "admission.k8s.gatekeeper.sh"
PER_TEST_TIMEOUT_S = 120


@pytest.fixture(autouse=True)
def _hard_timeout_and_clean_faults():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        FAULTS.reset()


def _policy_client():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    return client


def _need_owner_constraint(name="need-owner"):
    return {"apiVersion": "constraints.gatekeeper.sh/v1beta1",
            "kind": "K8sNeedOwner", "metadata": {"name": name},
            "spec": {}}


def _review(name, labels=None, uid=None, timeout_s=None):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": "d"}}
    if labels:
        obj["metadata"]["labels"] = labels
    request = {"uid": uid or f"uid-{name}", "operation": "CREATE",
               "kind": {"group": "", "version": "v1", "kind": "Pod"},
               "name": name, "namespace": "d",
               "userInfo": {"username": "plane"}, "object": obj}
    if timeout_s is not None:
        request["timeoutSeconds"] = timeout_s
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": request}


def _post(conn, path, review):
    conn.request("POST", path, json.dumps(review),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp, json.loads(resp.read())


# --------------------------------------------------- satellite: keep-alive


def test_keepalive_two_requests_reuse_one_connection():
    """Regression: the Handler must answer HTTP/1.1 — as HTTP/1.0 the
    server closes after every response despite its keep-alive comments,
    doubling connection + thread churn on the API server hot path."""
    server = WebhookServer(None, NamespaceLabelHandler(()), port=0)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        review = {"apiVersion": "admission.k8s.io/v1",
                  "kind": "AdmissionReview",
                  "request": {"uid": "ka-1", "object": {
                      "metadata": {"name": "ns1"}}}}
        resp, out = _post(conn, "/v1/admitlabel", review)
        assert resp.version == 11, "server must answer HTTP/1.1"
        assert not resp.will_close, "server closed a keep-alive connection"
        sock_before = conn.sock
        assert sock_before is not None
        resp, out = _post(conn, "/v1/admitlabel", review)
        assert out["response"]["uid"] == "ka-1"
        assert conn.sock is sock_before, \
            "second request did not reuse the connection"
    finally:
        server.stop(drain_timeout=1.0)


# ---------------------------------------------- satellite: ?timeout= query


def test_parse_timeout_query_tolerates_the_wild():
    assert parse_timeout_query("timeout=5s") == 5.0
    # duplicates: first parseable wins
    assert parse_timeout_query("timeout=5s&timeout=10s") == 5.0
    assert parse_timeout_query("timeout=&timeout=3s") == 3.0
    # percent-encoding decodes ('1m10s' with encoded 'm'; encoded '.')
    assert parse_timeout_query("timeout=1%6D10s") == 70.0
    assert parse_timeout_query("timeout=2%2E5") == 2.5
    # bare keys / junk / absence never raise
    assert parse_timeout_query("timeout") is None
    assert parse_timeout_query("&&=&timeout&x") is None
    assert parse_timeout_query("") is None
    assert parse_timeout_query("a=b&c") is None
    assert parse_timeout_query("timeout=bogus") is None
    # zero/negative budgets are not budgets
    assert parse_timeout_query("timeout=0s") is None


def test_http_timeout_query_reaches_the_deadline(monkeypatch):
    """End-to-end: a duplicate + percent-encoded query string still
    lands in request.timeoutSeconds through the real HTTP server."""
    seen = {}

    class Probe:
        batcher = MicroBatcher(None, evaluate=lambda reviews:
                               [[] for _ in reviews])

        def handle(self, review):
            seen["timeout"] = review["request"].get("timeoutSeconds")
            return {"apiVersion": review.get("apiVersion"),
                    "kind": review.get("kind"),
                    "response": {"uid": "p", "allowed": True}}

    server = WebhookServer(Probe(), None, port=0)
    server.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                          timeout=10)
        _post(conn, "/v1/admit?timeout=7%73&timeout=30s",
              _review("q1"))
        assert seen["timeout"] == 7.0
    finally:
        server.stop(drain_timeout=1.0)


# ------------------------------------------------- envelope fast encoding


def test_encode_envelope_matches_full_encoder():
    cases = [
        {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
         "response": {"uid": "5f0e-11:d.x_Y", "allowed": True}},
        {"apiVersion": "admission.k8s.io/v1beta1",
         "kind": "AdmissionReview",
         "response": {"uid": "u", "allowed": False,
                      "status": {"code": 403, "reason": 'msg "quoted" \\'}}},
        # exotic uid must take the fallback, not break JSON
        {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
         "response": {"uid": 'u"\n', "allowed": True}},
        # extra keys (patch) take the fallback
        {"apiVersion": None, "kind": None,
         "response": {"uid": "u", "allowed": True, "patchType": "JSONPatch",
                      "patch": "W10="}},
    ]
    for env in cases:
        assert json.loads(encode_envelope(env)) == env


# ------------------------------------------------------- decision cache


def test_decision_cache_hits_across_uid_churn():
    client = _policy_client()
    client.add_constraint(_need_owner_constraint())
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    out1 = handler.handle(_review("p1", {"owner": "me"}, uid="u-1"))
    out2 = handler.handle(_review("p1", {"owner": "me"}, uid="u-2"))
    assert out1["response"]["allowed"] and out2["response"]["allowed"]
    # same object, different uid: one evaluation, one hit — and each
    # response carries ITS OWN uid
    assert handler.cache.hits == 1
    assert out1["response"]["uid"] == "u-1"
    assert out2["response"]["uid"] == "u-2"
    # a denied object caches too (no --log-denies here)
    handler.handle(_review("bad", None, uid="u-3"))
    out = handler.handle(_review("bad", None, uid="u-4"))
    assert out["response"]["allowed"] is False
    assert handler.cache.hits == 2
    handler.batcher.stop()


def test_decision_cache_invalidated_by_constraint_update():
    """The acceptance case: a cached ALLOW must flip to DENY after a
    constraint lands and bumps the library generation."""
    client = _policy_client()  # template only: no constraint yet
    handler = ValidationHandler(client, kube=None,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    out = handler.handle(_review("pod-a", None, uid="u-1"))
    assert out["response"]["allowed"] is True
    # cached: an identical retry is served without evaluation
    out = handler.handle(_review("pod-a", None, uid="u-2"))
    assert handler.cache.hits == 1
    gen_before = client.generation
    client.add_constraint(_need_owner_constraint())
    assert client.generation > gen_before
    out = handler.handle(_review("pod-a", None, uid="u-3"))
    assert out["response"]["allowed"] is False, \
        "stale cached allow served after a constraint update"
    # and removing the constraint flips it back (another bump)
    client.remove_constraint(_need_owner_constraint())
    out = handler.handle(_review("pod-a", None, uid="u-4"))
    assert out["response"]["allowed"] is True
    handler.batcher.stop()


def test_decision_cache_ns_label_key():
    ns = {"metadata": {"name": "d", "labels": {"env": "prod"}}}
    ns2 = {"metadata": {"name": "d", "labels": {"env": "dev"}}}
    assert DecisionCache.ns_key(ns) != DecisionCache.ns_key(ns2)
    # the WHOLE namespace object keys the cache: policies can match on
    # annotations (or anything else the sideload carries), not labels
    # alone
    ns3 = {"metadata": {"name": "d", "labels": {"env": "prod"},
                        "annotations": {"owner": "x"}}}
    assert DecisionCache.ns_key(ns) != DecisionCache.ns_key(ns3)
    assert DecisionCache.ns_key(None) == b""
    # uid and timeoutSeconds are noise; object content is signal
    r = _review("x", {"owner": "me"})["request"]
    r2 = dict(r, uid="other", timeoutSeconds=3)
    assert DecisionCache.request_key(r) == DecisionCache.request_key(r2)
    r3 = _review("x", {"owner": "you"})["request"]
    assert DecisionCache.request_key(r) != DecisionCache.request_key(r3)


def test_decision_cache_log_denies_reevaluates_denials():
    """--log-denies: every denial must re-evaluate (and so re-log);
    allows still serve from the cache."""
    client = _policy_client()
    client.add_constraint(_need_owner_constraint())
    handler = ValidationHandler(client, kube=None, log_denies=True,
                                batcher=MicroBatcher(client,
                                                     max_wait=0.001))
    for uid in ("a", "b"):
        out = handler.handle(_review("bad", None, uid=uid))
        assert out["response"]["allowed"] is False
    assert handler.cache.hits == 0  # denials never hit under log_denies
    for uid in ("c", "d"):
        handler.handle(_review("ok", {"owner": "me"}, uid=uid))
    assert handler.cache.hits == 1  # allows still do
    handler.batcher.stop()


def test_decision_cache_lru_bound():
    cache = DecisionCache(size=4)
    for i in range(10):
        cache.put((bytes([i]), 0, 0), {"allowed": True})
    assert len(cache) == 4


# ------------------------------------------------------ backplane plumbing


def _plane(validation=None, ns_label=None, mutation=None,
           fail_closed=False):
    sock = default_socket_path() + ".t"
    engine = BackplaneEngine(sock, validation=validation,
                             ns_label=ns_label, mutation=mutation)
    engine.start()
    client = BackplaneClient(sock, worker_id="test")
    frontend = FrontendServer(client, port=0, addr="127.0.0.1",
                              fail_closed=fail_closed)
    frontend.start()
    return engine, client, frontend


def test_backplane_roundtrip_and_404():
    client = _policy_client()
    client.add_constraint(_need_owner_constraint())
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    engine, bc, fe = _plane(validation=validation,
                            ns_label=NamespaceLabelHandler(()))
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=10)
        _, out = _post(conn, "/v1/admit?timeout=5s",
                       _review("ok", {"owner": "me"}))
        assert out["response"]["allowed"] is True
        _, out = _post(conn, "/v1/admit", _review("bad"))
        assert out["response"]["allowed"] is False
        assert "no owner label" in out["response"]["status"]["reason"]
        _, out = _post(conn, "/v1/admitlabel", _review("ns"))
        assert out["response"]["allowed"] is True
        # mutation is NOT served by this plane: 404 locally, no hop
        conn.request("POST", "/v1/mutate", json.dumps(_review("m")),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 404
    finally:
        fe.stop(drain_timeout=1.0)
        engine.stop(drain_timeout=1.0)


def test_backplane_deadline_propagates_to_engine():
    """A 1s ?timeout= rides the frame: the engine answers per the
    failure stance BEFORE the budget expires, even when evaluation
    stalls far longer."""
    stall = threading.Event()

    def evaluate(reviews):
        stall.wait(10.0)
        return [[] for _ in reviews]

    batcher = MicroBatcher(None, max_wait=0.001, evaluate=evaluate)
    validation = ValidationHandler(_policy_client(), kube=None,
                                   batcher=batcher)
    engine, bc, fe = _plane(validation=validation)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=10)
        t0 = time.monotonic()
        _, out = _post(conn, "/v1/admit?timeout=1s", _review("slow"))
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0, "verdict landed after the 1s budget"
        assert out["response"]["allowed"] is True  # fail-open
        assert out["response"]["status"]["code"] == 504
    finally:
        stall.set()
        fe.stop(drain_timeout=1.0)
        engine.stop(drain_timeout=1.0)


@pytest.mark.parametrize("fail_closed", [False, True])
def test_engine_unreachable_answers_per_stance(fail_closed):
    bc = BackplaneClient(default_socket_path() + ".gone")
    fe = FrontendServer(bc, port=0, addr="127.0.0.1",
                        fail_closed=fail_closed)
    fe.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=10)
        resp, out = _post(conn, "/v1/admit", _review("x", uid="want-uid"))
        assert resp.status == 200
        assert out["response"]["allowed"] is (not fail_closed)
        assert out["response"]["status"]["code"] == 503
        # uid recovered by the lazy parse so the API server can match
        # the response to its request
        assert out["response"]["uid"] == "want-uid"
    finally:
        fe.stop(drain_timeout=1.0)


def test_backplane_engine_fault_point():
    """Arming backplane.engine makes a HEALTHY plane answer per the
    stance (chaos hook); disarming restores real verdicts."""
    client = _policy_client()
    client.add_constraint(_need_owner_constraint())
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    engine, bc, fe = _plane(validation=validation)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=10)
        FAULTS.inject("backplane.engine", mode="error")
        _, out = _post(conn, "/v1/admit", _review("bad"))
        assert out["response"]["allowed"] is True  # stance, not verdict
        assert out["response"]["status"]["code"] == 503
        assert FAULTS.fired("backplane.engine") == 1
        FAULTS.clear("backplane.engine")
        _, out = _post(conn, "/v1/admit", _review("bad2"))
        assert out["response"]["allowed"] is False  # real verdict again
    finally:
        fe.stop(drain_timeout=1.0)
        engine.stop(drain_timeout=1.0)


def test_backplane_client_hello_failure_does_not_deadlock(monkeypatch,
                                                          tmp_path):
    """An engine that dies between connect() and the hello send (the
    chaos suite's SIGKILL window) sends _ensure_connected into _drop()
    from inside its own _conn_lock critical section. With a
    non-reentrant lock that self-deadlocks — and every HTTP thread of
    the frontend then wedges behind the lock, hanging callers into
    their client-side timeouts instead of stance answers."""
    import socket as sk

    from gatekeeper_tpu.control import backplane as bp

    path = str(tmp_path / "hello.sock")
    srv = sk.socket(sk.AF_UNIX, sk.SOCK_STREAM)
    srv.bind(path)
    srv.listen(2)
    try:
        cl = bp.BackplaneClient(path, worker_id="t")
        monkeypatch.setattr(
            bp, "_send_frame",
            lambda *a, **k: (_ for _ in ()).throw(
                OSError("peer died before hello")))
        res: list = []

        def attempt():
            try:
                cl._ensure_connected()
                res.append("connected")
            except bp.BackplaneError:
                res.append("error")

        t1 = threading.Thread(target=attempt, daemon=True)
        t1.start()
        t1.join(5)
        assert res == ["error"], \
            "hello-failure path hung instead of raising"
        # the lock must be free again: a retry takes the same path
        t2 = threading.Thread(target=attempt, daemon=True)
        t2.start()
        t2.join(5)
        assert res == ["error", "error"], \
            "connection lock was left held after the hello failure"
        cl.close()
    finally:
        srv.close()


def test_frontend_forward_stats_reach_engine_metrics():
    from gatekeeper_tpu.control import metrics as gm

    client = _policy_client()
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    engine, bc, fe = _plane(validation=validation)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=10)
        for i in range(3):
            _post(conn, "/v1/admit", _review(f"s{i}", {"owner": "x"}))
        stats = fe.stats.drain("test")
        assert stats is not None and stats["count"] == 3
        bc.send_stats(stats)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            text = gm.REGISTRY.render()
            if "gatekeeper_tpu_backplane_forward_duration_seconds_count" \
                    in text and 'worker="test"' in text:
                break
            time.sleep(0.05)
        assert 'worker="test"' in gm.REGISTRY.render()
    finally:
        fe.stop(drain_timeout=1.0)
        engine.stop(drain_timeout=1.0)


# ------------------------------------------------- full runtime (CI smoke)


def test_serving_plane_runtime_smoke():
    """What the CI `serving` job boots: a Runtime with 2 pre-forked
    frontend processes + the engine, round-tripping admit / mutate /
    admitlabel through real subprocesses, then draining cleanly."""
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--operation", "mutation-webhook",
        "--admission-workers", "2"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        assert rt.webhook is None and rt.backplane is not None
        deadline = time.monotonic() + 10
        while rt.backplane.connected < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert rt.backplane.connected == 2
        assert rt.frontends.alive()
        conn = http.client.HTTPConnection("127.0.0.1", rt.frontends.port,
                                          timeout=15)
        for path in ("/v1/admit", "/v1/admitlabel", "/v1/mutate"):
            _, out = _post(conn, path + "?timeout=10s", _review("rt"))
            assert out["response"]["allowed"] is True, path
            assert out["response"]["uid"] == "uid-rt"
    finally:
        rt.stop()
    assert not rt.frontends.alive()


def test_supervisor_respawns_dead_frontend():
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        victim = rt.frontends._procs[0]
        victim.kill()
        victim.wait(10)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if rt.frontends.alive() and \
                    rt.frontends._procs[0] is not victim:
                break
            time.sleep(0.1)
        assert rt.frontends.alive(), "supervisor did not respawn"
        # the respawned worker serves
        conn = http.client.HTTPConnection("127.0.0.1", rt.frontends.port,
                                          timeout=15)
        for i in range(4):  # hit both workers' accept queues
            _, out = _post(conn, "/v1/admit", _review(f"r{i}"))
            assert out["response"]["allowed"] is True
            conn.close()
    finally:
        rt.stop()


# --------------------------------------------------- N-engine plane


def test_registry_snapshot_merge_delta_and_restart_reset():
    """The M-frame stats relay: an engine child's totals merge into the
    primary as deltas, and a restarted engine (totals reset to zero)
    contributes its new counts instead of a negative rewind."""
    from gatekeeper_tpu.control.metrics import Registry

    src = Registry()
    dst = Registry()
    names = ("request_count", "request_duration_seconds")
    src.counter_add("request_count", "h", 3, admission_status="allowed")
    src.observe("request_duration_seconds", "h", 0.02,
                admission_status="allowed")
    snap1 = src.snapshot(names)
    dst.merge_snapshot_delta(snap1, None)
    src.counter_add("request_count", "h", 2, admission_status="allowed")
    src.observe("request_duration_seconds", "h", 0.04,
                admission_status="allowed")
    snap2 = src.snapshot(names)
    dst.merge_snapshot_delta(snap2, snap1)
    text = dst.render()
    assert 'request_count{admission_status="allowed"} 5' in text
    assert ('request_duration_seconds_count'
            '{admission_status="allowed"} 2') in text
    # engine restart: a fresh process's totals are all new work
    fresh = Registry()
    fresh.counter_add("request_count", "h", 4,
                      admission_status="allowed")
    dst.merge_snapshot_delta(fresh.snapshot(names), snap2)
    assert 'request_count{admission_status="allowed"} 9' in dst.render()


def test_router_least_load_and_failover_on_engine_death():
    """BackplaneRouter over two engines: calls succeed, and after one
    engine drops dead mid-plane the router fails over — every later
    call still gets a REAL verdict from the survivor, no stance
    answers."""
    from gatekeeper_tpu.control.backplane import BackplaneRouter

    def build(tag):
        client = _policy_client()
        client.add_constraint(_need_owner_constraint())
        validation = ValidationHandler(
            client, kube=None,
            batcher=MicroBatcher(client, max_wait=0.001))
        sock = default_socket_path() + tag
        engine = BackplaneEngine(sock, validation=validation,
                                 ns_label=NamespaceLabelHandler(()))
        engine.start()
        return engine, sock

    e1, s1 = build(".r1")
    e2, s2 = build(".r2")
    router = BackplaneRouter([s1, s2], worker_id="rt")
    try:
        deadline = time.monotonic() + 5
        for i in range(8):
            body = json.dumps(_review(f"a{i}", {"owner": "me"})).encode()
            status, payload = router.call("/v1/admit", body, 5.0,
                                          time.monotonic() + 5)
            assert status == 200
            assert json.loads(payload)["response"]["allowed"] is True
        e1.abort()  # chaos: engine 1 dies with the plane live
        for i in range(8):
            body = json.dumps(_review(f"b{i}")).encode()
            status, payload = router.call("/v1/admit", body, 5.0,
                                          time.monotonic() + 5)
            assert status == 200
            out = json.loads(payload)["response"]
            assert out["allowed"] is False, "survivor must evaluate"
            assert "no owner label" in out["status"]["reason"]
    finally:
        router.close()
        e1.abort()
        e2.stop(drain_timeout=1.0)


def test_library_replication_ops_and_full_sync():
    """L frames: a replica engine's LibrarySink applies incremental ops
    (bumping ITS client's generation) and a full sync reconciles —
    replaying the snapshot and dropping templates/constraints the
    primary no longer carries."""
    from gatekeeper_tpu.control.engine import LibrarySink

    replica = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    sink = LibrarySink(replica)
    sock = default_socket_path() + ".lib"
    engine = BackplaneEngine(sock, library_sink=sink)
    engine.start()
    ctl = BackplaneClient(sock, worker_id="ctl")
    try:
        primary = _policy_client()
        primary.add_constraint(_need_owner_constraint())
        ops = []
        primary.on_change = lambda op, obj: ops.append((op, obj))
        gen0 = replica.generation
        ctl.control({"op": "sync",
                     "library": primary.snapshot_library()})
        assert replica.template_kinds() == ["K8sNeedOwner"]
        assert replica.library_index() == {
            "K8sNeedOwner": ["need-owner"]}
        assert replica.generation > gen0
        # incremental op: primary adds a constraint, the observer fires,
        # the op replicates, the replica's OWN generation bumps
        primary.add_constraint(_need_owner_constraint("second"))
        assert ops and ops[-1][0] == "add_constraint"
        gen1 = replica.generation
        ctl.control({"op": ops[-1][0], "obj": ops[-1][1]})
        assert replica.library_index() == {
            "K8sNeedOwner": ["need-owner", "second"]}
        assert replica.generation > gen1
        # sync reconciliation: the primary dropped a constraint the
        # replica still carries — the sync must remove it
        primary.remove_constraint(_need_owner_constraint("second"))
        ctl.control({"op": "sync",
                     "library": primary.snapshot_library()})
        assert replica.library_index() == {
            "K8sNeedOwner": ["need-owner"]}
        # unknown op is refused, not swallowed
        with pytest.raises(BackplaneError):
            ctl.control({"op": "no-such-op"})
    finally:
        ctl.close()
        engine.stop(drain_timeout=1.0)


def test_multi_engine_runtime_burst_with_engine_kill():
    """The acceptance e2e: a Runtime with --admission-engines 3 (this
    process is engine 0; engines 1 and 2 are spawned children, each
    with its own Client/MicroBatcher/socket) and 2 pre-forked frontends
    routing across all three. An open-loop burst of unique reviews must
    complete with ZERO unanswered admissions while engine 1 is
    SIGKILLed mid-burst; the library replicated to the children must
    produce correct verdicts; the supervisor must respawn the victim
    and resync it."""
    from gatekeeper_tpu.control import metrics as gm
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--admission-engines", "3"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        assert rt.engines is not None
        assert rt.engines.alive_count() == 2
        # library ingested AFTER boot replicates to every engine child
        rt.opa.add_template(_policy_client().get_template("K8sNeedOwner"))
        rt.opa.add_constraint(_need_owner_constraint())
        results: list = []
        res_lock = threading.Lock()
        kill_at = threading.Event()

        def worker(k):
            conn = http.client.HTTPConnection(
                "127.0.0.1", rt.frontends.port, timeout=30)
            mine = []
            for j in range(24):
                name = f"w{k}n{j}"
                labeled = (j % 2 == 0)
                review = _review(name,
                                 {"owner": "me"} if labeled else None)
                try:
                    _, out = _post(conn, "/v1/admit?timeout=15s", review)
                    mine.append((name, labeled,
                                 out["response"]["allowed"],
                                 out["response"]["uid"]))
                except Exception as e:  # an unanswered admission
                    mine.append((name, labeled, f"UNANSWERED: {e}",
                                 None))
                if k == 0 and j == 6:
                    kill_at.set()
            with res_lock:
                results.extend(mine)

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(6)]
        for t in threads:
            t.start()
        kill_at.wait(30)
        rt.engines.kill_engine(1)  # chaos: one chip's engine dies
        for t in threads:
            t.join(90)
        assert len(results) == 6 * 24
        for name, labeled, allowed, uid in results:
            assert isinstance(allowed, bool), \
                f"unanswered admission {name}: {allowed}"
            assert allowed is labeled, (name, labeled, allowed)
            assert uid == f"uid-{name}"
        # the victim respawns and resyncs
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if rt.engines.alive_count() == 2 and \
                    not rt.engines._dirty.get(1):
                break
            time.sleep(0.2)
        assert rt.engines.alive_count() == 2, "engine not respawned"
        # requests spread across engine processes: the relayed
        # per-engine counters prove the frontends actually routed
        rt.engines.poll_stats()
        text = gm.REGISTRY.render()
        assert 'gatekeeper_tpu_engine_requests_total' in text
        spread = [e for e in ("1", "2")
                  if f'engine="{e}"' in text]
        assert spread, "no requests reached any engine child"
    finally:
        rt.stop()
    assert not rt.frontends.alive()
