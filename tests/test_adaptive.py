"""Adaptive serving controller + degradation ladder (robustness PR).

Drives AdaptiveController.tick() deterministically — a private Registry
seeded with exactly the signal series the controller samples, fake
batcher/engines/SLO actuators, explicit `now` values — and asserts the
control policies, the anti-oscillation rate limits (per-knob cooldown,
reversal hysteresis, flip accounting), the degradation-ladder
escalation/de-escalation state machine, the ValidationHandler rung
gates on a real serving pipeline, the EngineSupervisor fan-out clamp,
and the kill switch's bit-exact baseline restore."""

from __future__ import annotations

import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control import metrics
from gatekeeper_tpu.control.adaptive import (
    RUNG_CACHE_ONLY,
    RUNG_FAIL_STANCE,
    RUNG_NORMAL,
    RUNG_TIGHTEN_SHED,
    AdaptiveController,
    DegradationLadder,
)
from gatekeeper_tpu.control.backplane import EngineSupervisor
from gatekeeper_tpu.control.metrics import FILL_BUCKETS, Registry
from gatekeeper_tpu.control.webhook import (
    SERVICE_ACCOUNT,
    MicroBatcher,
    ValidationHandler,
)
from gatekeeper_tpu.target import K8sValidationTarget

TARGET = "admission.k8s.gatekeeper.sh"


# ------------------------------------------------------------ fakes


class FakeBatcher:
    """MicroBatcher's knob surface without its threads."""

    def __init__(self, max_wait=0.005, max_batch=256, max_queue=64):
        self.max_wait = max_wait
        self.max_batch = max_batch
        self.max_queue = max_queue

    def set_knobs(self, max_wait=None, max_batch=None, max_queue=None):
        if max_wait is not None:
            self.max_wait = max(0.0, float(max_wait))
        if max_batch is not None:
            self.max_batch = max(1, int(max_batch))
        if max_queue is not None:
            self.max_queue = max(0, int(max_queue))
        return self.knob_values()

    def knob_values(self):
        return {"max_wait": self.max_wait, "max_batch": self.max_batch,
                "max_queue": self.max_queue}


class FakeEngines:
    """EngineSupervisor's fan-out surface."""

    def __init__(self, ids=("1", "2", "3")):
        self.engine_ids = list(ids)
        self._total = 1 + len(ids)
        self.calls: list = []

    def active_total(self):
        return self._total

    def scale_to(self, total):
        total = max(1, min(1 + len(self.engine_ids), int(total)))
        self._total = total
        self.calls.append(total)
        return total


class FakeSlo:
    def __init__(self):
        self.rates: dict = {}

    def set_burn(self, b5m, b1h):
        self.rates = {"availability": {"5m": {"burn_rate": b5m},
                                       "1h": {"burn_rate": b1h}}}

    def latest(self):
        return self.rates


def _seed_seals(reg, reason, n, fill):
    for _ in range(n):
        reg.counter_add("gatekeeper_tpu_batch_seal_total", "h",
                        reason=reason, plane="admission")
        reg.observe("gatekeeper_tpu_batch_fill_ratio", "h", fill,
                    buckets=FILL_BUCKETS, plane="admission")


def _controller(reg, **kw):
    kw.setdefault("interval", 999.0)      # the thread never ticks on
    kw.setdefault("cooldown_s", 0.0)      # its own: tests drive tick()
    kw.setdefault("hysteresis_s", 0.0)
    c = AdaptiveController(registry=reg, **kw)
    return c


# -------------------------------------------------- batch-shape policy


def test_max_wait_trickle_shrinks_wait():
    reg = Registry()
    b = FakeBatcher(max_wait=0.008)
    c = _controller(reg, batcher=b)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)
        assert b.max_wait == pytest.approx(0.004)
        acts = c.actuations()
        assert acts and acts[-1]["knob"] == "batch_max_wait"
        assert acts[-1]["direction"] == "down"
    finally:
        c.disarm()


def test_full_seals_grow_batch():
    reg = Registry()
    b = FakeBatcher(max_batch=128)
    c = _controller(reg, batcher=b)
    c.arm()
    try:
        _seed_seals(reg, "full", 10, fill=1.0)
        c.tick(now=100.0)
        assert b.max_batch == 256
        assert c.actuations()[-1]["knob"] == "batch_max_batch"
        assert c.actuations()[-1]["direction"] == "up"
    finally:
        c.disarm()


def test_quiet_plane_relaxes_toward_baseline_exactly():
    reg = Registry()
    b = FakeBatcher(max_wait=0.008)
    c = _controller(reg, batcher=b, relax_after_s=5.0)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)
        assert b.max_wait < 0.008
        # quiet window elapses: the knob drifts back and LANDS on the
        # baseline (min/max against the baseline, not an approach that
        # overshoots or stalls one step short)
        for i in range(10):
            c.tick(now=120.0 + i)
        assert b.max_wait == 0.008
    finally:
        c.disarm()


def test_clamp_to_declared_bounds():
    reg = Registry()
    b = FakeBatcher(max_wait=0.001)
    c = _controller(reg, batcher=b, max_wait_lo=0.0008)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)
        assert b.max_wait == 0.0008      # halving clamped at lo
        assert c.actuations()[-1]["clamped"] is True
    finally:
        c.disarm()


# --------------------------------------------- cooldown / hysteresis


def test_cooldown_suppresses_same_direction_repeat():
    reg = Registry()
    b = FakeBatcher(max_wait=0.02)
    c = _controller(reg, batcher=b, cooldown_s=5.0)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)
        assert b.max_wait == pytest.approx(0.01)
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=101.0)                # inside the 5s cooldown
        assert b.max_wait == pytest.approx(0.01)
        assert c.knobs["batch_max_wait"].suppressed >= 1
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=106.0)                # cooldown elapsed
        assert b.max_wait == pytest.approx(0.005)
    finally:
        c.disarm()


def test_hysteresis_holds_direction_reversals_and_counts_flips():
    reg = Registry()
    b = FakeBatcher(max_wait=0.008)
    c = _controller(reg, batcher=b, hysteresis_s=10.0,
                    relax_after_s=2.0)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)                # down
        assert b.max_wait == pytest.approx(0.004)
        # quiet: the relax step is a REVERSAL (up) — hysteresis holds
        # it inside the window even though the cooldown would allow it
        c.tick(now=105.0)
        assert b.max_wait == pytest.approx(0.004)
        assert c.knobs["batch_max_wait"].suppressed >= 1
        assert c.flip_count() == 0
        c.tick(now=111.0)                # window elapsed: flip lands
        assert b.max_wait == pytest.approx(0.008)
        assert c.flip_count() == 1
    finally:
        c.disarm()


# -------------------------------------------------- degradation ladder


def test_ladder_escalates_one_rung_per_dwell_after_shed_floor():
    reg = Registry()
    b = FakeBatcher(max_queue=64)
    slo = FakeSlo()
    c = _controller(reg, batcher=b, slo=slo, ladder_dwell=2,
                    shed_floor_frac=0.125)
    c.arm()
    try:
        shed = c.knobs["shed_depth"]
        assert (shed.lo, shed.hi) == (8, 64)
        slo.set_burn(20.0, 0.5)          # fast-burn alert bound crossed
        rungs = []
        for i in range(8):
            c.tick(now=100.0 + i)
            rungs.append(c.ladder.rung)
        # tightening first: 64 -> 32 -> 16 -> 8 while rung holds at 1,
        # then one rung per dwell — never a jump to the top
        assert b.max_queue == 8
        assert rungs[0] == RUNG_TIGHTEN_SHED
        assert rungs[-1] == RUNG_FAIL_STANCE
        assert [r for i, r in enumerate(rungs)
                if i and r > rungs[i - 1] + 1] == []
    finally:
        c.disarm()


def test_ladder_deescalates_and_relaxes_shed_when_burn_clears():
    reg = Registry()
    b = FakeBatcher(max_queue=64)
    slo = FakeSlo()
    c = _controller(reg, batcher=b, slo=slo, ladder_dwell=2,
                    ladder_clear=2)
    c.arm()
    try:
        slo.set_burn(20.0, 0.5)
        for i in range(8):
            c.tick(now=100.0 + i)
        assert c.ladder.rung == RUNG_FAIL_STANCE
        slo.set_burn(0.2, 0.2)           # burn under 1.0 on both windows
        for i in range(30):
            c.tick(now=200.0 + i)
        assert c.ladder.rung == RUNG_NORMAL
        assert b.max_queue == 64         # shed relaxed back to hi
    finally:
        c.disarm()


def test_ladder_ignores_warning_zone_between_clear_and_alert():
    reg = Registry()
    b = FakeBatcher(max_queue=64)
    slo = FakeSlo()
    c = _controller(reg, batcher=b, slo=slo)
    c.arm()
    try:
        slo.set_burn(3.0, 0.8)           # elevated but under 14.4/6
        for i in range(20):
            c.tick(now=100.0 + i)
        assert c.ladder.rung == RUNG_NORMAL
        assert b.max_queue == 64
    finally:
        c.disarm()


def test_ladder_clamps_and_records_history():
    ladder = DegradationLadder()
    ladder.set(99, "clamped high")
    assert ladder.rung == RUNG_FAIL_STANCE
    ladder.set(-5, "clamped low")
    assert ladder.rung == RUNG_NORMAL
    assert ladder.set(RUNG_NORMAL, "no-op") is False
    assert ladder.transitions == 2
    assert [h["to"] for h in ladder.history] == [RUNG_FAIL_STANCE,
                                                 RUNG_NORMAL]


# ------------------------------------------------------------ fan-out


def test_fanout_scales_up_on_duty_down_on_idle():
    reg = Registry()
    eng = FakeEngines(ids=("1", "2"))
    c = _controller(reg, engines=eng, fanout_cooldown_s=0.0)
    eng._total = 2                       # one child parked already
    c.arm()
    try:
        reg.gauge_set("gatekeeper_tpu_device_duty_cycle", "h", 0.9,
                      engine="1")
        c.tick(now=100.0)
        assert eng.calls[-1] == 3        # engine-bound: unpark
        reg.gauge_set("gatekeeper_tpu_device_duty_cycle", "h", 0.01,
                      engine="1")
        c.tick(now=200.0)                # idle duty, idle edge: park
        assert eng.calls[-1] == 2
        # never below 1 / above the configured fleet
        assert all(1 <= n <= 3 for n in eng.calls)
    finally:
        c.disarm()


def test_fanout_holds_scale_down_while_edge_busy():
    reg = Registry()
    eng = FakeEngines(ids=("1", "2"))
    c = _controller(reg, engines=eng, fanout_cooldown_s=0.0)
    c.arm()
    try:
        reg.gauge_set("gatekeeper_tpu_device_duty_cycle", "h", 0.01,
                      engine="1")
        reg.gauge_set("gatekeeper_tpu_queue_depth", "h", 50,
                      queue="admission", engine="0")
        c.tick(now=100.0)                # idle engines but deep queue:
        assert eng.calls == []           # the edge still needs them
    finally:
        c.disarm()


# ------------------------------------------------------------ prewarm


def test_prewarm_fires_once_per_settled_generation():
    reg = Registry()
    gens = iter([5, 5, 5, 6, 6, 6])
    fired = []
    done = threading.Event()

    def prewarm():
        fired.append(1)
        done.set()
        return 3

    c = _controller(reg, generation=lambda: next(gens),
                    prewarm=prewarm, prewarm_cooldown_s=0.0)
    c.arm()
    try:
        c.tick(now=100.0)                # learn gen 5
        c.tick(now=101.0)                # settled: fire
        assert done.wait(5.0)
        c.tick(now=102.0)                # still settled: no refire
        done.clear()
        c.tick(now=103.0)                # gen 6 in flight: hold
        c.tick(now=104.0)                # settled again: fire
        assert done.wait(5.0)
        time.sleep(0.05)
        assert len(fired) == 2
        assert [a["knob"] for a in c.actuations()].count("prewarm") == 2
    finally:
        c.disarm()


# ------------------------------------------------- kill switch / views


def test_disarm_restores_every_knob_bit_exactly():
    reg = Registry()
    b = FakeBatcher(max_wait=0.0075, max_batch=192, max_queue=64)
    slo = FakeSlo()
    c = _controller(reg, batcher=b, slo=slo)
    baseline = dict(b.knob_values())
    c.arm()
    _seed_seals(reg, "max_wait", 10, fill=0.01)
    slo.set_burn(20.0, 0.5)
    c.tick(now=100.0)
    c.tick(now=200.0)
    assert b.knob_values() != baseline   # the controller moved knobs
    assert c.ladder.rung > RUNG_NORMAL
    c.disarm()
    assert b.knob_values() == baseline   # bit-exact values restored
    assert b.max_wait == 0.0075
    assert c.ladder.rung == RUNG_NORMAL
    restores = [a for a in c.actuations()
                if a["direction"] == "restore"]
    assert restores
    # idempotent: a second disarm is a no-op
    c.disarm()


def test_on_actuate_hook_sees_every_landed_actuation():
    reg = Registry()
    b = FakeBatcher(max_wait=0.008)
    seen = []
    c = _controller(reg, batcher=b, on_actuate=seen.append)
    c.arm()
    try:
        _seed_seals(reg, "max_wait", 10, fill=0.01)
        c.tick(now=100.0)
        assert [a.knob for a in seen] == ["batch_max_wait"]
    finally:
        c.disarm()
    assert any(a.direction == "restore" for a in seen)


def test_status_payload_shape():
    reg = Registry()
    b = FakeBatcher()
    c = _controller(reg, batcher=b)
    c.arm()
    try:
        c.tick(now=100.0)
        st = c.status()
        assert st["armed"] is True and st["ticks"] == 1
        assert set(st["knobs"]) == {"batch_max_wait",
                                    "batch_max_batch", "shed_depth"}
        assert st["ladder"]["name"] == "normal"
        assert "signals" in st and "flip_count" in st
    finally:
        c.disarm()


def test_unbounded_shed_queue_parks_the_knob():
    reg = Registry()
    b = FakeBatcher(max_queue=0)         # 0 = unbounded
    slo = FakeSlo()
    c = _controller(reg, batcher=b, slo=slo)
    c.arm()
    try:
        slo.set_burn(50.0, 50.0)
        for i in range(10):
            c.tick(now=100.0 + i)
        assert b.max_queue == 0          # no tightening of "no bound"
    finally:
        c.disarm()


# ------------------------------------- ValidationHandler ladder gates


def _policy_client():
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sneedowner"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sNeedOwner"}}},
            "targets": [{"target": TARGET, "rego": """
package k8sneedowner
violation[{"msg": "no owner label"}] {
  not input.review.object.metadata.labels.owner
}
"""}]},
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sNeedOwner", "metadata": {"name": "need-owner"},
        "spec": {}})
    return client


def _review(name, username="adaptive-test"):
    obj = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": name, "namespace": "d",
                        "labels": {"owner": "me"}}}
    request = {"uid": f"uid-{name}", "operation": "CREATE",
               "kind": {"group": "", "version": "v1", "kind": "Pod"},
               "name": name, "namespace": "d",
               "userInfo": {"username": username}, "object": obj}
    return {"apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview", "request": request}


def test_fail_stance_rung_answers_without_evaluation():
    client = _policy_client()
    ladder = DegradationLadder()
    for fail_closed, want_allowed in ((False, True), (True, False)):
        batcher = MicroBatcher(client)
        handler = ValidationHandler(client, batcher=batcher,
                                    fail_closed=fail_closed,
                                    ladder=ladder)
        try:
            ladder.set(RUNG_FAIL_STANCE, "test")
            out = handler.handle(_review("p1"))
            assert out["response"]["allowed"] is want_allowed
            assert out["response"]["status"]["code"] == 429
            # the exemption that keeps the cluster repairable survives
            # the bottom rung
            sa = handler.handle(_review("p2", username=SERVICE_ACCOUNT))
            assert sa["response"]["allowed"] is True
            assert "status" not in sa["response"] or \
                sa["response"]["status"].get("code") != 429
        finally:
            ladder.set(RUNG_NORMAL, "test")
            batcher.stop()


def test_cache_only_rung_serves_hits_sheds_misses():
    client = _policy_client()
    ladder = DegradationLadder()
    batcher = MicroBatcher(client)
    handler = ValidationHandler(client, batcher=batcher, ladder=ladder)
    try:
        warm = _review("cached-pod")
        out = handler.handle(warm)       # rung 0: evaluated + cached
        assert out["response"]["allowed"] is True
        ladder.set(RUNG_CACHE_ONLY, "test")
        hit = handler.handle(warm)       # hit still serves at speed
        assert hit["response"]["allowed"] is True
        assert (hit["response"].get("status") or {}).get("code") != 429
        miss = handler.handle(_review("never-seen"))
        assert miss["response"]["status"]["code"] == 429
    finally:
        ladder.set(RUNG_NORMAL, "test")
        batcher.stop()


def test_cache_only_rung_sheds_when_cache_disabled():
    client = _policy_client()
    ladder = DegradationLadder()
    batcher = MicroBatcher(client)
    handler = ValidationHandler(client, batcher=batcher, ladder=ladder,
                                decision_cache_size=0)
    try:
        ladder.set(RUNG_CACHE_ONLY, "test")
        out = handler.handle(_review("p1"))
        assert out["response"]["status"]["code"] == 429
    finally:
        ladder.set(RUNG_NORMAL, "test")
        batcher.stop()


# ------------------------------------------- live MicroBatcher knobs


def test_microbatcher_set_knobs_live_and_floored():
    client = _policy_client()
    b = MicroBatcher(client, max_wait=0.005, max_batch=256,
                     max_queue=64)
    try:
        out = b.set_knobs(max_wait=0.001, max_batch=512, max_queue=32)
        assert out == {"max_wait": 0.001, "max_batch": 512,
                       "max_queue": 32}
        assert b.knob_values() == out
        # garbage replication frames clamp at the sanity floors
        out = b.set_knobs(max_wait=-1.0, max_batch=0, max_queue=-5)
        assert out == {"max_wait": 0.0, "max_batch": 1, "max_queue": 0}
        # a retuned batcher still serves
        res = b.submit({"object": {"apiVersion": "v1", "kind": "Pod",
                                   "metadata": {"name": "x",
                                                "labels":
                                                    {"owner": "me"}}}},
                       timeout=10.0)
        assert res == []
    finally:
        b.stop()


# ------------------------------------------ EngineSupervisor fan-out


def test_engine_supervisor_scale_clamps_and_tracks_desired():
    sup = EngineSupervisor([1, 2, 3], lambda k: f"/tmp/na-{k}.sock")
    assert sup.active_total() == 4
    assert sup.scale_to(99) == 4         # hard ceiling: configured fleet
    assert sup.scale_to(0) == 1          # engine 0 never parks
    assert sup.active_total() == 1
    assert sup.scale_to(2) == 2
    assert sup._active_ids() == {1}      # prefix of the configured list
    sup.set_knobs({"max_wait": 0.002})
    assert sup._knobs_gen == 1
    sup.set_knobs({"max_wait": 0.004})
    assert sup._knobs_gen == 2


# -------------------------------------------------- metric hygiene


def test_adaptive_metric_labels_fold_unknowns():
    metrics.report_adaptive_actuation("bogus_knob", "sideways")
    snap = metrics.REGISTRY.snapshot(
        ("gatekeeper_tpu_adaptive_actuations_total",))
    ent = snap["gatekeeper_tpu_adaptive_actuations_total"]
    folded = [tuple(k) for k, _ in ent["values"]
              if "other" in tuple(k)]
    assert (("other", "other") in folded
            or ("other",) in [f for f in folded])
    metrics.report_degradation_rung(99)  # clamps to the top rung
    snap = metrics.REGISTRY.snapshot(
        ("gatekeeper_tpu_degradation_rung",))
    vals = snap["gatekeeper_tpu_degradation_rung"]["values"]
    assert vals and vals[0][1] == 3.0
    metrics.report_degradation_rung(0)
