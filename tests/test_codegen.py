"""Differential conformance for the codegen materializer (rego/codegen.py).

The generated Python evaluators must be bit-identical to the reference
interpreter wherever compilation succeeds — they share its value model and
builtins, so any divergence is a codegen bug. Tier-1 analog of the
reference's opa-test discipline (SURVEY.md §4), run over the same harvested
corpus the device-filter conformance uses.
"""

from __future__ import annotations

import glob
from pathlib import Path

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.rego.codegen import Unsupported, compile_module
from gatekeeper_tpu.rego.interp import Interpreter, RegoError, UNDEF
from gatekeeper_tpu.rego.parser import parse_module
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.values import freeze, thaw

from .conftest import REFERENCE, requires_reference
from .test_ir_corpus import LIB_DIRS, harvest_cases

TARGET = "admission.k8s.gatekeeper.sh"


@requires_reference
@pytest.mark.parametrize("dirpath", LIB_DIRS)
def test_codegen_matches_interpreter_on_reference_corpus(dirpath):
    src = (REFERENCE / dirpath / "src.rego").read_text()
    test_src = (REFERENCE / dirpath / "src_test.rego").read_text()
    module = parse_module(src)
    fn = compile_module(module)  # all 23 library templates must compile
    cases = harvest_cases(src, test_src)
    assert cases
    interp = Interpreter({"m": module})
    checked = fired = 0
    for doc, inv in cases:
        inv = inv if inv is not None else {}
        a = fn.__input_call__(freeze(doc), freeze(inv))
        b = interp.eval_rule(module.package, "violation", doc,
                             overrides={("inventory",): inv})
        assert a == b, f"{dirpath}: codegen diverged\n cg: {thaw(a)!r}\n" \
                       f" in: {thaw(b) if b is not UNDEF else UNDEF!r}"
        checked += 1
        if b is not UNDEF and len(b):
            fired += 1
    assert checked > 0 and fired > 0, f"{dirpath}: corpus vacuous"


@requires_reference
def test_driver_uses_codegen_for_library_template():
    """The wiring, not just the compiler: RegoDriver must route violation
    materialization through the generated evaluator."""
    src = (REFERENCE / "library/general/requiredlabels/src.rego").read_text()
    d = RegoDriver()
    client = Backend(d).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8srequiredlabels"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sRequiredLabels"}}},
            "targets": [{"target": TARGET, "rego": src}],
        },
    })
    assert d._codegen_for(TARGET, "K8sRequiredLabels") is not None
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "c"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}},
    })
    client.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "x"}})
    msgs = [r.msg for r in client.audit().results()]
    assert msgs and "owner" in msgs[0]


@requires_reference
def test_codegen_runtime_failure_falls_back_loudly(caplog):
    """A generated evaluator that crashes must log, permanently disable
    itself for the kind, and still answer via the interpreter."""
    import logging

    src = (REFERENCE / "library/general/requiredlabels/src.rego").read_text()
    d = RegoDriver()
    client = Backend(d).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8srequiredlabels"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sRequiredLabels"}}},
            "targets": [{"target": TARGET, "rego": src}],
        },
    })

    def boom(_inp, _inv):
        raise IndexError("synthetic codegen bug")

    d._codegen[(TARGET, "K8sRequiredLabels")] = boom
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels", "metadata": {"name": "c"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}},
    })
    client.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "x"}})
    with caplog.at_level(logging.WARNING, "gatekeeper_tpu.client.drivers"):
        msgs = [r.msg for r in client.audit().results()]
    assert msgs and "owner" in msgs[0]
    assert any("falling back" in r.message for r in caplog.records)
    assert d._codegen[(TARGET, "K8sRequiredLabels")] is None


# ------------------------------------------------ focused semantics units


def _fn(src: str):
    return compile_module(parse_module(src))


def _run(src: str, inp, inv=None):
    module = parse_module(src)
    fn = compile_module(module)
    a = fn.__input_call__(freeze(inp), freeze(inv if inv is not None else {}))
    interp = Interpreter({"m": module})
    b = interp.eval_rule(module.package, "violation", inp,
                         overrides={("inventory",): inv}
                         if inv is not None else None)
    assert a == b, f"cg {thaw(a)!r} != in {b!r}"
    return a


def test_negation_scoping_and_wildcards():
    out = _run("""
package t
violation[{"msg": "m"}] {
  not input.review.object.spec.ok
  input.review.object.spec.items[_] == "x"
}
""", {"review": {"object": {"spec": {"items": ["y", "x"]}}}})
    assert len(out) == 1


def test_function_multiple_defs_and_undefined_args():
    out = _run("""
package t
mode(x) = "big" { x > 10 }
mode(x) = "small" { x <= 10 }
violation[{"msg": m}] {
  m := mode(input.review.object.n)
}
""", {"review": {"object": {"n": 3}}})
    assert thaw(out) == [{"msg": "small"}]
    # undefined arg -> undefined call -> no violation
    out = _run("""
package t
mode(x) = "big" { x > 10 }
violation[{"msg": m}] { m := mode(input.review.object.missing) }
""", {"review": {"object": {}}})
    assert out == frozenset()


def test_complete_rule_default_and_conflict():
    out = _run("""
package t
default level = "none"
level = "high" { input.review.object.x > 5 }
violation[{"msg": level}] { level != "none" }
""", {"review": {"object": {"x": 9}}})
    assert thaw(out) == [{"msg": "high"}]
    src = """
package t
both = "a" { input.review.object.x > 0 }
both = "b" { input.review.object.x > 1 }
violation[{"msg": both}] { true }
"""
    fn = _fn(src)
    with pytest.raises(RegoError):
        fn.__input_call__(freeze({"review": {"object": {"x": 2}}}), freeze({}))


def test_partial_object_rule():
    out = _run("""
package t
sizes[name] = n {
  c := input.review.object.spec.containers[_]
  name := c.name
  n := c.n
}
violation[{"msg": name}] {
  sizes[name] > 2
}
""", {"review": {"object": {"spec": {"containers": [
        {"name": "a", "n": 1}, {"name": "b", "n": 5}]}}}})
    assert thaw(out) == [{"msg": "b"}]


def test_object_comprehension_and_set_ops():
    out = _run("""
package t
violation[{"msg": msg, "details": d}] {
  provided := {l | input.review.object.metadata.labels[l]}
  required := {l | l := input.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  d := {k: true | k := missing[_]}
  msg := sprintf("missing: %v", [missing])
}
""", {"review": {"object": {"metadata": {"labels": {"a": "1"}}}},
      "parameters": {"labels": ["a", "b"]}})
    assert len(out) == 1


def test_inventory_access():
    out = _run("""
package t
violation[{"msg": h}] {
  other := data.inventory.cluster["v1"]["Svc"][name]
  h := other.host
  h == input.review.object.host
}
""", {"review": {"object": {"host": "x.example"}}},
        inv={"cluster": {"v1": {"Svc": {"s1": {"host": "x.example"},
                                        "s2": {"host": "y.example"}}}}})
    assert thaw(out) == [{"msg": "x.example"}]


def test_with_modifier_is_unsupported():
    with pytest.raises(Unsupported):
        _fn("""
package t
helper = x { x := input.a }
violation[{"msg": "m"}] { helper with input as {"a": 1} }
""")


def test_array_destructure_and_arith():
    out = _run("""
package t
violation[{"msg": msg}] {
  [cpu, mem] := input.review.object.pair
  total := cpu + mem * 2
  total > 10
  msg := sprintf("%v", [total])
}
""", {"review": {"object": {"pair": [3, 4]}}})
    assert thaw(out) == [{"msg": "11"}]


def test_template_update_invalidates_review_memo():
    """Updating a template must drop the per-review comprehension memo:
    the recompiled evaluator's memo slots are numbered for the NEW module
    (r3 code-review finding, confirmed stale-result repro)."""
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    def tmpl(rego):
        return {
            "apiVersion": "templates.gatekeeper.sh/v1beta1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8smemo"},
            "spec": {
                "crd": {"spec": {"names": {"kind": "K8sMemo"}}},
                "targets": [{"target": "admission.k8s.gatekeeper.sh",
                             "rego": rego}],
            },
        }

    v1 = tmpl("""
package k8smemo
violation[{"msg": msg}] {
  ls := {l | input.review.object.metadata.labels[l]}
  count(ls) > 0
  msg := sprintf("labels: %v", [ls])
}
""")
    v2 = tmpl("""
package k8smemo
violation[{"msg": msg}] {
  ans := {a | input.review.object.metadata.annotations[a]}
  count(ans) > 0
  msg := sprintf("annotations: %v", [ans])
}
""")
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template(v1)
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sMemo", "metadata": {"name": "c"}, "spec": {}})
    client.add_data({"apiVersion": "v1", "kind": "Namespace",
                     "metadata": {"name": "n", "labels": {"x": "y"}}})
    assert [r.msg for r in client.audit().results()] == ['labels: {"x"}']
    client.add_template(v2)  # same data revision; review identity reused
    assert client.audit().results() == []  # no annotations -> no violation


def test_arg_pure_fn_memo_invalidates_with_inventory():
    """Arg-pure function results memoize per frozen-inventory lifetime;
    an inventory change must produce fresh results, and input-reading
    functions must never be memoized across constraints."""
    from gatekeeper_tpu.client import Backend, RegoDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    client.add_template({
        "apiVersion": "templates.gatekeeper.sh/v1beta1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8sdupsel"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sDupSel"}}},
            "targets": [{"target": "admission.k8s.gatekeeper.sh",
                         "rego": """
package k8sdupsel

flat(svc) = out {
  pairs := [p | v := svc.spec.selector[k]; p := concat(":", [k, v])]
  out := concat(",", sort(pairs))
}

violation[{"msg": msg}] {
  input.review.kind.kind == "Service"
  mine := flat(input.review.object)
  other := data.inventory.namespace[ns][_][_][name]
  other.metadata.name != input.review.object.metadata.name
  theirs := flat(other)
  theirs == mine
  msg := sprintf("dup of %v", [name])
}
"""}],
        },
    })
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sDupSel", "metadata": {"name": "c"}, "spec": {}})

    def svc(name, sel):
        return {"apiVersion": "v1", "kind": "Service",
                "metadata": {"name": name, "namespace": "d"},
                "spec": {"selector": sel}}

    client.add_data(svc("a", {"app": "x"}))
    client.add_data(svc("b", {"app": "x"}))
    client.add_data(svc("c", {"app": "y"}))
    msgs = sorted(r.msg for r in client.audit().results())
    assert msgs == ["dup of a", "dup of b"]
    # inventory change: service c now collides too — stale memo entries
    # must not hide it
    client.add_data(svc("c", {"app": "x"}))
    msgs = sorted(r.msg for r in client.audit().results())
    assert msgs == ["dup of a", "dup of a", "dup of b", "dup of b",
                    "dup of c", "dup of c"]


def test_join_hint_pin_cannot_raise_before_enumeration():
    """Regression (advisor r4): the join-reorder hint evaluated the pin
    expression BEFORE the enumeration. If the pin called a user function
    that errors (complete-rule multi-output conflict), the compiled
    evaluator raised where the interpreter — evaluating the empty
    enumeration first — simply produced nothing. Error-prone pins are
    now excluded from hinting."""
    src = '''
package hintbug

boom(x) = y { y := 1 }
boom(x) = y { y := 2 }

violation[{"msg": "hit"}] {
  v := input.review.object.items[k]
  k == boom(input.review.object.pin)
  v == "x"
}
'''
    from gatekeeper_tpu.rego.codegen import compile_module
    from gatekeeper_tpu.rego.interp import UNDEF, Interpreter
    from gatekeeper_tpu.rego.parser import parse_module
    from gatekeeper_tpu.utils.values import freeze

    module = parse_module(src)
    interp = Interpreter({"m": module})
    fn = compile_module(module, entry="violation")
    # empty enumeration: the interpreter yields nothing; the compiled
    # evaluator must NOT raise through the hoisted pin
    empty = {"review": {"object": {"pin": "p"}}}
    want = interp.eval_rule(("hintbug",), "violation", empty)
    got = fn.__input_call__(freeze(empty), freeze({}))
    assert want is UNDEF or not want
    assert got == want or (got in (UNDEF, frozenset()) and
                           want in (UNDEF, frozenset()))
    # non-empty enumeration: both paths surface the conflict identically
    loaded = {"review": {"object": {"items": {"a": "x"}, "pin": "p"}}}
    try:
        want2 = interp.eval_rule(("hintbug",), "violation", loaded)
        want_raised = False
    except Exception:
        want_raised = True
    try:
        got2 = fn.__input_call__(freeze(loaded), freeze({}))
        got_raised = False
    except Exception:
        got_raised = True
    assert want_raised == got_raised
    if not want_raised:
        assert got2 == want2
