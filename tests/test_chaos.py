"""Chaos orchestrator, gray-failure liveness, crash consistency
(ISSUE 19 tentpole).

Covers:
  * backplane frame hygiene: a truncated frame and a corrupted
    (oversized-length) header each drop ONLY that connection — clean
    close, re-handshake, the engine keeps serving;
  * wire-fault injection modes (reset / truncate / slow) through the
    `backplane.wire` point;
  * schedule determinism: one integer seed fully determines the fault
    schedule (kinds, targets, offsets, params);
  * gray-failure liveness: a SIGSTOP'd engine child mid-burst is
    detected by the poll-age heartbeat, SIGKILLed, respawned, and the
    plane answers every request meanwhile (failover, zero unanswered);
    a SIGSTOP'd audit shard mid-sweep heals the same way and the
    re-swept round stays bit-equal;
  * crash-loop backoff: jittered exponential delays, first-death-free,
    healthy-uptime reset, breaker trip, gauge teardown on close;
  * the crash-consistency verifier's own checks (stance contract,
    fencing, stale gauges) and the /debug/chaos ledger provider;
  * utils/faults armed()/fired snapshots.
"""

from __future__ import annotations

import http.client
import json
import signal
import socket
import struct
import threading
import time

import pytest

from gatekeeper_tpu.client import Backend, RegoDriver
from gatekeeper_tpu.control import chaos
from gatekeeper_tpu.control import metrics as gm
from gatekeeper_tpu.control.backplane import (
    MAX_FRAME_LEN,
    BackplaneClient,
    BackplaneEngine,
    BackplaneError,
)
from gatekeeper_tpu.control.liveness import Backoff
from gatekeeper_tpu.control.webhook import MicroBatcher, ValidationHandler
from gatekeeper_tpu.target import K8sValidationTarget
from gatekeeper_tpu.utils.faults import FAULTS

PER_TEST_TIMEOUT_S = 180


@pytest.fixture(autouse=True)
def _hard_timeout_and_clean_faults():
    def boom(signum, frame):  # pragma: no cover - only on a real hang
        raise TimeoutError(
            f"test exceeded the {PER_TEST_TIMEOUT_S}s hard timeout")

    old = signal.signal(signal.SIGALRM, boom)
    signal.alarm(PER_TEST_TIMEOUT_S)
    FAULTS.reset()
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
        FAULTS.reset()


def _review(uid: str) -> bytes:
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": uid, "operation": "CREATE",
            "kind": {"group": "", "version": "v1", "kind": "Pod"},
            "object": {"apiVersion": "v1", "kind": "Pod",
                       "metadata": {"name": f"p-{uid}",
                                    "namespace": "default",
                                    "labels": {"owner": "t"}}}},
    }).encode()


def _engine(tmp_path, name="e"):
    client = Backend(RegoDriver()).new_client([K8sValidationTarget()])
    validation = ValidationHandler(
        client, kube=None, batcher=MicroBatcher(client, max_wait=0.001))
    sock = str(tmp_path / f"{name}.sock")
    eng = BackplaneEngine(sock, validation=validation)
    eng.start()
    return eng, sock


def _admit(client, uid, timeout=5.0):
    return client.call("/v1/admit", _review(uid), timeout,
                       time.monotonic() + timeout)


# --------------------------------------------------------- frame hygiene


def test_truncated_frame_drops_connection_then_rehandshakes(tmp_path):
    eng, sock = _engine(tmp_path)
    try:
        client = BackplaneClient(sock, worker_id="t1")
        status, body = _admit(client, "a")
        assert status == 200
        # next Q frame is cut mid-payload and the socket closed: the
        # engine must treat it as a dead peer (no partial parse), and
        # the CLIENT must re-handshake on the next call
        FAULTS.inject("backplane.wire", mode="truncate", count=1)
        with pytest.raises(BackplaneError):
            _admit(client, "b")
        status, body = _admit(client, "c")
        assert status == 200
        assert json.loads(bytes(body))["response"]["uid"] == "c"
        client.close()
    finally:
        eng.stop(drain_timeout=1.0)


def test_corrupt_oversized_header_closes_only_that_connection(tmp_path):
    eng, sock = _engine(tmp_path)
    try:
        healthy = BackplaneClient(sock, worker_id="ok")
        assert _admit(healthy, "h1")[0] == 200
        # raw connection speaking garbage: a length claiming 2 GiB
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.connect(sock)
        raw.sendall(struct.pack(">I", 0x7FFFFFFF) + b"junk")
        # the engine must close THIS connection (bounded read, no 2 GiB
        # allocation), visible as EOF or RST on the raw socket...
        raw.settimeout(5)
        try:
            assert raw.recv(1) == b""
        except ConnectionResetError:
            pass  # closing with unread bytes queued sends RST; same verdict
        raw.close()
        # ...while the healthy client keeps its session
        assert _admit(healthy, "h2")[0] == 200
        healthy.close()
    finally:
        eng.stop(drain_timeout=1.0)


def test_wire_fault_modes(tmp_path):
    eng, sock = _engine(tmp_path)
    try:
        client = BackplaneClient(sock, worker_id="w")
        # reset: hard RST mid-frame; the call fails, the next one
        # reconnects
        FAULTS.inject("backplane.wire", mode="reset", count=1)
        with pytest.raises(BackplaneError):
            _admit(client, "r")
        assert _admit(client, "r2")[0] == 200
        # slow: the frame drips but COMPLETES — no error, just latency
        FAULTS.inject("backplane.wire", mode="slow", param="0.001",
                      count=1)
        status, body = _admit(client, "s", timeout=10)
        assert status == 200
        assert json.loads(bytes(body))["response"]["uid"] == "s"
        client.close()
    finally:
        eng.stop(drain_timeout=1.0)
    assert MAX_FRAME_LEN >= 64 * 1024 * 1024  # rings fit under the cap


# -------------------------------------------------- schedule determinism


def test_schedule_deterministic_from_seed():
    a = chaos.ChaosSchedule.generate(1234, n_actions=16, horizon_s=30)
    b = chaos.ChaosSchedule.generate(1234, n_actions=16, horizon_s=30)
    assert a.to_dict() == b.to_dict(), \
        "one seed must yield one schedule, bit for bit"
    c = chaos.ChaosSchedule.generate(1235, n_actions=16, horizon_s=30)
    assert a.to_dict() != c.to_dict()
    # offsets sorted, targets bounded, kinds drawn from the surface
    ts = [act.t for act in a.actions]
    assert ts == sorted(ts)
    assert all(0 <= act.target < 4 for act in a.actions)
    assert all(act.kind in chaos.SURFACE for act in a.actions)


def test_orchestrator_records_skips_on_partial_plane():
    sched = chaos.ChaosSchedule(
        0, [chaos.FaultAction(t=0.0, kind="engine.kill"),
            chaos.FaultAction(t=0.0, kind="backplane.error")])
    orch = chaos.ChaosOrchestrator(chaos.PlaneHandles(), sched)
    ledger = orch.run()
    assert ledger[0]["detail"] == {"skipped": "no live engine child"}
    assert ledger[1]["detail"]["armed"] == "backplane.engine:error"
    assert FAULTS.armed_snapshot()  # the armed fault is visible...
    snap = chaos.debug_snapshot()
    assert snap["seed"] == 0 and len(snap["ledger"]) == 2
    FAULTS.reset()


# ------------------------------------------------------ crash-loop backoff


def _breaker_value(supervisor: str) -> float:
    series = gm.gauge_series("gatekeeper_tpu_crashloop_breaker")
    return series.get((supervisor,), 0.0)


def test_backoff_exponential_jittered_and_capped():
    b = Backoff("frontend", base=0.25, factor=2.0, cap=4.0,
                healthy_after=30.0, trip_after=5)
    delays = [b.delay_for(0, uptime_s=0.1) for _ in range(7)]
    assert delays[0] == 0.0, "first death respawns immediately"
    for i, lo_mult in enumerate([1, 2, 4, 8], start=1):
        lo = min(4.0, 0.25 * lo_mult) * 0.5
        hi = min(4.0, 0.25 * lo_mult * 1.5)
        assert lo <= delays[i] <= hi, (i, delays)
    assert delays[6] <= 4.0, "cap must bound the backoff"
    assert b.pending(0)
    assert _breaker_value("frontend") == 1.0, \
        "5 fast deaths must trip the breaker"
    # healthy uptime resets the slot: breaker clears, next death free
    b.note_healthy(0)
    assert _breaker_value("frontend") == 0.0
    assert b.delay_for(0, uptime_s=31.0) == 0.0
    b.close()
    assert all(v == 0.0 for v in gm.gauge_series(
        "gatekeeper_tpu_respawn_backoff_seconds").values())
    assert all(v == 0.0 for v in gm.gauge_series(
        "gatekeeper_tpu_crashloop_breaker").values())


def test_backoff_long_uptime_resets_count():
    b = Backoff("engine", base=0.25, healthy_after=10.0)
    assert b.delay_for(1, uptime_s=0.0) == 0.0
    assert b.delay_for(1, uptime_s=0.0) > 0.0
    # a child that ran healthy past the threshold starts over
    assert b.delay_for(1, uptime_s=11.0) == 0.0
    b.close()


# ------------------------------------------------- gray failure: engine


def test_sigstop_engine_mid_burst_fails_over_and_recovers():
    """SIGSTOP (not SIGKILL) an engine child mid-burst: the process is
    alive but silent — only the poll-age heartbeat can see it. The
    frontends must fail over (every request still answered), and the
    supervisor must SIGKILL + respawn the wedged child without operator
    action, recording a wedge recovery."""
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook", "--admission-workers", "2",
        "--admission-engines", "2"])
    rt = Runtime(args)
    rt.args.metrics_backend = "none"
    rt.start()
    try:
        rt.engines.heartbeat_deadline_s = 3.0
        deadline = time.monotonic() + 30
        while rt.backplane.connected < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        victim = rt.engines._procs[1]
        assert victim is not None

        answered, errors = {}, []

        def burst(k):
            conn = http.client.HTTPConnection(
                "127.0.0.1", rt.frontends.port, timeout=15)
            for i in range(20):
                uid = f"b{k}-{i}"
                try:
                    conn.request("POST", "/v1/admit?timeout=8s",
                                 _review(uid),
                                 {"Content-Type": "application/json"})
                    resp = conn.getresponse()
                    body = json.loads(resp.read())
                    answered[uid] = (resp.status, body)
                except Exception as e:  # pragma: no cover - fail below
                    errors.append((uid, repr(e)))
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", rt.frontends.port, timeout=15)
                time.sleep(0.02)
            conn.close()

        threads = [threading.Thread(target=burst, args=(k,),
                                    daemon=True) for k in range(2)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        rt.engines.pause_engine(1)  # gray failure, mid-burst
        for t in threads:
            t.join(60)

        assert not errors, errors
        assert len(answered) == 40, "zero unanswered during failover"
        for uid, (status, body) in answered.items():
            assert status == 200
            assert body["response"]["uid"] == uid
            assert body["response"]["allowed"] is True

        # detected by the heartbeat deadline, killed, respawned
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            cur = rt.engines._procs.get(1)
            if cur is not None and cur is not victim \
                    and rt.engines.alive_count() == 1:
                break
            time.sleep(0.2)
        assert rt.engines._procs[1] is not victim, \
            "wedged engine must be killed and respawned"
        assert victim.poll() is not None, "the paused child must be dead"
        text = gm.REGISTRY.render()
        assert 'gatekeeper_tpu_fault_recovery_seconds_count' \
               '{component="engine",fault="wedge"}' in text
    finally:
        rt.stop()


# -------------------------------------------- gray failure: audit shard


def test_sigstop_audit_shard_mid_sweep_converges_bit_equal(tmp_path):
    """SIGSTOP shard 1 while its slice sweep is in flight: the sweep
    Q-frame stalls, the heartbeat trips, the supervisor SIGKILLs and
    respawns the shard, the resync rebuilds only ITS slice (generation
    bump on the victim only), the leader re-dispatches the orphaned
    partition — and the composed round is still bit-equal."""
    from tools.chaos_verify import (_cluster_kube, _cluster_objects,
                                    _library, _result_key)
    from gatekeeper_tpu.client import Backend
    from gatekeeper_tpu.control.audit import (AuditManager,
                                              ShardedAuditPlane)
    from gatekeeper_tpu.control.backplane import AuditShardSupervisor
    from gatekeeper_tpu.ir import TpuDriver
    from gatekeeper_tpu.target import K8sValidationTarget

    objs = _cluster_objects()
    okube = _cluster_kube(objs)
    oracle_client = Backend(TpuDriver()).new_client(
        [K8sValidationTarget()])
    _library(oracle_client)
    oracle = AuditManager(okube, oracle_client, interval=3600,
                          incremental=True)
    oracle_results = [_result_key(r) for r in oracle.audit_once()]
    assert oracle_results

    kube = _cluster_kube(objs)
    leader = Backend(TpuDriver()).new_client([K8sValidationTarget()])
    sock = str(tmp_path / "audit.sock")
    plane_box = []
    sup = AuditShardSupervisor(
        2, socket_for=lambda k: f"{sock}.{k}",
        spawn_args=["--log-level", "WARNING"],
        snapshot_provider=lambda k: plane_box[0].sync_snapshot(k),
        heartbeat_deadline_s=3.0)
    plane = ShardedAuditPlane(kube, leader, sup, 2)
    plane_box.append(plane)
    plane.attach()
    _library(leader)
    mgr = AuditManager(kube, leader, interval=3600, shard_plane=plane)
    sup.start()
    try:
        assert [_result_key(r) for r in mgr.audit_once()] == \
            oracle_results
        gen_before = dict(sup.generation)

        pauser = threading.Timer(0.05, lambda: sup.pause_engine(1))
        pauser.start()
        round2 = [_result_key(r) for r in mgr.audit_once()]
        pauser.join()
        assert round2 == oracle_results, \
            "mid-sweep SIGSTOP round must converge bit-equal"
        # the wedge respawn is asynchronous: the leader re-sweeps the
        # orphaned slice without waiting for the supervisor, so the
        # generation bump may land after the round has already converged
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if sup.generation[1] > gen_before[1] and sup.alive_count() == 2:
                break
            time.sleep(0.2)
        # only the victim's slice was rebuilt and re-swept
        assert sup.generation[1] > gen_before[1], \
            "the wedged shard must have been respawned + resynced"
        assert sup.generation[0] == gen_before[0], \
            "the healthy shard must NOT have been resynced"
        assert sup.alive_count() == 2
        text = gm.REGISTRY.render()
        assert 'gatekeeper_tpu_fault_recovery_seconds_count' \
               '{component="audit_shard",fault="wedge"}' in text
    finally:
        sup.stop()
        plane.stop()


# ----------------------------------------------------------- verifier


def test_verifier_stance_contract():
    v = chaos.Verifier()
    ok = {"u1": (200, {"response": {"uid": "u1", "allowed": True}}),
          # fail-open stance answer: allowed, engine unreachable
          "u2": (200, {"response": {"uid": "u2", "allowed": True,
                                    "status": {"code": 503}}})}
    assert v.check_admissions(2, ok, [], fail_closed=False).ok
    bad = {
        # stance answer contradicting fail_closed=False
        "u3": (200, {"response": {"uid": "u3", "allowed": False,
                                  "status": {"code": 503}}}),
        # internal NOT_READY leaked to HTTP
        "u4": (200, {"response": {"uid": "u4", "allowed": True,
                                  "status": {"code": 599}}}),
        # envelope uid mismatch
        "u5": (200, {"response": {"uid": "other", "allowed": True}}),
    }
    r = v.check_admissions(4, bad, [("u6", "conn reset")],
                           fail_closed=False)
    assert len(r.violations) == 4  # 3 contract breaks + 1 unanswered


def test_verifier_fencing_and_stale_gauges():
    v = chaos.Verifier()
    writes = [(1.0, "a", "a"), (2.0, "a", "thief"), (3.0, "b", "a")]
    r = v.check_fencing(writes, writers={"a", "b"})
    # the thief window is recorded but only the cross-candidate write
    # violates
    assert r.detail["holder_mismatches"] == 2
    assert len(r.violations) == 1 and "'b'" in r.violations[0]
    # stale-gauge check: a non-zero lifecycle series must be caught
    gm.report_respawn_backoff("frontend", 1.25)
    r2 = v.check_stale_gauges()
    assert any("respawn_backoff" in s for s in r2.violations)
    gm.report_respawn_backoff("frontend", 0.0)
    v2 = chaos.Verifier()
    assert v2.check_stale_gauges().ok
    # the family list is shared with gklint's static checker at runtime
    names = chaos.lifecycle_gauge_names()
    assert "gatekeeper_tpu_respawn_backoff_seconds" in names
    assert "gatekeeper_tpu_crashloop_breaker" in names


def test_faults_armed_and_fired_snapshots():
    FAULTS.reset()
    assert FAULTS.armed_snapshot() == {}
    FAULTS.inject("backplane.engine", mode="error", count=2)
    FAULTS.inject("kube.write", mode="error", param="503", rate=0.5)
    snap = FAULTS.armed_snapshot()
    assert snap["backplane.engine"]["mode"] == "error"
    assert snap["backplane.engine"]["count"] == 2
    assert snap["kube.write"]["param"] == "503"
    assert snap["kube.write"]["rate"] == 0.5
    assert FAULTS.consume("backplane.engine") is not None
    assert FAULTS.fired_snapshot() == {"backplane.engine": 1}
    FAULTS.reset()
    assert FAULTS.armed_snapshot() == {} and FAULTS.fired_snapshot() == {}


def test_debug_chaos_provider_wired():
    from gatekeeper_tpu.control.main import Runtime, build_parser

    args = build_parser().parse_args([
        "--fake-kube", "--port", "0", "--prometheus-port", "0",
        "--disable-cert-rotation", "--health-addr", ":0",
        "--operation", "webhook"])
    rt = Runtime(args)  # not started: providers are wired at build time
    providers = rt.debug_providers()
    snap = providers["chaos"]("")
    assert set(snap) == {"seed", "schedule", "ledger", "faults"}
    assert set(snap["faults"]) == {"armed", "fired"}
