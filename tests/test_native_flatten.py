"""Native flattener conformance: the C extractor must produce
bit-identical feature tensors AND identical intern-id assignment order
to the Python reference — across scalar/entries/count slots, nested
axes, numeric keys, bucket overflow, and absent paths."""

import numpy as np
import pytest

from gatekeeper_tpu.client import Backend
from gatekeeper_tpu.ir import TpuDriver
from gatekeeper_tpu.ir.features import Extractor, extract_batch
from gatekeeper_tpu.native import flatten_ext
from gatekeeper_tpu.ops.strtab import StringTable
from gatekeeper_tpu.target import K8sValidationTarget

pytestmark = pytest.mark.skipif(flatten_ext() is None,
                                reason="no C compiler for the native path")

TEMPLATE = {
    "apiVersion": "templates.gatekeeper.sh/v1beta1",
    "kind": "ConstraintTemplate",
    "metadata": {"name": "k8sfeat"},
    "spec": {
        "crd": {"spec": {"names": {"kind": "K8sFeat"}}},
        "targets": [{"target": "admission.k8s.gatekeeper.sh", "rego": """
package k8sfeat
violation[{"msg": "m"}] {
  c := input.review.object.spec.containers[_]
  startswith(c.image, "bad/")
}
violation[{"msg": "labels"}] {
  input.review.object.metadata.labels[k] == "no"
}
violation[{"msg": "count"}] {
  count(input.review.object.spec.volumes) > 3
}
violation[{"msg": "ports"}] {
  c := input.review.object.spec.containers[_]
  c.ports[_].hostPort > 100
}
"""}],
    },
}


def reviews_fixture():
    objs = [
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "p0", "namespace": "d",
                      "labels": {"a": "yes", "b": "no", "n": 7}},
         "spec": {"containers": [
             {"name": "c1", "image": "bad/x",
              "ports": [{"hostPort": 80}, {"hostPort": 8080}]},
             {"name": "c2", "image": "ok/y", "ports": []},
         ], "volumes": [{"name": f"v{i}"} for i in range(5)]}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "p1", "namespace": "d"},
         "spec": {"containers": [], "volumes": "notalist"}},
        {"apiVersion": "v1", "kind": "Pod",
         "metadata": {"name": "p2", "namespace": "d", "labels": {}},
         "spec": {"containers": [
             {"name": "x", "image": True,
              "ports": [{"hostPort": 3.5}, {"hostPort": None}]}]}},
        {"apiVersion": "v1", "kind": "Pod", "metadata": {"name": "p3"},
         "spec": None},
    ]
    return [{"kind": {"group": "", "version": "v1", "kind": "Pod"},
             "name": o["metadata"]["name"], "object": o} for o in objs]


def program():
    d = TpuDriver()
    Backend(d).new_client([K8sValidationTarget()]).add_template(TEMPLATE)
    prog = d._programs["K8sFeat"]
    assert prog is not None
    return prog


def extract_with(native: bool):
    prog = program()
    table = StringTable()
    ex = Extractor(prog, table, native=native)
    reviews = reviews_fixture()
    sizes = ex.axis_sizes(reviews)
    from gatekeeper_tpu.ir.features import _bucket

    buckets = {a: _bucket(s) for a, s in sizes.items()}
    feats = ex.extract(reviews, 4, buckets)
    return feats, table, sizes


def test_native_matches_python_exactly():
    f_py, t_py, s_py = extract_with(native=False)
    f_c, t_c, s_c = extract_with(native=True)
    assert s_py == s_c
    # identical intern tables, including assignment ORDER
    assert t_py._strs == t_c._strs
    assert f_py.keys() == f_c.keys()
    for slot in f_py:
        for name in f_py[slot]:
            a, b = f_py[slot][name], f_c[slot][name]
            if a.dtype == np.float32:
                assert ((a == b) | (np.isnan(a) & np.isnan(b))).all(), \
                    (slot, name)
            else:
                assert (a == b).all(), (slot, name)


def test_native_end_to_end_audit_parity():
    """Full audit through the TpuDriver must agree with the native
    extractor disabled (same firing pairs, same messages)."""
    import os

    def run(disable: bool):
        if disable:
            os.environ["GATEKEEPER_TPU_NATIVE"] = "0"
        try:
            import gatekeeper_tpu.native as nat

            nat._tried = False
            nat._flatten = None
            d = TpuDriver()
            c = Backend(d).new_client([K8sValidationTarget()])
            c.add_template(TEMPLATE)
            c.add_constraint({
                "apiVersion": "constraints.gatekeeper.sh/v1beta1",
                "kind": "K8sFeat", "metadata": {"name": "c"}, "spec": {}})
            for r in reviews_fixture():
                c.add_data(r["object"])
            return sorted((x.resource["metadata"]["name"], x.msg)
                          for x in c.audit().results())
        finally:
            os.environ.pop("GATEKEEPER_TPU_NATIVE", None)
            nat._tried = False
            nat._flatten = None

    with_native = run(disable=False)
    without = run(disable=True)
    assert with_native == without and len(with_native) >= 3


def test_extract_batch_smoke_large():
    """Randomized wider batch: native path equals Python on every array."""
    import random

    rng = random.Random(5)
    objs = []
    for i in range(200):
        containers = [{"name": f"c{j}",
                       "image": rng.choice(["a/x", "b/y", f"u/{i}-{j}"]),
                       "ports": [{"hostPort": rng.randrange(2000)}
                                 for _ in range(rng.randrange(3))]}
                      for j in range(rng.randrange(4))]
        objs.append({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "namespace": "d",
                                  "labels": {f"k{rng.randrange(6)}":
                                             rng.choice(["yes", "no", "7"])
                                             for _ in range(3)}},
                     "spec": {"containers": containers,
                              "volumes": [{"name": "v"}] *
                              rng.randrange(6)}})
    reviews = [{"kind": {"group": "", "version": "v1", "kind": "Pod"},
                "name": o["metadata"]["name"], "object": o} for o in objs]
    prog = program()
    outs = []
    for native in (False, True):
        table = StringTable()
        ex = Extractor(prog, table, native=native)
        sizes = ex.axis_sizes(reviews)
        from gatekeeper_tpu.ir.features import _bucket

        buckets = {a: _bucket(s) for a, s in sizes.items()}
        outs.append((ex.extract(reviews, 256, buckets), table._strs))
    (f_py, strs_py), (f_c, strs_c) = outs
    assert strs_py == strs_c
    for slot in f_py:
        for name in f_py[slot]:
            a, b = f_py[slot][name], f_c[slot][name]
            if a.dtype == np.float32:
                assert ((a == b) | (np.isnan(a) & np.isnan(b))).all()
            else:
                assert (a == b).all()
