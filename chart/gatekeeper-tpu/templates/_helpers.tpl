{{/*
Name helpers, mirroring chart/gatekeeper-operator/templates/_helpers.tpl.
*/}}
{{- define "gatekeeper-tpu.name" -}}
{{- default .Chart.Name .Values.nameOverride | trunc 63 | trimSuffix "-" -}}
{{- end -}}
